"""The compiled-model contract: what the TPU wavefront engine needs.

The reference's hot loop calls dynamically-dispatched user callbacks per
state (``Model::actions`` / ``next_state`` / property closures,
src/checker/bfs.rs:230-335).  Under XLA everything is traced once and
compiled, so a TPU-checkable model provides the same three ingredients in
static-shape form:

- a bit-packed state encoding: each state is a vector of ``state_width``
  uint32 words, with ``encode``/``decode`` forming a bijection to the host
  model's states.  Bounded containers (message sets, queues) become
  fixed-width bitmaps/lanes — semantically fine because ``within_boundary``
  already bounds these spaces in the reference models.
- a ``step`` function: ``uint32[W] -> (uint32[A, W], bool[A])`` producing
  all ``max_actions`` candidate successors with a validity mask (the
  reference's data-dependent action list becomes a static arity with masked
  lanes; wasted lanes are the price of vmap).  The engine vmaps this over
  the frontier.
- ``property_conds``: ``uint32[W] -> bool[P]`` evaluating every property
  condition as a fused predicate, in the same order as
  ``model.properties()``.

A compiled model never replaces the host model — the host ``Model`` stays
the oracle for path reconstruction (decoded packed states are re-executed
host-side to recover action traces) and for golden-count differential tests.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..core.model import Model


class CompiledModel:
    """Device form of a :class:`Model`.  Subclass per model family.

    Attributes
    ----------
    model: the host oracle model.
    state_width: W, uint32 words per packed state (static).
    max_actions: A, static action arity of :meth:`step`.
    """

    model: Model
    state_width: int
    max_actions: int

    # When True, :meth:`step` returns a third value — a boolean *scalar*
    # (one flag per input state; fold per-action flags with ``jnp.any``
    # inside ``step``) marking that some successor exceeded the packed
    # encoding's capacity assumptions (e.g. more in-flight messages than
    # the layout holds).  The engines surface the flag as a hard error
    # instead of silently corrupting states, mirroring the loud refusal of
    # the host-side ``encode``.
    step_flags: bool = False

    # How many leading words of the packed row participate in state
    # identity.  The engines fingerprint ``row[:fp_words]`` (None = the
    # whole row), so trailing words carry per-state data that the host
    # model excludes from its hash — e.g. raft's delivered_messages/buffer
    # (examples/raft.rs:39-56 excludes them from the manual Hash impl).
    # States equal on the fingerprinted prefix dedup to the first-inserted
    # representative, exactly the host's first-writer-wins join.
    fp_words: Optional[int] = None

    # --- host side -----------------------------------------------------------

    def init_packed(self) -> np.ndarray:
        """Packed init states, shape [num_init, W] uint32."""
        states = [s for s in self.model.init_states() if self.model.within_boundary(s)]
        return np.stack([self.encode(s) for s in states]).astype(np.uint32)

    def encode(self, state: Any) -> np.ndarray:
        """Host state -> uint32[W].  Must be injective."""
        raise NotImplementedError

    def decode(self, words: Sequence[int]) -> Any:
        """uint32[W] -> host state; inverse of :meth:`encode`."""
        raise NotImplementedError

    # --- device side (jnp, traced) ------------------------------------------

    def step(self, state):
        """uint32[W] -> (uint32[A, W] successors, bool[A] valid).

        Invalid lanes may contain arbitrary words; the engine masks them.
        A successor lane is valid iff the corresponding host action is
        enabled AND produces a state change (``next_state`` not None).
        With ``step_flags`` True, returns a third encoding-overflow flag.
        """
        raise NotImplementedError

    def property_conds(self, state):
        """uint32[W] -> bool[P], P == len(model.properties()), same order."""
        raise NotImplementedError

    def boundary(self, state) -> Optional[Any]:
        """uint32[W] -> bool scalar, the device ``within_boundary``; None
        (default) means the model is unbounded / bounded by encoding."""
        return None

    # --- symmetry canonicalization (parallel/canon.py) ------------------------

    def canon_spec(self):
        """Declarative symmetry spec (:class:`~.canon.CanonSpec`): which
        row-word spans form the symmetric record block and which fields
        hold record-index (Id) values to remap.  None (default) means the
        model has no device canonicalization — ``symmetry()`` on the TPU
        spawns then raises loudly instead of silently exploring the full
        space (core/checker.py)."""
        return None

    def canon_rows(self, state):
        """uint32[W] -> uint32[W]: the canonical form of one packed row —
        the device ``representative()``.  Default: the kernel built from
        :meth:`canon_spec`; override only for canonicalizations the
        declarative spec cannot express.  Must be idempotent
        (``canon(canon(r)) == canon(r)``) and must only ever apply a
        genuine symmetry of the model, or the reduction silently prunes
        reachable states (tests/test_tpu_symmetry.py pins both)."""
        from .canon import canonicalize

        spec = self.canon_spec()
        if spec is None:
            raise NotImplementedError(
                f"{type(self).__name__} declares no canon_spec(); define "
                "one (or override canon_rows) to use symmetry() with the "
                "TPU engines"
            )
        return canonicalize(spec, state)

    def cache_key(self) -> tuple:
        """Key under which compiled device programs are shared across
        checker instances.  Must uniquely determine device behavior: two
        compiled models with equal keys must trace identical programs.
        The default covers models whose ``repr`` captures their full
        configuration (e.g. frozen dataclasses); others get per-instance
        keys (correct, just no sharing).  In-process only — the
        PERSISTENT spec identity lives in :mod:`..incr.spec_hash`, which
        deliberately never uses ``hash()`` or ``id()``-flavored reprs."""
        return (
            type(self).__qualname__,
            self.state_width,
            self.max_actions,
            repr(self.model),
        )

    # --- persistent spec identity (incr/spec_hash.py) -------------------------

    def spec_constants(self) -> Optional[dict]:
        """The model's CONSTANTS as a flat name -> repr dict — the
        "constants" component of the persistent spec hash
        (incr/spec_hash.py): the data the transition function closes
        over, separated from its CODE so the incremental store can
        classify "same model, one constant changed" without re-running
        anything.  The default reads dataclass fields (deterministic
        and ``PYTHONHASHSEED``-independent for the int/str/bool fields
        real models use); non-dataclass models return None — "no stable
        constants declaration" — and the store then refuses every reuse
        path LOUDLY rather than risk two differently-parameterized
        models hashing alike (docs/INCREMENTAL.md)."""
        import dataclasses

        if dataclasses.is_dataclass(self.model):
            return {
                f.name: repr(getattr(self.model, f.name))
                for f in dataclasses.fields(self.model)
            }
        return None

    # --- gang batching (fleet/gang.py) ----------------------------------------

    def gang_key(self) -> Optional[tuple]:
        """Family key under which compiled models may be GANG-BATCHED:
        K queued jobs whose compiled models share a gang_key run as one
        device dispatch with a leading *jobs* axis (fleet/gang.py) —
        the same trick as the batch over states.  Two models with equal
        keys must trace IDENTICAL device programs through the
        ``gang_*`` hooks below (their differing constants travel as
        traced array inputs, never baked into the trace), so the key
        must pin everything that shapes the program: codec widths,
        action arity, property count/order, and the hook code itself
        (the type).  None (default) = not gang-capable; the fleet
        scheduler then runs the job solo."""
        return None

    def gang_constants(self) -> np.ndarray:
        """The model's constants as one uint32 vector — the per-job
        lane of the gang dispatch's ``consts`` input.  Same length for
        every member of a gang_key family; each ``gang_*`` hook reads
        its constants from here instead of closing over Python ints."""
        raise NotImplementedError

    def gang_step(self, state, consts):
        """:meth:`step`, with constants as a traced input:
        ``(uint32[W], uint32[C]) -> (uint32[A, W], bool[A])``.  Must
        compute exactly what ``step`` computes when ``consts`` equals
        this model's :meth:`gang_constants` — the gang parity gate
        (per-job ``discovered_fingerprints()`` bit-equal to the solo
        run) holds only if the two never disagree."""
        raise NotImplementedError

    def gang_property_conds(self, state, consts):
        """:meth:`property_conds` with constants as a traced input."""
        raise NotImplementedError

    def gang_boundary(self, state, consts):
        """:meth:`boundary` with constants as a traced input; None
        (default) means unbounded, exactly like :meth:`boundary` —
        but the choice must MATCH ``boundary`` (a model bounded solo
        and unbounded in a gang explores different spaces)."""
        return None

    def spec_widens(self, old_constants: dict) -> bool:
        """Does THIS model's constant set describe a monotone
        reachable-set WIDENING of ``old_constants`` (a prior run of the
        same codec — e.g. a boundary bound raised, with the packed
        encoding and transition semantics of every old state
        unchanged)?  When True, the incremental store may seed a
        re-check's frontier and hash set from the prior reachable set
        and explore only the new region (docs/INCREMENTAL.md states the
        soundness argument).  Default False: widening is a per-model
        semantic claim and must never be inferred structurally."""
        return False

def compiled_model_for(model: Model) -> CompiledModel:
    """Resolve the compiled form of ``model``.

    Models opt in by defining ``compiled() -> CompiledModel``.
    """
    fn = getattr(model, "compiled", None)
    if fn is None:
        raise NotImplementedError(
            f"{type(model).__name__} has no compiled form; define "
            "compiled() returning a CompiledModel to use spawn_tpu()"
        )
    return fn()
