"""HBM-resident fingerprint hash set with batched insert-if-absent.

The reference dedups successors through a lock-sharded concurrent hash map —
one contended insert per generated state (src/checker/bfs.rs:301-315).  The
TPU equivalent is a device-resident open-addressing table keyed by the
64-bit packed-state fingerprint, stored as two uint32 planes (no u64 on TPU
vector lanes), with whole *waves* of candidate keys inserted at once.

Insertion is lock-free in rounds rather than per-element.  Each round every
unresolved lane gathers its probe slot and then:

- key already present  → resolved as duplicate;
- slot occupied by a different key → advance (linear probe);
- slot empty → contend: every contender scatters its lane id into a *claim
  plane* at the slot and gathers it back; the lane that reads its own id is
  the unique winner and scatters its key (so the two key planes can never
  interleave words from different lanes — no phantom keys).  Losers retry
  the SAME slot next round and now see the winner's key: equal keys resolve
  as duplicates, which is how batch-internal duplicates are handled with no
  pre-sorting; different keys advance.

Expected rounds ≈ 1/(1-load); the engine keeps load < 0.5.  Everything is
gather/scatter — no sorts — so it compiles small and maps onto the VPU.

Empty slots are (0, 0); fingerprints are guaranteed nonzero
(ops.device_fp, mirroring the reference's NonZeroU64 fingerprints,
src/lib.rs:341).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..ops.device_fp import _fmix32, _rotl

_U32 = jnp.uint32
NO_SLOT = jnp.uint32(0xFFFFFFFF)


class HashSet(NamedTuple):
    """Key planes of the open-addressing table; capacity is a power of two."""

    key_hi: jax.Array  # uint32[capacity]
    key_lo: jax.Array  # uint32[capacity]

    @property
    def capacity(self) -> int:
        return self.key_hi.shape[0]


def make_hashset(capacity: int) -> HashSet:
    if capacity & (capacity - 1):
        raise ValueError("capacity must be a power of two")
    return HashSet(
        key_hi=jnp.zeros((capacity,), _U32),
        key_lo=jnp.zeros((capacity,), _U32),
    )


def home_slot(hi, lo, capacity: int):
    """Initial probe slot for a key; a second independent mix of the 64-bit
    fingerprint so table position isn't correlated with the key planes."""
    return _fmix32(hi ^ _rotl(lo, 16) ^ _U32(0x7FEB352D)) & _U32(capacity - 1)


def insert_batch(
    table: HashSet, hi, lo, active
) -> Tuple[HashSet, jax.Array, jax.Array, jax.Array]:
    """Insert-if-absent a batch of keys (duplicates within the batch fine).

    ``hi``/``lo``: uint32[B] fingerprints; ``active``: bool[B] lanes to
    insert.

    Returns ``(table, slot[B] uint32, is_new[B] bool, ok bool)``: ``slot``
    is the key's table slot (for duplicates, the earlier winner's slot;
    NO_SLOT for inactive lanes); ``is_new`` marks exactly one lane per
    newly inserted key; ``ok`` is False if probing exhausted the table
    (overfull — the engine resizes/raises long before).
    """
    capacity = table.capacity
    mask = _U32(capacity - 1)
    b = hi.shape[0]
    lane = jnp.arange(b, dtype=_U32)
    slot0 = home_slot(hi, lo, capacity)
    max_rounds = 2 * capacity  # claim losers take two rounds per slot

    def cond(carry):
        _kh, _kl, _claim, _slot, done, _new, rounds = carry
        return (~jnp.all(done)) & (rounds < max_rounds)

    def body(carry):
        kh, kl, claim, slot, done, is_new, rounds = carry
        cur_hi = kh[slot]
        cur_lo = kl[slot]
        present = (cur_hi == hi) & (cur_lo == lo)
        empty = (cur_hi == 0) & (cur_lo == 0)
        found = ~done & present
        want = ~done & empty
        claim_idx = jnp.where(want, slot, _U32(capacity))
        claim = claim.at[claim_idx].set(lane, mode="drop")
        won = want & (claim[slot] == lane)
        key_idx = jnp.where(won, slot, _U32(capacity))
        kh = kh.at[key_idx].set(hi, mode="drop")
        kl = kl.at[key_idx].set(lo, mode="drop")
        done = done | found | won
        # Occupied by a different key -> linear probe; claim losers RETRY the
        # same slot so equal keys dedup against the winner next round.
        advance = ~done & ~empty & ~present
        slot = jnp.where(advance, (slot + _U32(1)) & mask, slot)
        return kh, kl, claim, slot, done, is_new | won, rounds + 1

    init = (
        table.key_hi,
        table.key_lo,
        jnp.zeros((capacity,), _U32),
        slot0,
        ~active,
        jnp.zeros((b,), jnp.bool_),
        jnp.zeros((), jnp.int32),
    )
    kh, kl, _claim, slot, done, is_new, _rounds = jax.lax.while_loop(
        cond, body, init
    )
    ok = jnp.all(done)
    slot = jnp.where(active, slot, NO_SLOT)
    return HashSet(kh, kl), slot, is_new, ok
