"""Shared wave-loop core for the single-chip and sharded wavefront engines.

Both engines run the same host-side loop around their fused device
program: call the program, read ONE stats vector back, fold counters and
discoveries, journal/metrics, maybe checkpoint, dispatch overflow flags
(grow in place or raise loudly), and decide termination.  Before this
module the two copies had already drifted (the sharded engine raised
where the single-chip one grew, and only the single-chip loop honored
the keep-partial-on-deadline rule); :class:`FusedWaveLoop` is the one
definition both engines drive, so `save_snapshot`/`resume_from`,
checkpoint cadence, cooperative cancel, and the in-place auto-grow
contract exist on both engines by construction rather than by copy.

This module also owns the **exchange bucket geometry** — the sharded
engine's per-destination all_to_all buckets (:func:`exchange_bucket_lanes`)
— as the single source of truth shared by the device program, the traced
byte model, and `accounting()`, so the reported payload shape can never
drift from what the device actually transmits (docs/SHARDED_SCALING.md).
"""

from __future__ import annotations

import logging
import time
from typing import NamedTuple, Optional

# Default per-destination bucket slack, in PERCENT of the even share
# u_sz/n.  The measured per-wave exchange occupancy (docs/SHARDED_SCALING.md:
# 0.28% of transmitted lanes at 8 shards, <1% even at the peak wave) says
# real candidates fill a few percent of the even share at most, so HALF the
# even share still carries >90% headroom — and the overflow-flag +
# retry-at-next-rung contract makes an undersized bucket a recompile, not
# a wrong answer.  Warm starts load the discovered rung from the knob
# cache (runtime/knob_cache.py) and skip the ramp entirely.
BUCKET_SLACK_DEFAULT = 50

# Doubling rung ladder: 50% of the even share, then 100%, 200%, ... until
# the bucket reaches the full u_sz buffer (the pre-bucketing shape, which
# cannot overflow: a shard never has more than u_sz candidates in total).
BUCKET_SLACK_MAX_RUNGS = 12


def exchange_bucket_lanes(u_sz: int, n: int, slack_pct: int) -> int:
    """Per-destination exchange bucket width in lanes: ``slack_pct`` percent
    of the even share ``u_sz/n``, rounded up to a 128-lane multiple (TPU
    lane tile), floored at 8 lanes, capped at ``u_sz`` (the full
    pre-bucketing buffer — always safe).  ``n == 1`` meshes elide the
    exchange entirely and keep the full buffer shape."""
    if n <= 1:
        return u_sz
    even = -(-u_sz // n)  # ceil
    want = -(-even * max(int(slack_pct), 1) // 100)
    want = max(8, ((want + 127) // 128) * 128 if want > 8 else want)
    return min(u_sz, want)


def next_bucket_slack(u_sz: int, n: int, slack_pct: int) -> Optional[int]:
    """The next rung of the bucket-slack ladder (doubling), or None when
    the bucket already spans the full ``u_sz`` buffer — at which point a
    bucket overflow is impossible by construction."""
    if exchange_bucket_lanes(u_sz, n, slack_pct) >= u_sz:
        return None
    grown = slack_pct * 2
    for _ in range(BUCKET_SLACK_MAX_RUNGS):
        if exchange_bucket_lanes(u_sz, n, grown) > exchange_bucket_lanes(
            u_sz, n, slack_pct
        ):
            return grown
        grown *= 2
    return None


# --- sort-geometry rung ladder (the dedup-sort analogue of the bucket
# ladder above; ROADMAP #1) ---------------------------------------------------
#
# The dedup sort pre-insert runs 3 co-sorted planes over the compaction
# buffer every wave; the worst-case buffer U = max(min(B, 16K),
# B/dedup_factor) is sized for a wave where EVERY candidate lane is valid,
# while the measured valid density (LoopVitals) is a few percent of it.
# ``sort_lanes`` is a power-of-two rung the engines compact into INSTEAD
# of U — the sort, probe rounds, and every U-sized gather downstream then
# touch rung lanes, not worst-case lanes.  The contract is exactly the
# bucket-slack ladder's: a wave whose valid candidates exceed the rung
# raises the non-committing flag-4 overflow, the host climbs one rung
# (×2, capped at the full U buffer — which reproduces the pre-ladder
# criterion exactly, so the top rung can never be wrong), and the
# discovered rung persists in the knob cache / tuned_kwargs so warm runs
# start past the ramp.  Downshifts are density-driven between committed
# quanta (:func:`downshift_sort_lanes`), with at-least-halving hysteresis
# so the compiled rung set stays small (the recompile-storm detector
# watches a thrashing ladder).
SORT_RUNG_MIN = 256

# Sizing headroom over the measured per-wave valid peak: quantum-averaged
# densities under-read the true in-wave peak, and an undersized rung costs
# a retry (never a wrong answer), so 4× balances "rarely retries" against
# "stops sorting dead lanes" (the report advisor's constant).
SORT_RUNG_HEADROOM = 4.0

# Committed density observations required before a downshift.  BFS
# density RAMPS over the first levels (tiny init frontier), so early
# peaks badly under-read steady state — acting on two waves of evidence
# measured as a downshift-then-climb-back thrash on 2pc(4); eight quanta
# of peak-tracking ride out the ramp (a fused quantum is up to 256
# waves, so production runs reach the window almost immediately).
SORT_TUNE_MIN_QUANTA = 8

# --- step-geometry rung ladder (the frontier-sized step; ROADMAP #1) ---------
#
# The OTHER buffer-proportional full-width pass: the expansion kernel and
# valid-lane compaction scan B = max_frontier × max_actions candidate
# lanes every wave, while the live frontier level is often a fraction of
# the chunk (56% of wave time on the post-PR-12 low-density gauge).
# ``step_lanes`` is a power-of-two rung on the per-wave CHUNK width (in
# frontier lanes): the chunk slice, the candidate batch (rung ×
# max_actions lanes), the valid compaction, and the dedup buffers all
# span the rung instead of the worst case.  A wave whose remaining level
# exceeds the rung raises the non-committing flag 128 and the host
# climbs one rung (×2, capped at max_frontier — where the flag cannot
# fire and behavior is exactly pre-ladder); the frontier-size tuner
# downshifts between committed quanta through the shared helpers below.
# The discovered rung rides the knob cache / tuned_kwargs like the sort
# rung.  Processing a level wider than max_frontier still chunks through
# multiple waves, exactly as before — the ladder only removes the dead
# lanes below the cap.
STEP_RUNG_MIN = 256
STEP_RUNG_HEADROOM = 4.0
STEP_TUNE_MIN_QUANTA = 8


def _pow2_ceil(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


def clamp_rung(requested: int, min_rung: int) -> int:
    """Normalize a requested rung onto a ladder: next power of two,
    floored at ``min_rung``.  The full-buffer cap is applied live by the
    engines (``min(rung, full)``) because auto-grow can move the full
    width mid-run.  Shared by both ladders so they cannot drift."""
    return max(min_rung, _pow2_ceil(max(1, int(requested))))


def clamp_sort_lanes(requested: int) -> int:
    return clamp_rung(requested, SORT_RUNG_MIN)


def clamp_step_lanes(requested: int) -> int:
    return clamp_rung(requested, STEP_RUNG_MIN)


def next_rung(cur: int, full: int, min_rung: int) -> Optional[int]:
    """The next rung up (doubling, capped at ``full``), or None when the
    rung already spans the full buffer — at which point the overflow
    criterion is the pre-ladder condition and the remaining lever is the
    ladder-specific relax/raise path.  Shared by both ladders."""
    if cur >= full:
        return None
    return min(max(min_rung, cur * 2), full)


def next_sort_lanes(cur: int, u_sz: int) -> Optional[int]:
    """The next sort rung up; None once the sort spans the full ``u_sz``
    buffer (the rung overflow criterion then IS the pre-ladder dedup
    criterion and the remaining growth lever is
    ``relax_dedup_geometry``)."""
    return next_rung(cur, u_sz, SORT_RUNG_MIN)


def next_step_lanes(cur: int, full: int) -> Optional[int]:
    """The next step rung up; None once the chunk spans the full
    ``max_frontier`` (where the clamp flag cannot fire by construction)."""
    return next_rung(cur, full, STEP_RUNG_MIN)


def downshift_rung(
    cur: int, full: int, floor: int, peak: float,
    min_rung: int, headroom: float,
) -> Optional[int]:
    """The ONE downshift decision both ladders share, parameterized by
    (min, headroom) with at-least-halving hysteresis: the rung that
    holds the measured peak at ``headroom``× slack, or None when no
    at-least-halving move exists.  ``floor`` is the overflow-proven
    minimum (a rung this run already climbed past must never be
    revisited — the ladder-thrash mode the watch verb badges)."""
    want = max(
        min_rung,
        int(floor),
        _pow2_ceil(max(1, int(peak * headroom) + 1)),
    )
    want = min(want, full)
    if want * 2 <= cur:
        return want
    return None


def downshift_sort_lanes(
    cur: int, u_sz: int, floor: int, peak_valid: float
) -> Optional[int]:
    """Density-driven sort-rung downshift (see :func:`downshift_rung`):
    the rung holding the measured per-wave valid peak at
    ``SORT_RUNG_HEADROOM``× headroom."""
    return downshift_rung(
        cur, u_sz, floor, peak_valid, SORT_RUNG_MIN, SORT_RUNG_HEADROOM
    )


def downshift_step_lanes(
    cur: int, full: int, floor: int, peak_frontier: float
) -> Optional[int]:
    """Frontier-size-driven step-rung downshift (see
    :func:`downshift_rung`): the chunk rung holding the measured live
    frontier peak at ``STEP_RUNG_HEADROOM``× headroom."""
    return downshift_rung(
        cur, full, floor, peak_frontier, STEP_RUNG_MIN, STEP_RUNG_HEADROOM
    )


def climb_sort_rung(eng, full: int) -> Optional[str]:
    """The flag-4 rung-climb half of the growth rule, shared by both
    engines (the relax_dedup_geometry pattern — one definition so the
    retry semantics cannot drift): climb one rung toward ``full``,
    record the overflow-proven floor and peak evidence, and return the
    grow note.  None when the rung already spans the full buffer — the
    caller falls back to :func:`relax_dedup_geometry`."""
    cur = eng._sort_width()
    nxt = next_sort_lanes(cur, full)
    if nxt is None:
        return None
    eng._sort_lanes = nxt
    eng._sort_rung_floor = nxt
    # The overflow proved this wave's valid count exceeds the old rung.
    eng._sort_peak_valid = max(eng._sort_peak_valid, cur)
    return f"sort_lanes={nxt}"


def climb_step_rung(eng, full: int) -> Optional[str]:
    """The flag-128 rung climb (the step ladder's analog of
    :func:`climb_sort_rung`, shared by all three engines): climb one
    chunk rung toward ``full`` (= ``max_frontier`` / ``chunk_size``),
    record the overflow-proven floor and peak evidence, and return the
    grow note.  None when the chunk already spans the full width — which
    cannot be reached via flag 128 (the clamp flag is compiled out at
    the top rung), so None here means a logic error surfacing loudly."""
    cur = eng._step_width()
    nxt = next_step_lanes(cur, full)
    if nxt is None:
        return None
    eng._step_lanes = nxt
    eng._step_rung_floor = nxt
    # The clamp proved the live frontier exceeds the old rung.
    eng._step_peak_frontier = max(eng._step_peak_frontier, cur)
    return f"step_lanes={nxt}"


def fall_back_to_sort(eng) -> str:
    """The sortless → sort-rung fallback (the engines' flag dispatch
    under ``sortless``): flip the dedup path to the sorted fallback rung
    — the already-proven PR 12 ladder — re-journal the geometry event so
    journal readers (`watch`'s ``dedup=`` field and fallback-thrash
    badge) track the flip, and return the grow note.  Non-committing by
    the same contract as every other ladder move: the flagged wave never
    committed, so the re-run at the sorted program is exact.  The knob
    cache persists the flipped mode (``tuned_kwargs()['sortless']``), so
    the fallback is a per-workload selection, paid once."""
    eng._sortless = False
    if eng._journal:
        eng._journal.append("geometry", **eng._wl_geometry())
    return "sortless=0"


def reset_sort_rung_to_full(eng, old_full: int) -> None:
    """The relax-path tail: a FULL-buffer flag-4 overflow relaxed
    dedup_factor, so the rung resets to the new (larger) full width and
    the evidence records that one wave held ≥ ``old_full`` valid lanes
    (the density tuner must not shrink the new buffer back).  The
    geometry event is re-journaled so journal readers — the `watch`
    verb's ``sort_rung`` in particular — track the reset; the grow note
    alone carries no ``sort_lanes=`` and would leave them stale."""
    eng._sort_lanes = None
    eng._sort_peak_valid = max(eng._sort_peak_valid, old_full)
    if eng._journal:
        eng._journal.append("geometry", **eng._wl_geometry())


def _maybe_retune(eng, measured, ns: dict) -> bool:
    """The ONE measured-evidence → rung-downshift tuner both ladders
    share (parameterized by the attribute namespace ``ns`` — see
    ``_SORT_NS``/``_STEP_NS`` below — so the two tuners cannot drift):
    folds the quantum's measurement into the engine's running peak and
    applies a downshift once enough committed quanta accumulated.
    Returns True exactly when the rung changed — traced loops use it to
    refresh their phase programs."""
    apply = getattr(eng, ns["apply"], None)
    if apply is None or measured is None:
        return False
    if not getattr(eng, ns["tune"], False):
        # An EXPLICIT rung (warm start from the knob cache, or a pinned
        # measurement leg) is the caller's rung: the tuner must not
        # fight it.  The overflow ladder stays armed regardless — an
        # explicit rung that proves too small still climbs.
        return False
    full = getattr(eng, ns["full"])()
    cur = getattr(eng, ns["width"])()
    setattr(eng, ns["quanta"], getattr(eng, ns["quanta"]) + 1)
    peak_obs = measured * full if ns["scale_by_full"] else measured
    setattr(
        eng, ns["peak"], max(getattr(eng, ns["peak"]), peak_obs)
    )
    if getattr(eng, ns["quanta"]) < ns["min_quanta"]:
        return False
    want = downshift_rung(
        cur, full, getattr(eng, ns["floor"]), getattr(eng, ns["peak"]),
        ns["min_rung"], ns["headroom"],
    )
    if want is None:
        return False
    apply(want)
    return True


# The sort ladder's evidence is the measured valid DENSITY (a fraction
# of the full buffer — scaled back to lanes here); the step ladder's is
# the live frontier backlog, already in lanes.
_SORT_NS = dict(
    apply="_wl_apply_sort_rung", tune="_sort_tune",
    full="_wl_full_sort_lanes", width="_sort_width",
    quanta="_sort_quanta", peak="_sort_peak_valid",
    floor="_sort_rung_floor", min_rung=SORT_RUNG_MIN,
    headroom=SORT_RUNG_HEADROOM, min_quanta=SORT_TUNE_MIN_QUANTA,
    scale_by_full=True,
)
_STEP_NS = dict(
    apply="_wl_apply_step_rung", tune="_step_tune",
    full="_wl_full_step_lanes", width="_step_width",
    quanta="_step_quanta", peak="_step_peak_frontier",
    floor="_step_rung_floor", min_rung=STEP_RUNG_MIN,
    headroom=STEP_RUNG_HEADROOM, min_quanta=STEP_TUNE_MIN_QUANTA,
    scale_by_full=False,
)


def maybe_retune_sort(eng, density) -> bool:
    """Shared density→sort-rung downshift, called by every host loop
    after a committed quantum (fused and traced alike; engines without
    the ``_wl_apply_sort_rung`` hook are untouched)."""
    return _maybe_retune(eng, density, _SORT_NS)


def maybe_retune_step(eng, remaining) -> bool:
    """Frontier→step-rung downshift, same cadence and hysteresis as the
    sort tuner by construction (one shared helper): the evidence is the
    committed quantum's remaining frontier backlog (an underestimate of
    intra-quantum peaks, which the headroom absorbs — and an undersized
    rung is the non-committing flag 128, never a wrong answer)."""
    return _maybe_retune(eng, remaining, _STEP_NS)


def relax_dedup_geometry(chunk, dedup_factor, lanes_of, lane_cap,
                         chunk_label: str, chunk_floor: int = 2048):
    """The shared dedup-overflow growth rule: straight to the always-safe
    ``dedup_factor=1`` (intermediate stops measured as new worker-crash
    geometries, wavefront.py's `_grow`), halving the chunk while
    ``lanes_of(chunk, 1)`` exceeds the device-safe band.  Returns
    ``(dedup_factor, chunk, note)`` or None when even the floor chunk
    cannot fit the band (max_actions > 256)."""
    if dedup_factor <= 1:
        return None
    notes = ["dedup_factor=1"]
    c = chunk
    while c > chunk_floor and lanes_of(c, 1) > lane_cap:
        c //= 2
        notes.append(f"{chunk_label}={c}")
    if lanes_of(c, 1) > lane_cap:
        return None
    return 1, c, "; ".join(notes)


class CheckpointCadence:
    """Mid-run checkpoint pacing shared by every host loop: due every
    ``every_waves`` waves (counted in whatever quantum the loop reports)
    or ``every_sec`` seconds, whichever the engine was configured with."""

    def __init__(self, every_waves: Optional[int], every_sec: Optional[float]):
        self.every_waves = every_waves
        self.every_sec = every_sec
        self._waves = 0
        self._last = time.monotonic()

    def due(self, waves_increment: int) -> bool:
        self._waves += waves_increment
        if self.every_waves is not None and self._waves >= self.every_waves:
            return True
        return (
            self.every_sec is not None
            and time.monotonic() - self._last >= self.every_sec
        )

    def mark(self) -> None:
        self._waves = 0
        self._last = time.monotonic()


class LoopVitals:
    """Always-on fused-loop vitals (docs/OBSERVABILITY.md "Always-on
    vitals"): cheap per-quantum counters and histograms recorded at the
    host-side call boundary every engine loop already crosses — never an
    extra device sync, so the trace=False device program stays
    byte-for-byte pinned.  One instance per engine run; writes land in
    the engine's :class:`~stateright_tpu.obs.metrics.MetricsRegistry`:

    - ``wave_latency_sec`` histogram — per-wave wall latency (a fused
      quantum of ``waves_per_call`` waves records its mean latency with
      weight waves_per_call; traced loops record each wave exactly);
    - ``waves_per_grow`` histogram — committed waves between
      overflow-triggered recoveries (how long a geometry survived
      before overflowing);
    - ``uniq_per_sec_ema`` / ``waves_per_sec_ema`` gauges — exponential
      moving averages over committed quanta (alpha 0.3: a few quanta of
      memory, mid-run readable from ``/.metrics``);
    - ``host_sec_total`` counter — host-side loop time: the fused loop
      accounts the between-calls gap (journal/metrics/checkpoint/grow
      dispatch); the traced loops report their measured ``readback``
      phase via :meth:`record_host` instead.  Either way, the
      time-in-host complement of the device time;
    - ``overflow_retries`` counter — every overflow-flagged wave that
      was recovered and re-run.  The separate ``grows`` counter
      (:func:`log_grow`) counts only ACTUAL geometry changes: a
      recovery that re-runs without growing (the tiered engine's
      spill-instead-of-grow) moves ``overflow_retries`` but not
      ``grows``;
    - ``valid_density_ema`` gauge + ``valid_density`` histogram — the
      measured per-wave VALID-candidate count as a fraction of the
      worst-case compaction/dedup ``U`` buffer (``cand_lanes``).  The
      numerator is the quantum's ``state_count`` delta divided by its
      wave count: ``state_count`` advances by exactly the
      boundary-passing valid successors each committed wave
      (wave_common.wave_eval's ``generated``), so the density needs NO
      extra readback — the fused program stays byte-for-byte pinned.
      Bounded at 1.0 by construction: the flag-4 overflow criterion
      fires on the SAME valid-lane count (hashset.compact_valid — "a
      stricter criterion than distinct keys"), so a committed wave's
      numerator can never exceed its ``U`` buffer.  This is the number
      the dedup-geometry ladder (ROADMAP #1) sizes against, and what
      the report advisor (obs/report.py) reads back out of the journal;
    - ``table_load_factor`` histogram (``load_factor``) — the hot-table
      load trajectory, one observation per committed quantum (the gauge
      form already rides ``table_occupancy``).
    """

    EMA_ALPHA = 0.3

    def __init__(self, registry, initial_unique: Optional[int] = None,
                 initial_states: Optional[int] = None):
        from ..obs.metrics import (
            COUNT_BUCKETS, FRACTION_BUCKETS, LATENCY_BUCKETS,
        )

        self._reg = registry
        self._latency_buckets = LATENCY_BUCKETS
        self._count_buckets = COUNT_BUCKETS
        self._fraction_buckets = FRACTION_BUCKETS
        self._uniq_ema: Optional[float] = None
        self._wave_ema: Optional[float] = None
        self._density_ema: Optional[float] = None
        self.last_density: Optional[float] = None
        # Baseline for the first quantum's uniq/s delta: the unique
        # count already committed before the loop starts (init seeding,
        # or a resumed snapshot's count — which must not read as "found
        # this call").  None = unknown; the first quantum then only
        # primes the baseline.  ``initial_states`` is the same baseline
        # for the density's generated-successors delta.
        self._last_unique = initial_unique
        self._last_states = initial_states
        self._waves_since_grow = 0
        self._host_mark: Optional[float] = None
        self._reg.inc("host_sec_total", 0.0)  # key exists from wave 0

    def call_started(self, now: float) -> None:
        """Account the host-side gap since the previous call ended
        (journal/metrics/checkpoint/grow work) as host time; the first
        call has no gap yet."""
        if self._host_mark is not None:
            self._reg.inc(
                "host_sec_total", max(0.0, now - self._host_mark)
            )

    def call_ended(self, now: float) -> None:
        self._host_mark = now

    def record_host(self, sec: float) -> None:
        """Directly account host-side seconds — the traced loops' path:
        their per-wave timers already isolate the host ``readback``
        phase inside the wave, so they report it here instead of the
        fused loop's between-calls gap."""
        self._reg.inc("host_sec_total", max(0.0, sec))

    def record_quantum(
        self, call_sec: float, waves: int, unique: int, committed: bool,
        states: Optional[int] = None, cand_lanes: Optional[int] = None,
        occupancy: Optional[float] = None,
    ) -> None:
        """Fold one device-call quantum into the vitals.  Aborted
        (flagged) quanta count latency but not rates: their unique delta
        is zero by construction and would drag the EMA to the floor.
        ``states``/``cand_lanes`` feed the density telemetry (see the
        class docstring), ``occupancy`` the load-factor trajectory."""
        waves = max(1, int(waves))
        self._reg.observe(
            "wave_latency_sec", call_sec / waves, count=waves,
            boundaries=self._latency_buckets,
        )
        self.last_density = None  # stale density must not journal on abort
        if not committed:
            return
        if occupancy is not None:
            self._reg.observe(
                "load_factor", occupancy,
                boundaries=self._fraction_buckets,
            )
        if states is not None and cand_lanes:
            if self._last_states is not None:
                density = (
                    max(0, states - self._last_states) / waves / cand_lanes
                )
                self.last_density = density
                self._density_ema = (
                    density if self._density_ema is None
                    else self._density_ema
                    + self.EMA_ALPHA * (density - self._density_ema)
                )
                self._reg.observe(
                    "valid_density", density, count=waves,
                    boundaries=self._fraction_buckets,
                )
                self._reg.update(
                    valid_density_ema=round(self._density_ema, 6),
                )
            self._last_states = states
        self._waves_since_grow += waves
        if call_sec > 0:
            wave_rate = waves / call_sec
            if self._last_unique is not None:
                uniq_rate = max(0, unique - self._last_unique) / call_sec
                self._uniq_ema = (
                    uniq_rate if self._uniq_ema is None
                    else self._uniq_ema
                    + self.EMA_ALPHA * (uniq_rate - self._uniq_ema)
                )
            self._wave_ema = (
                wave_rate if self._wave_ema is None
                else self._wave_ema
                + self.EMA_ALPHA * (wave_rate - self._wave_ema)
            )
            self._reg.update(
                waves_per_sec_ema=round(self._wave_ema, 4),
                **(
                    {"uniq_per_sec_ema": round(self._uniq_ema, 2)}
                    if self._uniq_ema is not None else {}
                ),
            )
        self._last_unique = unique

    def record_overflow_recovery(self) -> None:
        self._reg.inc("overflow_retries", 1)
        self._reg.observe(
            "waves_per_grow", max(1, self._waves_since_grow),
            boundaries=self._count_buckets,
        )
        self._waves_since_grow = 0


def journal_geometry(eng) -> None:
    """One ``geometry`` journal event at loop start (fused and traced
    alike): the engine's live geometry knobs plus the worst-case
    candidate-lane denominator the density telemetry divides by —
    everything the report advisor (obs/report.py) needs to turn measured
    densities back into recommended knobs.  Engines expose it via the
    optional ``_wl_geometry()`` hook."""
    geom = getattr(eng, "_wl_geometry", None)
    if eng._journal and geom is not None:
        eng._journal.append("geometry", **geom())


class WaveView(NamedTuple):
    """The host-visible summary of one fused program call, decoded from
    the engine's stats readback — everything the shared loop needs to
    journal, checkpoint, grow, and decide termination."""

    waves_this_call: int
    remaining: int  # frontier states left in the current level (global)
    depth: int
    flags: int
    unique: int
    states: int
    occupancy: float  # fingerprint-table load (sharded: fullest shard)
    discoveries: tuple  # ((prop_name, state_id), ...)
    extra: dict  # engine-specific journal enrichment (e.g. tail)


def loop_should_break(eng, view_remaining: int, depth: int, deadline) -> bool:
    """The shared termination tail (exact predicate order preserved from
    the pre-extraction loops): level drained / target depth / finish_when
    / target_state_count / wall deadline / cooperative stop.  Used by the
    fused driver below AND the engines' traced loops, so a traced run can
    never outlive (or under-live) a fused one."""
    opts = eng._options
    if view_remaining == 0:
        return True
    if (
        opts._target_max_depth is not None
        and depth + 1 >= opts._target_max_depth
    ):
        return True
    if opts._finish_when.matches(
        frozenset(eng._wl_discovered_names()), eng._properties
    ):
        return True
    if (
        opts._target_state_count is not None
        and opts._target_state_count <= eng._state_count
    ):
        return True
    if deadline is not None and time.monotonic() >= deadline:
        return True
    return eng._stop_requested.is_set()


class FusedWaveLoop:
    """The fused host loop, engine-agnostic.  The engine adapter (the
    checker itself) provides:

    - ``_wl_call(carry) -> carry`` — run the fused device program once;
    - ``_wl_view(carry) -> WaveView`` — the one stats readback, decoded;
    - ``_wl_set_discovery(name, id)`` — first-writer-wins discovery fold
      (called under the engine lock);
    - ``_wl_write_checkpoint(carry) -> dict`` — persist a mid-run
      snapshot, returning extra journal fields;
    - ``_wl_retryable_flags() -> int`` — flag bits the engine can grow
      in place (everything else raises);
    - ``_wl_grow(flags, carry) -> carry | None`` — in-place growth (may
      recompile programs / re-upload a fixed stats vector); None means
      the tripped knob cannot grow;
    - ``_wl_overflow_message(flags) -> str`` — the loud error text;
    - ``_wl_after_commit(carry, view) -> carry | None`` (OPTIONAL) — the
      spill/refill dispatch: called after every committed (flags == 0)
      wave, before the checkpoint cadence, so an engine with a tiered
      store (tiered/engine.py) can evict hot-tier partitions at its
      budget threshold and have the very next checkpoint persist the
      post-spill state.  Returning None keeps the carry;

    plus the shared checker attributes (`_options`, `_properties`,
    `_journal`, `_metrics`, `_lock`, `_stop_requested`, counters, and the
    checkpoint knobs).  An overflowing wave NEVER commits (both engines'
    device programs guarantee it), so growth re-runs the same chunk with
    no work lost and no host-visible side effects.
    """

    def __init__(self, eng):
        self.eng = eng

    def run(self, carry, deadline=None):
        from ..obs.timeline import SpanRecorder

        eng = self.eng
        cadence = CheckpointCadence(eng._ckpt_every_waves, eng._ckpt_every_sec)
        vitals = LoopVitals(
            eng._metrics,
            initial_unique=getattr(eng, "_unique_count", None),
            initial_states=getattr(eng, "_state_count", None),
        )
        # Host-tail span decomposition (obs/timeline.py): every named
        # section of the between-calls tail below runs under
        # ``spans.span(...)`` — two extra ``time.monotonic()`` calls per
        # section, no device traffic, so the trace=False fused program
        # stays byte-for-byte pinned.  The recorder flushes ONE
        # ``host_span`` journal event per quantum at the same boundary
        # ``vitals.call_started`` accounts into ``host_sec_total``.
        spans = SpanRecorder(eng._journal, eng._metrics)
        journal_geometry(eng)
        waves_total = 0
        while True:
            spans.quantum_start(time.monotonic())
            t_call = time.monotonic()
            vitals.call_started(t_call)
            with spans.step():
                carry = eng._wl_call(carry)
            with spans.span("readback"):
                view = eng._wl_view(carry)
            spans.collect(eng)
            t_done = time.monotonic()
            call_sec = t_done - t_call
            vitals.call_ended(t_done)
            spans.tail_start(t_done)
            cand_lanes = getattr(eng, "_wl_cand_lanes", None)
            vitals.record_quantum(
                call_sec, view.waves_this_call, view.unique,
                committed=view.flags == 0,
                states=view.states,
                cand_lanes=cand_lanes() if cand_lanes is not None else None,
                occupancy=view.occupancy,
            )
            waves_total += view.waves_this_call
            with eng._lock:
                eng._state_count = view.states
                eng._unique_count = view.unique
                eng._max_depth = view.depth + (1 if view.remaining else 0)
                for name, ident in view.discoveries:
                    eng._wl_set_discovery(name, ident)
            if eng._journal:
                with spans.span("journal"):
                    eng._journal.append(
                        "wave",
                        waves=waves_total,
                        remaining=view.remaining,
                        unique=view.unique,
                        states=view.states,
                        depth=view.depth,
                        flags=view.flags,
                        call_sec=round(call_sec, 4),
                        mono=round(t_call, 6),
                        occupancy=round(view.occupancy, 6),
                        **(
                            {"density": round(vitals.last_density, 6)}
                            if vitals.last_density is not None else {}
                        ),
                        **view.extra,
                    )
            eng._metrics.update(
                waves=waves_total,
                table_occupancy=round(view.occupancy, 6),
                last_call_sec=round(call_sec, 6),
            )
            eng._metrics.inc("device_call_sec_total", call_sec)
            eng._metrics.inc("device_calls", 1)
            if view.flags == 0:
                # Spill/refill rung (tiered engines only): evict AT the
                # committed boundary so the cadence block below persists
                # the post-spill tier state in the same pass.
                after_commit = getattr(eng, "_wl_after_commit", None)
                if after_commit is not None:
                    with spans.span("spill"):
                        carry = after_commit(carry, view) or carry
                # Density-driven sort-rung downshift and frontier-driven
                # step-rung downshift (engines with the hooks only): the
                # carry is rung-independent — only the per-wave scratch
                # buffers reshape — so a retune is a program swap
                # between calls, never a migration.
                with spans.span("retune"):
                    maybe_retune_sort(eng, vitals.last_density)
                    # remaining == 0 means the run is about to break — a
                    # downshift there would recompile for zero waves.
                    maybe_retune_step(eng, view.remaining or None)
            if (
                eng._checkpoint_path is not None
                and view.flags == 0
                and cadence.due(view.waves_this_call)
            ):
                with spans.span("checkpoint"):
                    t_ck = time.monotonic()
                    ck_extra = eng._wl_write_checkpoint(carry) or {}
                    cadence.mark()
                    if eng._journal:
                        eng._journal.append(
                            "checkpoint",
                            path=eng._checkpoint_path,
                            unique=view.unique,
                            depth=view.depth,
                            write_sec=round(time.monotonic() - t_ck, 4),
                            **ck_extra,
                        )
            if view.flags:
                fatal = view.flags & ~eng._wl_retryable_flags()
                if fatal:
                    raise RuntimeError(eng._wl_overflow_message(fatal))
                if eng._stop_requested.is_set() or (
                    deadline is not None and time.monotonic() >= deadline
                ):
                    # Growth costs a recompile + re-run; a run already
                    # past its budget (or asked to stop) keeps its
                    # partial result instead.  But the break lands on a
                    # FLAGGED wave, whose aborted insert may have
                    # scribbled keys into the fingerprint table: engines
                    # whose aborted waves mutate the table must erase
                    # them before the carry is persisted, or a resumed
                    # run would treat the wave's states as already
                    # visited and silently lose their subtrees (the
                    # sharded engine zeroes validity pre-insert, so it
                    # needs no hook).
                    cleanup = getattr(eng, "_wl_abort_cleanup", None)
                    if cleanup is not None:
                        carry = cleanup(carry) or carry
                    break
                with spans.span("grow"):
                    grown = eng._wl_grow(view.flags, carry)
                if grown is None:
                    raise RuntimeError(eng._wl_overflow_message(view.flags))
                vitals.record_overflow_recovery()
                carry = grown
                continue
            if loop_should_break(eng, view.remaining, view.depth, deadline):
                break
        # The final quantum's tail has no next call to account it into
        # ``host_sec_total`` via the between-calls gap — measure it here
        # (before the flush write) and fold it in directly, so the
        # journaled decomposition and the counter stay reconciled.
        vitals.record_host(spans.finish(time.monotonic()))
        return carry, waves_total


def finalize_run(eng, carry_dict: dict) -> None:
    """The shared run tail: stash the snapshot-ready carry, write the
    final completion checkpoint (a run directory always ends with a
    durable resumable snapshot), and journal ``engine_done``."""
    eng._carry_dev = carry_dict
    if eng._checkpoint_path is not None:
        eng._write_snapshot(eng._checkpoint_path, carry_dict)
        if eng._journal:
            eng._journal.append(
                "checkpoint",
                path=eng._checkpoint_path,
                unique=eng._unique_count,
                depth=eng._max_depth,
                final=True,
            )
    if eng._journal:
        eng._journal.append(
            "engine_done",
            unique=eng._unique_count,
            states=eng._state_count,
            depth=eng._max_depth,
        )


def fingerprints_of_rows(cm, rows_np, canon=None, sort=True):
    """Sorted uint64 fingerprints of a batch of packed state rows — the
    shared implementation behind both engines'
    ``discovered_fingerprints()``, so cross-engine discovery-set pins
    compare one definition: the device fingerprint of the row's leading
    ``fp_words``, through ``canon`` when symmetry is on — exactly what
    identifies a state everywhere else in the engines (dedup keys,
    shard routing, tiered cold keys).  Under symmetry the logged
    ORIGINAL row is whichever orbit member the traversal reached first
    — order-dependent by construction — so the identity (= canonical)
    fingerprint is the only traversal-invariant discovery-set pin."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..ops.device_fp import device_fp64

    fpw = cm.fp_words or cm.state_width
    rows = jnp.asarray(rows_np)
    if canon is not None:
        rows = jax.vmap(canon)(rows)
    hi, lo = device_fp64(rows[:, :fpw])
    fps = (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | np.asarray(
        lo
    ).astype(np.uint64)
    # sort=False keeps row order: resharding re-owners each logged row by
    # its fingerprint and needs fps[i] to stay aligned with rows_np[i].
    return np.sort(fps) if sort else fps


def log_grow(eng, flags: int, grown: str, unique: int, depth: int) -> None:
    """Shared grow-event surfacing: a warning log line, a journaled
    ``grow`` record, and the ``grows`` metric — identical on both
    engines so supervisors, scrapers, and tests read one schema.  Only
    ACTUAL geometry changes come through here; overflow recoveries that
    re-run without growing (the tiered engine's spill-instead-of-grow)
    count in ``overflow_retries`` alone (:class:`LoopVitals`)."""
    eng._metrics.inc("grows", 1)
    logging.getLogger(eng.__class__.__module__).warning(
        "auto-tune: overflow flags=%d; growing in place (%s) at "
        "unique=%d depth=%d",
        flags, grown, unique, depth,
    )
    if eng._journal:
        eng._journal.append(
            "grow", flags=flags, grown=grown, unique=unique, depth=depth
        )
