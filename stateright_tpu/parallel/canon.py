"""Device-side symmetry canonicalization: sort-of-record-blocks kernels.

The reference's symmetry reduction dedups on
``fingerprint(representative(state))`` while continuing the search with the
original state (src/checker/dfs.rs:309-334); ``RewritePlan.from_values_to_sort``
builds the permutation by stable-sorting per-actor values and ``reindex``
permutes indexed collections while rewriting nested ``Id`` values
(src/checker/rewrite_plan.rs:81-123; host port: core/symmetry.py).  This
module is the device analog: a packed state row's symmetric *record block*
(one fixed-width record per symmetric process) is stably sorted, the
resulting permutation is applied to every per-record field, and Id-valued
fields (fields holding a record index) are remapped through the permutation
— all in traced uint32 ops, so the engines can vmap it over whole waves and
fingerprint the canonical row while logging the original.

Canonicalization choice — FULL-record sort keys.  The reference's 2pc
representative sorts by the ``rm_state`` field alone and lets the stable
sort's original-index tie-break pick among equal keys
(examples/2pc.rs:203-223).  That tie-break makes the representative
traversal-order-dependent: two states in the same orbit can map to
*different* representatives, so the visited-representative count depends on
which orbit member a given schedule happens to expand (the reference's DFS
reports 665 on 2pc rm=5; the same recipe under BFS order reports 508).  A
parallel wavefront — chunked levels on one chip, shard-interleaved chunks
on a mesh — has no single canonical traversal to pin such a count to, so
the device spec sorts by the ENTIRE record: ties then only occur between
fully interchangeable records, the canonical form is a true orbit invariant
(2pc rm=5: 314 classes — the exact orbit count, and a strictly stronger cut
than the reference's 665), and every engine, chunk size, and mesh shape
reports the same number.  See docs/SYMMETRY.md.

Soundness does not depend on key choice: ``canonicalize`` only ever applies
a genuine record permutation (plus the consistent Id remap), so the output
is always a member of the input's orbit and equal canonical rows imply
symmetric states.  An under-keyed spec costs reduction strength and
traversal invariance, never correctness.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple


class CanonField(NamedTuple):
    """One per-record field of a symmetric record block.

    Record ``i``'s value lives at bits ``[shift + i*bit_stride,
    ... + width)`` of word ``word + i*word_stride``.  Bit-packed layouts
    (2pc: 2-bit RM states packed in one word) use ``word_stride=0,
    bit_stride=width``; word-aligned layouts (one or more whole words per
    record) use ``bit_stride=0, word_stride=k``.

    ``is_id`` marks a field whose VALUE is a record index (the device
    analog of the reference's ``Rewrite<Id>`` values): it is excluded from
    the sort key and remapped through the permutation; values ``>= n``
    (e.g. a none/sentinel encoding) pass through unchanged.
    """

    word: int
    shift: int
    width: int
    bit_stride: int
    word_stride: int
    is_id: bool


def field(
    word: int,
    shift: int,
    width: int,
    *,
    bit_stride: Optional[int] = None,
    word_stride: int = 0,
    is_id: bool = False,
) -> CanonField:
    """Build a :class:`CanonField`; ``bit_stride`` defaults to ``width``
    for bit-packed fields and to 0 when ``word_stride`` is given."""
    if bit_stride is None:
        bit_stride = 0 if word_stride else width
    return CanonField(word, shift, width, bit_stride, word_stride, is_id)


class CanonSpec(NamedTuple):
    """Declarative canonicalization spec a compiled model exposes via
    ``CompiledModel.canon_spec()``.

    ``n``: number of symmetric records (e.g. the RM count).
    ``fields``: the per-record fields; non-Id fields form the stable-sort
    key in declaration order — declare EVERY per-record field (see the
    module docstring: full-record keys make the canonical form an orbit
    invariant, which the wavefront engines' traversal-invariant counts
    rely on).
    ``id_fields``: global (non-record) locations holding a record index,
    remapped through the permutation; values ``>= n`` pass unchanged.

    An empty spec (``n <= 1`` and no fields) is the identity — valid, and
    useful for wiring tests on models with no symmetric structure.
    """

    n: int
    fields: Tuple[CanonField, ...] = ()
    id_fields: Tuple[CanonField, ...] = ()


def validate_spec(
    spec: CanonSpec, state_width: int, fp_words: Optional[int] = None
) -> None:
    """Loud spawn-time validation: a malformed spec must fail before any
    wave runs, not canonicalize garbage (an out-of-range read would merge
    unrelated states and silently prune reachable ones — the same failure
    mode core/symmetry.py's rewrite_value refuses with a TypeError).

    ``fp_words``: the model's identity prefix (``CompiledModel.fp_words``).
    Sort-KEY fields must lie inside it: a key read from a non-identity
    word would make the permutation — and through it the canonical
    fingerprint — depend on data the model excludes from state identity,
    so two rows plain dedup merges could canonicalize apart (silent count
    inflation).  Id fields are exempt (they never shape the sort)."""
    n = spec.n
    if n < 0:
        raise ValueError(f"canon_spec: n must be >= 0, got {n}")
    for f in spec.fields:
        if f.width <= 0 or f.width > 32:
            raise ValueError(f"canon_spec: field width out of range: {f}")
        last_word = f.word + max(n - 1, 0) * f.word_stride
        last_shift = f.shift + max(n - 1, 0) * f.bit_stride
        if f.word < 0 or last_word >= state_width:
            raise ValueError(
                f"canon_spec: field spans words outside the "
                f"{state_width}-word row: {f}"
            )
        if f.shift < 0 or last_shift + f.width > 32:
            raise ValueError(
                f"canon_spec: field bits exceed a 32-bit word "
                f"(n={n}): {f}"
            )
        if f.bit_stride and f.bit_stride < f.width:
            raise ValueError(
                f"canon_spec: records overlap (bit_stride < width): {f}"
            )
        if (
            fp_words is not None
            and fp_words < state_width
            and not f.is_id
            and last_word >= fp_words
        ):
            raise ValueError(
                f"canon_spec: sort-key field reads words beyond the "
                f"fp_words={fp_words} identity prefix; the permutation "
                f"would depend on non-identity data and split states "
                f"plain dedup merges: {f}"
            )
    for g in spec.id_fields:
        if g.width <= 0 or g.width > 32 or g.shift + g.width > 32:
            raise ValueError(f"canon_spec: id field bits out of range: {g}")
        if g.word < 0 or g.word >= state_width:
            raise ValueError(
                f"canon_spec: id field outside the {state_width}-word "
                f"row: {g}"
            )
        if (1 << g.width) < n:
            raise ValueError(
                f"canon_spec: id field too narrow to hold indices "
                f"0..{n - 1}: {g}"
            )


def _extract(row, f: CanonField, n: int):
    """Per-record values of one field: uint32[n] (trace-unrolled — n is a
    small static record count, not a data dimension)."""
    import jax.numpy as jnp

    u = jnp.uint32
    mask = u((1 << f.width) - 1)
    per = []
    for i in range(n):
        w = row[f.word + i * f.word_stride]
        per.append((w >> u(f.shift + i * f.bit_stride)) & mask)
    return jnp.stack(per)


def canonicalize(spec: CanonSpec, row):
    """uint32[W] -> uint32[W]: the canonical (record-sorted, Id-remapped)
    form of one packed state row.  Traced; engines vmap it over waves.

    The permutation is the stable sort of the records by their non-Id
    fields in declaration order — exactly ``RewritePlan.from_values_to_sort``
    with the whole record as the value — and is applied to every
    per-record field; Id fields ride to their record's new position AND
    have their value remapped old-index -> new-index.
    """
    import jax
    import jax.numpy as jnp

    u = jnp.uint32
    n = spec.n
    if n <= 1 or not spec.fields:
        return row

    iota = jnp.arange(n, dtype=u)
    vals = [_extract(row, f, n) for f in spec.fields]
    keys = [v for f, v in zip(spec.fields, vals) if not f.is_id]
    if keys:
        sorted_ops = jax.lax.sort([*keys, iota], num_keys=len(keys),
                                  is_stable=True)
        order = sorted_ops[-1]  # order[new_index] = old_index
    else:
        order = iota
    # mapping[old_index] = new_index: the RewritePlan's rewrite().
    mapping = jnp.zeros((n,), u).at[order].set(iota)

    def remap_ids(pv):
        # Values >= n are sentinels (e.g. "no holder"); pass unchanged.
        safe = jnp.minimum(pv, u(n - 1))
        return jnp.where(pv < u(n), mapping[safe], pv)

    out = row
    for f, v in zip(spec.fields, vals):
        pv = v[order]  # new position i gets old record order[i]'s value
        if f.is_id:
            pv = remap_ids(pv)
        if f.word_stride == 0:
            # Bit-packed: all n records share one word — clear the whole
            # span, OR the permuted values back in one update.
            clear = 0
            bits = jnp.zeros((), u)
            for i in range(n):
                sh = f.shift + i * f.bit_stride
                clear |= ((1 << f.width) - 1) << sh
                bits = bits | (pv[i] << u(sh))
            out = out.at[f.word].set(
                (out[f.word] & u(~clear & 0xFFFFFFFF)) | bits
            )
        else:
            mask = u(((1 << f.width) - 1) << f.shift)
            for i in range(n):
                wi = f.word + i * f.word_stride
                out = out.at[wi].set(
                    (out[wi] & ~mask) | (pv[i] << u(f.shift))
                )
    for g in spec.id_fields:
        mask = u((1 << g.width) - 1)
        val = (out[g.word] >> u(g.shift)) & mask
        nv = remap_ids(val)
        out = out.at[g.word].set(
            (out[g.word] & ~(mask << u(g.shift))) | (nv << u(g.shift))
        )
    return out


def make_canon(cm):
    """Resolve a compiled model's canonicalization: its overridden
    ``canon_rows`` if it defines one, else a kernel built from its
    declarative ``canon_spec()``, else None (the engines raise loudly on
    ``symmetry()`` + None — silent fallback to no reduction would report
    wrong-looking counts as if they were reduced)."""
    from .compiled import CompiledModel

    if type(cm).canon_rows is not CompiledModel.canon_rows:
        return cm.canon_rows
    spec = cm.canon_spec()
    if spec is None:
        return None
    validate_spec(spec, cm.state_width, fp_words=cm.fp_words)

    def canon(row, _spec=spec):
        return canonicalize(_spec, row)

    return canon


def canon_batch_host(cm, rows):
    """Host-side evaluation of the model's canon kernel over packed rows
    (numpy in, numpy out), pinned bit-identical to the device by running
    the SAME traced function on the CPU backend.  Used where the host
    needs canonical fingerprints without a device round trip — e.g. the
    sharded engine's init-state owner placement — and by the parity
    tests."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    canon = make_canon(cm)
    if canon is None:
        raise ValueError(
            f"{type(cm).__name__} declares no canonicalization "
            "(canon_spec()/canon_rows)"
        )
    try:
        dev = jax.devices("cpu")[0]
    except RuntimeError:
        # JAX_PLATFORMS masked the cpu backend out; the default device
        # still gives bit-identical integer results, just via one small
        # round trip.
        dev = jax.devices()[0]
    with jax.default_device(dev):
        return np.asarray(jax.vmap(canon)(jnp.asarray(rows)))
