"""Run-supervisor subsystem: crash-resilient checking with durable progress.

Long exhaustive checks on tunneled accelerators die in ways the engine
cannot recover from inside one process: the TPU worker hard-crashes on
long per-device calls, the tunnel drops, the driver kills the whole
process at a wall deadline.  This package makes any such run survivable
and observable, the way swarm verification (Holzmann et al.) and TLC's
checkpoint/restore made week-long exhaustive runs practical — restartable
workers plus durable progress state (see PAPERS.md):

- :mod:`journal` — an append-only JSON-lines telemetry stream (per-wave
  progress, checkpoint/crash/resume events) written as a run artifact and
  doubling as the supervisor's liveness signal;
- :mod:`supervisor` — runs a checker in an isolated child process,
  checkpoints via the engines' ``save_snapshot`` every N waves / T
  seconds, detects child death and hangs, and auto-resumes from the last
  checkpoint with an adaptive geometry backoff (straight to
  ``dedup_factor=1``, never stepwise);
- :mod:`child` — the child-process entry (``python -m
  stateright_tpu.runtime.child RUN_DIR``);
- :mod:`knob_cache` — persisted ``tuned_kwargs`` keyed by (workload,
  model, device, engine geometry), so bench rounds and suite children
  reload discovered engine knobs instead of re-paying the ~21-minute
  auto-tune discovery (VERDICT r5 weak #2);
- :mod:`chaos` — deterministic fault injection for the *actor* runtime
  (seeded drop/duplicate/reorder/delay/partition schedules over any
  transport) plus live linearizability auditing of the faulted run with
  the model checker's own consistency testers (docs/ACTORS.md).

The schema and policies are documented in docs/RUNTIME.md.
"""

from .chaos import (
    ChaosSpec,
    FaultyTransport,
    LiveAuditor,
    RecordingTransport,
    run_chaos_register_system,
)
from .journal import Journal, read_journal
from .knob_cache import drop_knobs, load_knobs, store_knobs
from .supervisor import (
    CheckSpec,
    RunSupervisor,
    SupervisorConfig,
    SupervisorError,
    TRANSIENT_MARKERS,
    relax_geometry,
    run_isolated,
)

__all__ = [
    "ChaosSpec",
    "FaultyTransport",
    "LiveAuditor",
    "RecordingTransport",
    "run_chaos_register_system",
    "Journal",
    "read_journal",
    "drop_knobs",
    "load_knobs",
    "store_knobs",
    "CheckSpec",
    "RunSupervisor",
    "SupervisorConfig",
    "SupervisorError",
    "TRANSIENT_MARKERS",
    "relax_geometry",
    "run_isolated",
]
