"""Child-process entry for supervised runs:
``python -m stateright_tpu.runtime.child RUN_DIR``.

Rebuilds the pickled :class:`~stateright_tpu.runtime.supervisor.CheckSpec`
in a fresh process, spawns the checker with the journal/checkpoint hooks
pointed into the run directory, resumes from the latest checkpoint when
one exists, and writes ``result.json`` on completion.  A Python-level
failure is written to ``error.txt`` and exits with rc=3 so the supervisor
can separate deterministic errors (no retry) from runtime kills (retry +
geometry backoff).

Fault injection (used by the crash-resilience tests, harmless otherwise):
``STATERIGHT_RUNTIME_FAULT_EXIT_AFTER_CHECKPOINT=<rc>`` makes a
NON-resumed child die with ``os._exit(rc)`` as soon as its first
checkpoint lands — a deterministic stand-in for the mid-run worker kill.
"""

from __future__ import annotations

import json
import os
import pathlib
import pickle
import sys
import time
import traceback

from .journal import Journal
from .supervisor import (
    CHECKPOINT_FILE,
    CHILD_CONFIG_FILE,
    CHILD_ERROR_RC,
    ERROR_FILE,
    JOURNAL_FILE,
    RELAX_FILE,
    RESULT_FILE,
    SPEC_FILE,
    load_json_or_default,
)

FAULT_ENV = "STATERIGHT_RUNTIME_FAULT_EXIT_AFTER_CHECKPOINT"




def run_child(run_dir: str) -> int:
    run_dir = os.path.abspath(run_dir)
    # Persistent XLA cache: restarted children recompile the same
    # programs; without this every resume pays full compile time.
    repo = pathlib.Path(run_dir)
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR", str(repo / ".jax_cache")
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

    journal = Journal(os.path.join(run_dir, JOURNAL_FILE))
    try:
        with open(os.path.join(run_dir, SPEC_FILE), "rb") as fh:
            spec = pickle.load(fh)
        cfg = load_json_or_default(
            os.path.join(run_dir, CHILD_CONFIG_FILE), {}
        )
        relax = load_json_or_default(os.path.join(run_dir, RELAX_FILE), {})

        checkpoint = os.path.join(run_dir, CHECKPOINT_FILE)
        engine_kwargs = dict(spec.engine_kwargs)
        engine_kwargs.update(relax)
        # Traced children never resume: the engines refuse trace=True +
        # resume_from (tracing is a diagnostic mode), and a restart that
        # passed both would die in __init__ on every attempt — burning
        # the supervisor's restarts in seconds.  A traced child restarts
        # from scratch instead; its journal still carries every
        # completed wave's trace records.
        resumed = (
            bool(cfg.get("resume", True))
            and os.path.exists(checkpoint)
            and not bool(engine_kwargs.get("trace"))
        )
        engine_kwargs.update(
            journal=journal,
            checkpoint_path=checkpoint,
            checkpoint_every_waves=cfg.get("checkpoint_every_waves"),
            checkpoint_every_sec=cfg.get("checkpoint_every_sec"),
        )
        if resumed:
            engine_kwargs["resume_from"] = checkpoint

        journal.append(
            "run_start", pid=os.getpid(), resumed=resumed,
            engine=spec.engine, engine_kwargs={
                k: v for k, v in engine_kwargs.items()
                if isinstance(v, (int, float, str, bool, type(None)))
            },
        )

        model = spec.build_model()
        builder = model.checker()
        if spec.target_state_count is not None:
            builder = builder.target_state_count(spec.target_state_count)
        if spec.target_max_depth is not None:
            builder = builder.target_max_depth(spec.target_max_depth)
        if spec.timeout is not None:
            builder = builder.timeout(spec.timeout)
        if spec.engine == "sharded":
            checker = builder.spawn_tpu_sharded(**engine_kwargs)
        elif spec.engine == "tiered":
            checker = builder.spawn_tpu_tiered(**engine_kwargs)
        elif spec.engine == "tiered-sharded":
            checker = builder.spawn_tpu_tiered_sharded(**engine_kwargs)
        else:
            checker = builder.spawn_tpu(**engine_kwargs)

        fault_rc = os.environ.get(FAULT_ENV)
        if fault_rc is not None and not resumed:
            # Die mid-run, deterministically, once durable progress
            # exists — the test stand-in for a TPU worker kill.  Only a
            # non-resumed child dies, so the restarted attempt completes.
            while not checker.is_done():
                if os.path.exists(checkpoint):
                    journal.append("fault_injected", rc=int(fault_rc))
                    os._exit(int(fault_rc))
                time.sleep(0.005)

        checker.join()
        discoveries = checker.discoveries()
        result = {
            "completed": True,
            "unique_state_count": checker.unique_state_count(),
            "state_count": checker.state_count(),
            "max_depth": checker.max_depth(),
            "discoveries": sorted(discoveries),
            "discovery_classifications": {
                name: checker.discovery_classification(name)
                for name in discoveries
            },
            # The observability snapshot rides the durable result, so a
            # supervised run's wave cadence / occupancy / trace summary
            # survive the child process (docs/OBSERVABILITY.md).
            "metrics": checker.metrics(),
        }
        tmp = os.path.join(run_dir, RESULT_FILE + ".tmp")
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(result, fh)
        os.replace(tmp, os.path.join(run_dir, RESULT_FILE))
        journal.append("run_end", **result)
        return 0
    except Exception:
        err = traceback.format_exc()
        with open(
            os.path.join(run_dir, ERROR_FILE), "w", encoding="utf-8"
        ) as fh:
            fh.write(err)
        journal.append("child_error", error=err[-2000:])
        sys.stderr.write(err)
        return CHILD_ERROR_RC
    finally:
        journal.close()


if __name__ == "__main__":
    if len(sys.argv) != 2:
        print("usage: python -m stateright_tpu.runtime.child RUN_DIR",
              file=sys.stderr)
        sys.exit(2)
    sys.exit(run_child(sys.argv[1]))
