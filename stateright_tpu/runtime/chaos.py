"""Deterministic fault injection and live correctness auditing for the
actor runtime.

The model checker's ``Network`` semantics (``actor/network.py``) enumerate
drop, duplication, and reordering — but the production runtime only ever
saw a well-behaved loopback.  This module closes that gap in the spirit of
swarm verification (Holzmann et al., PAPERS.md: faults are expected,
progress must be durable): inject the faults the model enumerates into the
*real* runtime, journal every injection, and audit the live history with
the same ``ConsistencyTester``s the checker uses.

Three layers, all stackable over any ``Transport``:

- :class:`FaultyTransport` — wraps a transport with seeded, per-link
  drop / duplicate / reorder / delay probabilities plus timed
  partition/heal windows (:class:`ChaosSpec`).  Drop/duplicate/reorder/
  delay decisions for the n-th datagram on a directed link are a pure
  function of ``(seed, src, dst, n)`` — independent of thread scheduling
  and wall time — so a fixed seed gives a bit-reproducible fault
  schedule.  (Partition drops are the one exception: their windows are
  measured in elapsed wall time, so they are journaled like everything
  else but excluded from the reproducibility guarantee.)  Every injected
  fault is appended to a ``runtime/journal.py`` JSONL journal.
- :class:`RecordingTransport` — taps the transport boundary, decoding
  datagrams and handing ``Envelope``s to callbacks on send and receive.
- :class:`LiveAuditor` — adapts recorded register-protocol traffic
  (``Put``/``Get`` invocations, ``PutOk``/``GetOk`` returns, with
  ordered-reliable-link wrappers unwrapped and retransmits deduplicated)
  into a live ``LinearizabilityTester`` / ``SequentialConsistencyTester``
  history, checked against the same ``SequentialSpec`` the model uses.

:func:`run_chaos_register_system` composes them: a hermetic loopback
cluster of ORL-wrapped register actors under chaos, audited live — the
``spawn --chaos ... --audit`` CLI flow and the CI chaos smoke.
"""

from __future__ import annotations

import json
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

from ..actor.ids import Id
from ..actor.transport import Endpoint, Transport
from .journal import Journal, as_journal

_MASK64 = (1 << 64) - 1

# The four per-datagram draws, in their fixed order (the schedule for
# datagram n must never shift with timing): drop, reorder, duplicate,
# delay.  The indices are shared with the device fate kernel
# (ensemble/fate.py), which evaluates the same counter positions.
FATE_DROP, FATE_REORDER, FATE_DUPLICATE, FATE_DELAY = range(4)
FATE_DRAWS = 4

_SPLITMIX_GAMMA = 0x9E3779B97F4A7C15


def fault_fate_u32(link_seed: int, n: int, k: int) -> int:
    """The fate word: a uniform uint32 deciding draw ``k`` (one of the
    four ``FATE_*`` positions) for the ``n``-th datagram on the link
    whose seed is ``link_seed`` (:func:`_link_rng_seed`).

    Counter-mode splitmix64 — the finalizer evaluated at counter
    ``4n + k + 1`` over the link seed, top 32 bits kept.  There is no
    sequential generator state, so the same function is implementable
    as uint32 limb arithmetic inside a vmapped device step
    (``ensemble/fate.py``) and matches this transport bit-for-bit:
    the load-bearing bridge that lets a device-found failing fault
    schedule replay exactly in the host transport."""
    z = (int(link_seed) + (4 * int(n) + int(k) + 1) * _SPLITMIX_GAMMA) & _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    z = z ^ (z >> 31)
    return z >> 32


def fault_draws(link_seed: int, n: int) -> Tuple[float, float, float, float]:
    """The four unit-interval draws ``(drop, reorder, duplicate,
    delay)`` for datagram ``n``: each is ``fate / 2**32`` — exact in
    float64 — so the host comparison ``draw < rate`` is bit-equivalent
    to the device threshold compare ``fate < ceil(rate * 2**32)``
    (``ensemble/fate.py.rate_threshold`` proves the rounding out)."""
    return (
        fault_fate_u32(link_seed, n, FATE_DROP) / 4294967296.0,
        fault_fate_u32(link_seed, n, FATE_REORDER) / 4294967296.0,
        fault_fate_u32(link_seed, n, FATE_DUPLICATE) / 4294967296.0,
        fault_fate_u32(link_seed, n, FATE_DELAY) / 4294967296.0,
    )


# --- chaos specification -----------------------------------------------------


@dataclass(frozen=True)
class LinkFaults:
    """Per-directed-link fault probabilities (each decided per datagram)."""

    drop: float = 0.0
    duplicate: float = 0.0
    reorder: float = 0.0
    delay: Tuple[float, float] = (0.0, 0.0)  # uniform seconds (lo, hi)


@dataclass(frozen=True)
class Partition:
    """A timed partition window: links crossing group boundaries drop all
    datagrams while ``at <= elapsed < heal`` (``heal=None``: forever)."""

    at: float
    heal: Optional[float]
    groups: Tuple[FrozenSet[int], ...]

    def cuts(self, src: int, dst: int, elapsed: float) -> bool:
        if elapsed < self.at or (self.heal is not None and elapsed >= self.heal):
            return False
        src_g = dst_g = None
        for i, g in enumerate(self.groups):
            if src in g:
                src_g = i
            if dst in g:
                dst_g = i
        return src_g is not None and dst_g is not None and src_g != dst_g


_FAULT_KEYS = ("drop", "duplicate", "reorder", "delay")


def _parse_faults(d: dict, where: str) -> LinkFaults:
    if not isinstance(d, dict):
        raise ValueError(
            f"chaos {where} must be an object of fault rates: {d!r}"
        )
    unknown = set(d) - set(_FAULT_KEYS)
    if unknown:
        raise ValueError(f"unknown chaos fault key(s) in {where}: {sorted(unknown)}")
    rates = {}
    for k in ("drop", "duplicate", "reorder"):
        v = d.get(k, 0.0)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or not (0.0 <= v <= 1.0):
            raise ValueError(f"chaos {where}.{k} must be a probability in [0, 1]: {v!r}")
        rates[k] = float(v)
    delay = d.get("delay", (0.0, 0.0))
    if isinstance(delay, (int, float)) and not isinstance(delay, bool):
        delay = (float(delay), float(delay))
    try:
        lo, hi = (float(delay[0]), float(delay[1]))
    except (TypeError, ValueError, IndexError):
        raise ValueError(
            f"chaos {where}.delay must be seconds or [lo, hi]: {delay!r}"
        ) from None
    if lo < 0 or hi < lo:
        raise ValueError(f"chaos {where}.delay must satisfy 0 <= lo <= hi: {delay!r}")
    return LinkFaults(delay=(lo, hi), **rates)


def _parse_partition(p, where: str) -> Partition:
    """One partition window; every malformed shape raises a single
    ``ValueError`` naming the offending key path (``partitions[i].at``
    etc.), never a raw ``KeyError``/``TypeError``."""
    if not isinstance(p, dict):
        raise ValueError(f"chaos {where} must be an object: {p!r}")
    unknown = set(p) - {"at", "heal", "groups"}
    if unknown:
        raise ValueError(
            f"unknown chaos key(s) in {where}: {sorted(unknown)}"
        )
    missing = [k for k in ("at", "groups") if k not in p]
    if missing:
        raise ValueError(
            f"chaos {where} needs {'/'.join(missing)} "
            f"(at/groups + optional heal): {p!r}"
        )
    try:
        at = float(p["at"])
    except (TypeError, ValueError):
        raise ValueError(
            f"chaos {where}.at must be seconds: {p['at']!r}"
        ) from None
    try:
        heal = None if p.get("heal") is None else float(p["heal"])
    except (TypeError, ValueError):
        raise ValueError(
            f"chaos {where}.heal must be seconds or null: {p['heal']!r}"
        ) from None
    raw_groups = p["groups"]
    if not isinstance(raw_groups, (list, tuple)):
        raise ValueError(
            f"chaos {where}.groups must be an array of id arrays: "
            f"{raw_groups!r}"
        )
    groups = []
    for j, g in enumerate(raw_groups):
        if not isinstance(g, (list, tuple)):
            raise ValueError(
                f"chaos {where}.groups[{j}] must be an array of actor "
                f"ids: {g!r}"
            )
        try:
            groups.append(frozenset(int(x) for x in g))
        except (TypeError, ValueError):
            raise ValueError(
                f"chaos {where}.groups[{j}] must contain integer actor "
                f"ids: {g!r}"
            ) from None
    if heal is not None and heal < at:
        raise ValueError(f"chaos {where}: heal < at: {p!r}")
    return Partition(at, heal, tuple(groups))


@dataclass(frozen=True)
class ChaosSpec:
    """Parsed chaos spec: default link faults, per-link overrides, and
    partition windows.  JSON schema (docs/ACTORS.md):

    ``{"drop": 0.1, "duplicate": 0.05, "reorder": 0.1, "delay": [0, 0.02],
    "links": {"0->1": {"drop": 0.5}},
    "partitions": [{"at": 0.5, "heal": 1.5, "groups": [[0, 1], [2]]}]}``

    Fault keys may be given at top level (the default for every link) or
    under ``"default"``; ``"links"`` keys are ``"SRC->DST"`` actor ids.
    """

    default: LinkFaults = field(default_factory=LinkFaults)
    links: Tuple[Tuple[Tuple[int, int], LinkFaults], ...] = ()
    partitions: Tuple[Partition, ...] = ()

    @staticmethod
    def from_json(obj) -> "ChaosSpec":
        if isinstance(obj, (str, bytes)):
            obj = json.loads(obj)  # JSONDecodeError is a ValueError
        if obj is None:
            return ChaosSpec()
        if not isinstance(obj, dict):
            raise ValueError(f"chaos spec must be a JSON object: {obj!r}")
        known = set(_FAULT_KEYS) | {"default", "links", "partitions"}
        unknown = set(obj) - known
        if unknown:
            raise ValueError(f"unknown chaos spec key(s): {sorted(unknown)}")
        top = {k: obj[k] for k in _FAULT_KEYS if k in obj}
        if top and "default" in obj:
            raise ValueError(
                "chaos spec: give fault rates at top level OR under "
                '"default", not both'
            )
        default = _parse_faults(top or obj.get("default", {}) or {}, "default")
        links_obj = obj.get("links") or {}
        if not isinstance(links_obj, dict):
            raise ValueError(
                'chaos links must be an object of "SRC->DST" keys: '
                f"{links_obj!r}"
            )
        links = []
        for key, d in links_obj.items():
            try:
                src_s, dst_s = str(key).split("->")
                link = (int(src_s), int(dst_s))
            except ValueError:
                raise ValueError(
                    f'chaos links key must look like "SRC->DST": {key!r}'
                ) from None
            links.append((link, _parse_faults(d or {}, f"links[{key}]")))
        parts_obj = obj.get("partitions") or ()
        if not isinstance(parts_obj, (list, tuple)):
            raise ValueError(
                f"chaos partitions must be an array: {parts_obj!r}"
            )
        partitions = []
        for i, p in enumerate(parts_obj):
            partitions.append(_parse_partition(p, f"partitions[{i}]"))
        return ChaosSpec(
            default=default,
            links=tuple(sorted(links)),
            partitions=tuple(partitions),
        )

    def remap_ids(self, mapping: Dict[int, int]) -> "ChaosSpec":
        """Rewrite link and partition-group ids through ``mapping`` —
        specs are written with model indices (0, 1, 2, …), but over UDP
        the actors' real ids are socket-addr encodings, which would
        silently never match (ids absent from the mapping pass through
        unchanged)."""

        def m(x: int) -> int:
            return mapping.get(x, x)

        return ChaosSpec(
            default=self.default,
            links=tuple(
                sorted(((m(s), m(d)), f) for (s, d), f in self.links)
            ),
            partitions=tuple(
                Partition(
                    p.at, p.heal, tuple(frozenset(m(x) for x in g) for g in p.groups)
                )
                for p in self.partitions
            ),
        )

    def faults_for(self, src: Id, dst: Id) -> LinkFaults:
        link = (int(src), int(dst))
        for k, f in self.links:
            if k == link:
                return f
        return self.default

    def to_dict(self) -> dict:
        def faults(f: LinkFaults) -> dict:
            return {
                "drop": f.drop, "duplicate": f.duplicate,
                "reorder": f.reorder, "delay": list(f.delay),
            }

        return {
            "default": faults(self.default),
            "links": {f"{s}->{d}": faults(f) for (s, d), f in self.links},
            "partitions": [
                {
                    "at": p.at,
                    "heal": p.heal,
                    "groups": [sorted(g) for g in p.groups],
                }
                for p in self.partitions
            ],
        }


# --- the fault-injecting transport -------------------------------------------


def _link_rng_seed(seed: int, src: Id, dst: Id) -> int:
    """A stable 64-bit per-link seed: fault schedules depend only on
    (seed, src, dst, per-link datagram index), never on hash
    randomization, thread interleaving, or wall time."""
    h = (int(seed) & _MASK64) * 0x9E3779B97F4A7C15
    h = (h + (int(src) + 1) * 0xC2B2AE3D27D4EB4F) & _MASK64
    h = (h + (int(dst) + 1) * 0x165667B19E3779F9) & _MASK64
    return h


class _LinkState:
    __slots__ = ("link_seed", "n", "held")

    def __init__(self, seed: int, src: Id, dst: Id):
        self.link_seed = _link_rng_seed(seed, src, dst)
        self.n = 0  # datagrams sent on this link so far
        self.held: List[bytes] = []  # reorder buffer


class FaultyEndpoint(Endpoint):
    def __init__(self, transport: "FaultyTransport", inner: Endpoint, id: Id):
        self._transport = transport
        self._inner = inner
        self.id = Id(id)

    def send(self, dst: Id, data: bytes) -> None:
        self._transport._send(self._inner, self.id, Id(dst), data)

    def recv(self, timeout: float):
        return self._inner.recv(timeout)

    def close(self) -> None:
        self._inner.close()


class FaultyTransport(Transport):
    """Wraps ``inner`` with the seeded fault schedule of ``spec``.

    Fault decision order per datagram (all four random draws happen for
    every datagram, so the schedule for datagram ``n`` on a link never
    shifts with timing): partition check → drop → reorder-hold →
    duplicate → delay.  A held (reordered) datagram is released right
    after the next delivered datagram on the same link — i.e. the two
    swap places; held datagrams are discarded if the transport closes
    first (indistinguishable from a drop, which the ORL retransmit
    absorbs).  Every injected fault appends a ``chaos_*`` event to the
    journal and bumps ``fault_counts``.
    """

    def __init__(
        self,
        inner: Transport,
        spec: ChaosSpec,
        seed: int = 0,
        journal=None,
    ):
        self.inner = inner
        self.spec = spec if isinstance(spec, ChaosSpec) else ChaosSpec.from_json(spec)
        self.seed = int(seed)
        self.journal: Optional[Journal] = as_journal(journal)
        self.fault_counts: Dict[str, int] = {}
        # Per-directed-link, per-kind injection counters — the fault
        # attribution table (docs/OBSERVABILITY.md "Actor-runtime
        # observability"): these aggregate exactly the journaled
        # ``chaos_*`` events, so a report rebuilt from the journal and a
        # live ``/.metrics`` scrape must agree to the count.
        self.link_fault_counts: Dict[Tuple[int, int], Dict[str, int]] = {}
        self._links: Dict[Tuple[int, int], _LinkState] = {}
        self._lock = threading.Lock()
        self._timers: set = set()
        self._closed = False
        self._summarized = False
        self._start = time.monotonic()
        if self.journal is not None:
            self.journal.append(
                "chaos_start", seed=self.seed, spec=self.spec.to_dict()
            )

    def bind(self, id: Id) -> FaultyEndpoint:
        return FaultyEndpoint(self, self.inner.bind(id), id)

    def fault_summary(self) -> dict:
        """Injected-fault aggregate: total, per-kind counts, and the
        per-link ``"src->dst" -> {kind: n}`` attribution table."""
        with self._lock:
            by_kind = dict(sorted(self.fault_counts.items()))
            links = {
                f"{src}->{dst}": dict(sorted(kinds.items()))
                for (src, dst), kinds in sorted(self.link_fault_counts.items())
            }
        return {
            "total": sum(by_kind.values()),
            "by_kind": by_kind,
            "links": links,
        }

    def close(self) -> None:
        with self._lock:
            already = self._closed
            self._closed = True
            timers, self._timers = list(self._timers), set()
        for t in timers:
            t.cancel()
        # The quiescence summary: one journal event carrying the whole
        # attribution table, emitted once even if close() is re-entered
        # (endpoint teardown and transport teardown both chain here).
        if self.journal is not None and not already and not self._summarized:
            self._summarized = True
            self.journal.append(
                "chaos_summary", seed=self.seed, **self.fault_summary()
            )
        self.inner.close()

    # -- internals ------------------------------------------------------------

    def _send(self, inner: Endpoint, src: Id, dst: Id, data: bytes) -> None:
        link = (int(src), int(dst))
        # Fault events are decided (and counted) under the lock but
        # journaled after releasing it: the critical section must not
        # include disk I/O, or every actor thread's send serializes
        # behind a file flush.  Journal.append has its own lock.
        events: List[dict] = []

        def event(kind: str, **fields) -> None:
            self.fault_counts[kind] = self.fault_counts.get(kind, 0) + 1
            per_link = self.link_fault_counts.setdefault(link, {})
            per_link[kind] = per_link.get(kind, 0) + 1
            events.append({"event": kind, **fields})

        batch = None
        delay = 0.0
        with self._lock:
            if self._closed:
                return
            ls = self._links.get(link)
            if ls is None:
                ls = self._links[link] = _LinkState(self.seed, src, dst)
            n = ls.n
            ls.n += 1
            # All four draws, at fixed counter positions: the schedule
            # for datagram n is a pure function of (seed, link, n) — and
            # counter-mode, so the device fate kernel (ensemble/fate.py)
            # reproduces each draw without host generator state.
            r_drop, r_reorder, r_dup, r_delay = fault_draws(
                ls.link_seed, n
            )
            faults = self.spec.faults_for(src, dst)
            elapsed = time.monotonic() - self._start
            if any(
                p.cuts(link[0], link[1], elapsed) for p in self.spec.partitions
            ):
                event("chaos_partition", src=link[0], dst=link[1], n=n)
            elif r_drop < faults.drop:
                event("chaos_drop", src=link[0], dst=link[1], n=n)
            elif r_reorder < faults.reorder:
                ls.held.append(data)
                event("chaos_reorder", src=link[0], dst=link[1], n=n)
            else:
                batch = [data]
                if r_dup < faults.duplicate:
                    batch.append(data)
                    event("chaos_duplicate", src=link[0], dst=link[1], n=n)
                batch.extend(ls.held)
                ls.held = []
                lo, hi = faults.delay
                delay = lo + r_delay * (hi - lo) if hi > 0 else 0.0
                if delay > 0:
                    event(
                        "chaos_delay", src=link[0], dst=link[1], n=n,
                        sec=round(delay, 6),
                    )
        if self.journal is not None:
            for e in events:
                self.journal.append(**e)
        if batch is None:
            return

        def deliver() -> None:
            for d in batch:
                inner.send(dst, d)

        if delay > 0:
            timer = threading.Timer(delay, self._fire)
            timer.args = (timer, deliver)  # so _fire can retire it
            timer.daemon = True
            with self._lock:
                if self._closed:
                    return
                self._timers.add(timer)
            timer.start()
        else:
            deliver()

    def _fire(self, timer, deliver: Callable[[], None]) -> None:
        with self._lock:
            self._timers.discard(timer)
            if self._closed:
                return
        deliver()

    def datagram_count(self) -> int:
        """Total datagrams offered to the fabric (pre-fault) — the chaos
        harness's quiescence signal."""
        with self._lock:
            return sum(ls.n for ls in self._links.values())


# --- transport-boundary history recording ------------------------------------


@dataclass(frozen=True)
class WireEnvelope:
    """A decoded datagram observed at the transport boundary."""

    src: Id
    dst: Id
    msg: Any


class RecordingEndpoint(Endpoint):
    def __init__(self, transport: "RecordingTransport", inner: Endpoint, id: Id):
        self._transport = transport
        self._inner = inner
        self.id = Id(id)

    def send(self, dst: Id, data: bytes) -> None:
        self._transport._record_out(self.id, Id(dst), data)
        self._inner.send(dst, data)

    def recv(self, timeout: float):
        received = self._inner.recv(timeout)
        if received is not None:
            data, src = received
            self._transport._record_in(Id(src), self.id, data)
        return received

    def close(self) -> None:
        self._inner.close()


class RecordingTransport(Transport):
    """Decodes every datagram crossing the transport boundary and hands
    ``WireEnvelope``s to ``on_out`` (at send, pre-fault-injection) and
    ``on_in`` (at receive, post-fault-injection).  Undecodable datagrams
    are skipped — the runtime drops those anyway."""

    def __init__(
        self,
        inner: Transport,
        deserialize: Callable[[bytes], Any],
        on_out: Optional[Callable[[WireEnvelope], None]] = None,
        on_in: Optional[Callable[[WireEnvelope], None]] = None,
    ):
        self.inner = inner
        self._deserialize = deserialize
        self._on_out = on_out
        self._on_in = on_in

    def bind(self, id: Id) -> RecordingEndpoint:
        return RecordingEndpoint(self, self.inner.bind(id), id)

    def close(self) -> None:
        self.inner.close()

    def _record(self, hook, src: Id, dst: Id, data: bytes) -> None:
        if hook is None:
            return
        try:
            msg = self._deserialize(data)
        except (ValueError, KeyError):
            return
        hook(WireEnvelope(src, dst, msg))

    def _record_out(self, src: Id, dst: Id, data: bytes) -> None:
        self._record(self._on_out, src, dst, data)

    def _record_in(self, src: Id, dst: Id, data: bytes) -> None:
        self._record(self._on_in, src, dst, data)


# --- live consistency auditing -----------------------------------------------


class LiveAuditor:
    """Feeds register-harness traffic observed at the transport boundary
    into a ``ConsistencyTester`` — the *same* tester + ``SequentialSpec``
    the model checker evaluates in its ``always`` properties, now judging
    a live run.

    Client→server ``Put``/``Get`` datagrams record invocations; server→
    client ``PutOk``/``GetOk`` datagrams record returns.  Ordered-
    reliable-link ``Deliver`` wrappers are unwrapped, and retransmits /
    chaos duplicates are deduplicated by ``(client, request_id)`` so the
    history sees each operation exactly once.  Tester-level history
    violations (double invocation, orphan return) are collected rather
    than raised — a violating history is simply reported inconsistent.
    """

    def __init__(self, tester, client_ids, journal=None):
        from ..actor import register as _register

        self._reg = _register
        self.tester = tester
        self.client_ids = frozenset(Id(c) for c in client_ids)
        self.violations: List[str] = []
        self._invoked: set = set()
        self._returned: set = set()
        self._lock = threading.Lock()
        # Optional op journal: one ``actor_op`` event per deduplicated
        # invocation/return, timestamping the operation window so a
        # rejected history can be correlated against the injected-fault
        # timeline (obs/report.py's fault-attribution table).
        self.journal: Optional[Journal] = as_journal(journal)

    @staticmethod
    def _unwrap(msg: Any) -> Any:
        from ..actor.ordered_reliable_link import Deliver

        return msg.msg if isinstance(msg, Deliver) else msg

    def on_out(self, env: WireEnvelope) -> None:
        from ..semantics.register import READ, WriteOp

        if env.src not in self.client_ids:
            return
        msg = self._unwrap(env.msg)
        if isinstance(msg, self._reg.Put):
            op = WriteOp(msg.value)
        elif isinstance(msg, self._reg.Get):
            op = READ
        else:
            return
        key = (int(env.src), msg.request_id)
        with self._lock:
            if key in self._invoked:
                return  # retransmit of an already-recorded invocation
            self._invoked.add(key)
            try:
                self.tester.on_invoke(env.src, op)
            except ValueError as e:
                self.violations.append(f"invoke {key}: {e}")
        if self.journal is not None:
            self.journal.append(
                "actor_op", kind="invoke", client=key[0],
                request_id=key[1],
            )

    def on_in(self, env: WireEnvelope) -> None:
        from ..semantics.register import WRITE_OK, ReadOk

        if env.dst not in self.client_ids:
            return
        msg = self._unwrap(env.msg)
        if isinstance(msg, self._reg.PutOk):
            ret = WRITE_OK
        elif isinstance(msg, self._reg.GetOk):
            ret = ReadOk(msg.value)
        else:
            return
        key = (int(env.dst), msg.request_id)
        with self._lock:
            if key in self._returned:
                return  # duplicate delivery of an already-recorded return
            if key not in self._invoked:
                self.violations.append(f"return without invocation: {key}")
                return
            self._returned.add(key)
            try:
                self.tester.on_return(env.dst, ret)
            except ValueError as e:
                self.violations.append(f"return {key}: {e}")
        if self.journal is not None:
            self.journal.append(
                "actor_op", kind="return", client=key[0],
                request_id=key[1],
            )

    @property
    def invoked_count(self) -> int:
        with self._lock:
            return len(self._invoked)

    @property
    def returned_count(self) -> int:
        with self._lock:
            return len(self._returned)

    def result(self) -> dict:
        """Final verdict (runs the tester's interleaving search)."""
        with self._lock:
            violations = list(self.violations)
            invoked, returned = len(self._invoked), len(self._returned)
            pending = self.tester.pending_count()
            serialized = (
                None if violations else self.tester.serialized_history()
            )
        return {
            "consistent": not violations and serialized is not None,
            "invoked": invoked,
            "returned": returned,
            "in_flight": pending,
            "violations": violations,
        }


# --- the composed chaos run --------------------------------------------------


def run_chaos_register_system(
    make_server_actor: Callable[[List[Id]], Any],
    *,
    server_count: int = 3,
    client_count: int = 2,
    put_count: int = 2,
    spec: Optional[ChaosSpec] = None,
    seed: int = 0,
    tester_factory: Optional[Callable[[], Any]] = None,
    wire_types: Tuple = (),
    journal=None,
    deadline_sec: float = 20.0,
    resend_interval: Tuple[float, float] = (0.05, 0.1),
    backoff_factor: float = 2.0,
    max_resend_interval: float = 1.0,
    max_resends: Optional[int] = 40,
    storage_dir: Optional[str] = None,
    transport_factory: Optional[Callable[[], Transport]] = None,
    quiesce_sec: float = 2.0,
    trace: bool = False,
    metrics_port: Optional[int] = None,
    stats_interval: float = 0.5,
) -> dict:
    """Run a register-protocol cluster hermetically under chaos and audit it.

    ``make_server_actor(peers)`` builds one server actor (e.g. a
    ``RegisterServer(AbdActor(peers))``) given its peer ids; servers get
    ids ``0..server_count-1`` and scripted ``RegisterClient``s ride at
    ``server_count..`` — plain model indices, since the loopback fabric
    needs no socket addresses.  Every actor is wrapped in the hardened
    ordered reliable link (exponential backoff, journal-visible give-up),
    the transport stack is ``Recording(Faulty(Loopback))``, and the run
    ends when every client op has returned, when ``deadline_sec`` passes,
    or — after the last partition window has closed — when the fabric has
    been quiescent (no datagram offered anywhere) for ``quiesce_sec``:
    per the reference ORL semantics a message no-op'd by a busy replica
    is acked but never redelivered, so a stalled client is a legal stable
    outcome (its op stays in flight, which the testers treat as optional)
    rather than something worth spinning on until the deadline.

    ``trace=True`` turns on the causal trace envelope at the transport
    boundary (``actor/obs.py``): spans are journaled as ``actor_span``
    events, and — the fault schedule being a pure function of the
    per-link datagram *index*, never the bytes — the injected schedule
    for a fixed seed is bit-identical with tracing on or off
    (tests/test_actor_chaos.py).  ``metrics_port`` serves the runtime's
    live ``/.metrics`` during the run (0 picks an ephemeral port); at
    quiescence the harness scrapes its own surface over real HTTP,
    validates the Prometheus exposition with ``parse_prometheus``, and
    folds the scrape into the result (``metrics``, ``prometheus_valid``,
    ``metrics_address``).  A journal additionally gets periodic
    ``actor_stats`` events (datagram/op/retransmit progress +
    ``partition_active``) — the stream the ``watch`` verb renders.

    Returns the audit verdict dict plus ``faults`` (injected-fault
    counts), ``fault_links`` (the per-link attribution table),
    ``completed``, ``elapsed_sec``, and ``errors``.
    """
    import shutil

    from ..actor.ids import Id as _Id
    from ..actor.obs import ObservedTransport, serve_actor_metrics
    from ..actor.ordered_reliable_link import ActorWrapper, Ack, Deliver, LinkStorage
    from ..actor.register import Get, GetOk, Put, PutOk, RegisterClient
    from ..actor.spawn import spawn
    from ..actor.transport import LoopbackTransport
    from ..actor.wire import register_wire_types, wire_deserialize, wire_serialize
    from ..obs.metrics import MetricsRegistry
    from ..semantics import LinearizabilityTester, Register

    journal = as_journal(journal)
    spec = spec if spec is not None else ChaosSpec()
    register_wire_types(
        Deliver, Ack, LinkStorage, Put, Get, PutOk, GetOk, *wire_types
    )
    server_ids = [_Id(i) for i in range(server_count)]
    client_ids = [_Id(server_count + i) for i in range(client_count)]

    if tester_factory is None:
        tester_factory = lambda: LinearizabilityTester(Register(None))  # noqa: E731
    auditor = LiveAuditor(tester_factory(), client_ids, journal=journal)
    registry = MetricsRegistry()

    def give_up(actor_id, dropped):
        if journal is not None:
            journal.append(
                "orl_give_up",
                actor=int(actor_id),
                dropped=len(dropped),
                seqs=[seq for seq, _dm in dropped],
            )

    def wrap(actor):
        return ActorWrapper(
            actor,
            resend_interval=resend_interval,
            backoff_factor=backoff_factor,
            max_resend_interval=max_resend_interval,
            max_resends=max_resends,
            on_give_up=give_up,
            metrics=registry,
        )

    actors = [
        (sid, wrap(make_server_actor([p for p in server_ids if p != sid])))
        for sid in server_ids
    ] + [
        (cid, wrap(RegisterClient(put_count=put_count, server_count=server_count)))
        for cid in client_ids
    ]

    # Stack order matters: Recording(Observed(Faulty(Loopback))) — the
    # auditor decodes clean payloads ABOVE the envelope boundary, the
    # observer envelopes/counts at the actor-facing boundary, and the
    # fault injector treats enveloped datagrams as opaque bytes below.
    inner = transport_factory() if transport_factory is not None else LoopbackTransport()
    faulty = FaultyTransport(inner, spec, seed=seed, journal=journal)
    observed = ObservedTransport(
        faulty, registry=registry, trace=trace, journal=journal
    )
    transport: Transport = RecordingTransport(
        observed, wire_deserialize, on_out=auditor.on_out, on_in=auditor.on_in
    )

    tmp_storage = None
    if storage_dir is None:
        tmp_storage = tempfile.mkdtemp(prefix="stateright-chaos-")
        storage_dir = tmp_storage

    expected = client_count * (put_count + 1)
    started = time.monotonic()
    runtime = spawn(
        wire_serialize,
        wire_deserialize,
        wire_serialize,
        wire_deserialize,
        actors,
        storage_dir=storage_dir,
        transport=transport,
        metrics=registry,
    )
    metrics_server = None
    scrape = None

    def partition_active(elapsed: float) -> bool:
        return any(
            p.at <= elapsed and (p.heal is None or elapsed < p.heal)
            for p in spec.partitions
        )

    def journal_stats(count: int) -> None:
        if journal is None:
            return
        journal.append(
            "actor_stats",
            datagrams=count,
            invoked=auditor.invoked_count,
            returned=auditor.returned_count,
            retransmits=int(registry.get("orl_retransmits_total", 0) or 0),
            give_ups=int(registry.get("orl_give_ups_total", 0) or 0),
            faults=faulty.fault_summary()["total"],
            partition_active=partition_active(time.monotonic() - started),
        )

    try:
        if metrics_port is not None:
            metrics_server = serve_actor_metrics(
                runtime, ("127.0.0.1", int(metrics_port))
            )
        deadline = started + deadline_sec
        # Quiescence detection only arms once every healing partition has
        # healed; permanent (heal=None) partitions don't delay it — after
        # the ORL gives up on a permanently cut link, silence is final.
        last_heal = max(
            (p.heal for p in spec.partitions if p.heal is not None),
            default=0.0,
        )
        quiesce_from = started + last_heal
        last_count, last_change = -1, time.monotonic()
        last_stats = time.monotonic()
        while auditor.returned_count < expected and time.monotonic() < deadline:
            count = faulty.datagram_count()
            now = time.monotonic()
            if now - last_stats >= stats_interval:
                last_stats = now
                journal_stats(count)
            if count != last_count:
                last_count, last_change = count, now
            elif now >= quiesce_from and now - last_change >= quiesce_sec:
                break  # stalled-stable: nothing has moved for quiesce_sec
            time.sleep(0.01)
        journal_stats(faulty.datagram_count())
        if metrics_server is not None:
            # The scrape the CI smoke gates on: this process GETs its own
            # /.metrics over real HTTP — both forms — and validates the
            # Prometheus exposition with the minimal parser.
            scrape = _self_scrape(metrics_server)
    finally:
        if metrics_server is not None:
            metrics_server.shutdown()
        runtime.stop(raise_errors=False)
        if tmp_storage is not None:
            shutil.rmtree(tmp_storage, ignore_errors=True)

    result = auditor.result()
    fault_summary = faulty.fault_summary()
    result.update(
        completed=result["returned"] >= expected,
        expected=expected,
        elapsed_sec=round(time.monotonic() - started, 3),
        faults=fault_summary["by_kind"],
        fault_links=fault_summary["links"],
        seed=seed,
        errors=[repr(e) for e in runtime.errors],
    )
    # Journal the verdict BEFORE folding in the scrape: the full metrics
    # dict (histogram bucket arrays, per-link maps) belongs in the
    # returned/printed result, not duplicated into every journal line.
    if journal is not None:
        journal.append("audit", **result)
    if scrape is not None:
        result.update(scrape)
    return result


def _self_scrape(server) -> dict:
    """GET the actor metrics server's own ``/.metrics`` (JSON and
    Prometheus) and validate the exposition; failures land in the dict
    (``prometheus_valid: false`` + ``scrape_error``), never raise."""
    import urllib.request

    from ..obs.prometheus import parse_prometheus

    host, port = server.server_address[:2]
    base = f"http://{host}:{port}/.metrics"
    out: dict = {"metrics_address": f"{host}:{port}"}
    try:
        with urllib.request.urlopen(base, timeout=10) as r:
            out["metrics"] = json.loads(r.read())
        with urllib.request.urlopen(
            base + "?format=prometheus", timeout=10
        ) as r:
            parse_prometheus(r.read().decode())
        out["prometheus_valid"] = True
    except Exception as e:
        out["prometheus_valid"] = False
        out["scrape_error"] = repr(e)
    return out
