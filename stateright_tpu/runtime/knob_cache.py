"""Persisted engine-knob cache: discovered ``tuned_kwargs`` survive the
process.

bench.py's measurement protocol discovers right-sized engine knobs with a
default-knob auto-tune run before every measured run.  Discovery is the
expensive half — ~21 minutes for the 61.5M-state ``2pc check 10`` — and
was re-paid by every round and every suite child because the result never
left the process (VERDICT r5 weak #2).  This cache stores each workload's
tuned kwargs as one JSON object keyed by (workload, model identity,
device, engine geometry), under a directory that doubles as the bench's
checkpoint dir; suite children (separate processes) and later rounds
reload instead of rediscovering.

Staleness is harmless by construction: the engines' auto-tune grows
undersized knobs in place mid-run, and the caller golden-gates every
measured run — a cache entry that no longer reproduces the golden is
dropped (:func:`drop_knobs`) and the caller falls back to a fresh
discovery.  Writes are atomic (write + rename) so concurrent children
can never leave a torn file.  Within one process every mutation holds a
module lock around its read-merge-write, so the checking service's
concurrent jobs (serve/scheduler.py) never lose each other's entries;
ACROSS processes (bench suite children) last-whole-file-writer wins,
which is fine for a cache whose entries are all independently
rediscoverable.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Optional

KNOBS_FILE = "knobs.json"

# Engine/protocol tags for knob_key(): bump when a default-geometry or
# knob-semantics change makes old entries misleading.  The sharded tag
# changed when the exchange went bucketed — its entries now carry the
# discovered ``bucket_slack`` rung (parallel/wave_loop.py), so a warm
# start skips the bucket overflow-retry ramp as well as auto-tune;
# pre-bucketing entries have no rung and must not shadow that.  Bumped
# to v2 for the adaptive sort-geometry ladder (entries carry the
# discovered ``sort_lanes`` rung), and to v3 for the sortless default +
# step ladder: v3 entries carry the discovered dedup path
# (``sortless`` 0/1 — a fallen-back workload must warm-start on the
# sort path without re-paying the fallback retry) and the ``step_lanes``
# rung; a v2 entry with an explicit ``sort_lanes`` would silently force
# every warm repeat onto the sort path and forfeit the election.
SINGLE_CHIP_ENGINE = "tpu-wavefront-v3"
SHARDED_ENGINE = "tpu-sharded-bucketed-v3"
# Tiered entries persist the budget-derived capacity (tiered/engine.py
# pins it — the in-HBM right-sizing rule would silently un-tier a
# warm-started repeat), so they must never shadow single-chip entries;
# the serve scheduler additionally keys their LABEL by the job's
# memory_budget_mb so entries never shadow each other across budgets.
TIERED_ENGINE = "tpu-tiered-v3"
# The composed engine shares neither geometry: its table is per-shard
# AND budget-pinned, so entries must shadow neither sharded nor tiered
# warm starts (the scheduler budget-keys the label here too).
TIERED_SHARDED_ENGINE = "tpu-tiered-sharded-v1"

# Serializes read-merge-write cycles within this process (two service
# jobs storing knobs for different workloads must both survive).
_LOCK = threading.Lock()


def _path(cache_dir: str) -> str:
    return os.path.join(cache_dir, KNOBS_FILE)


def knob_key(label: str, engine: str = SINGLE_CHIP_ENGINE) -> str:
    """The canonical cache key: workload label + device identity +
    engine/protocol version (geometry defaults change what discovery
    finds).  One definition shared by bench.py and the checking service
    (serve/scheduler.py) so the key FORMAT cannot drift; their label
    namespaces stay deliberately disjoint ("2pc_check_5" vs
    "serve:twophase:5") because the two discover different things —
    bench persists auto-tune-shrunk measurement sizes, the service its
    jobs' exact final spawn geometry.  Imports jax lazily — callers
    already run on a device."""
    import jax

    d = jax.devices()[0]
    kind = getattr(d, "device_kind", d.platform)
    return f"{label}|{d.platform}|{kind}|{engine}"


def _read_all(cache_dir: str) -> dict:
    """The whole cache, {} on any read/parse failure — a torn or
    hand-edited file degrades to rediscovery, never a crash."""
    try:
        with open(_path(cache_dir), "r", encoding="utf-8") as fh:
            data = json.load(fh)
        return data if isinstance(data, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _write_all(cache_dir: str, data: dict) -> None:
    os.makedirs(cache_dir, exist_ok=True)
    tmp = _path(cache_dir) + f".tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
    os.replace(tmp, _path(cache_dir))


def load_knobs(cache_dir: str, key: str) -> Optional[dict]:
    """The cached kwargs dict for ``key``, or None.  Values come back as
    plain ints (engine kwargs are all integer knobs — except the tiered
    engines' fractional ``memory_budget_mb``, which stays a float)."""
    entry = _read_all(cache_dir).get(key)
    if not isinstance(entry, dict):
        return None
    knobs = entry.get("knobs")
    if not isinstance(knobs, dict) or not knobs:
        return None
    try:
        return {
            str(k): (float(v) if k == "memory_budget_mb" else int(v))
            for k, v in knobs.items()
        }
    except (TypeError, ValueError):
        return None


def store_knobs(cache_dir: str, key: str, knobs: dict, **meta) -> None:
    """Merge one entry into the cache file (atomic write + rename, under
    the process lock).  ``meta`` keys (e.g. the golden count that
    validated the knobs) are stored alongside for human inspection; only
    ``knobs`` is read back."""
    with _LOCK:
        data = _read_all(cache_dir)
        # Geometry knobs are integers — EXCEPT memory_budget_mb, the
        # tiered engines' fractional-MB budget (int() would floor the
        # spill-forcing test budgets to 0 and change the derived cap).
        data[key] = {"knobs": {
            k: (float(v) if k == "memory_budget_mb" else int(v))
            for k, v in knobs.items()
        }, **meta}
        _write_all(cache_dir, data)


def drop_knobs(cache_dir: str, key: str) -> None:
    """Invalidate one entry (a golden-gate failure at cached knobs, or a
    served job that errored at cached sizes)."""
    with _LOCK:
        data = _read_all(cache_dir)
        if data.pop(key, None) is not None:
            _write_all(cache_dir, data)
