"""Append-only JSON-lines telemetry journal for long checking runs.

One event per line, written with a single ``write()`` each so concurrent
writers (the engine thread, the child process wrapper, and the supervisor
parent all append to the same file through O_APPEND handles) interleave at
line granularity.  The journal is both a run artifact — per-wave frontier
size, unique states, dedup occupancy, device-call wall time — and the
supervisor's liveness signal: a child whose journal stops moving past the
per-call deadline is declared hung and restarted from the last checkpoint.

Event schema (full field lists in docs/RUNTIME.md): every event carries
``t`` (unix wall time, float seconds) and ``event`` (a string tag).
Every segment opens with a ``clock_sync`` header — a paired wall +
monotonic timestamp plus the writer's ``pid@host`` stamp — because the
other events mix ``time.time()`` stamps with ``time.monotonic()``
durations: the pair anchors each process's monotonic clock to wall
time once, so the timeline exporter (obs/timeline.py) can fold
multi-process fleet journals onto one aligned axis even on hosts whose
wall clocks step mid-run.  Readers never see it unless they ask
(``read_journal(path, include_sync=True)`` or
:func:`read_clock_syncs`).
Engine events: ``resume``, ``wave``, ``checkpoint``, ``grow``,
``geometry`` (the run's live knobs, once per loop start), ``compile``
(program-cache misses with first-call timing + key provenance,
parallel/wave_common.py), ``engine_done``, and — traced runs only —
``trace_summary``.  Under
``trace=True`` each ``wave`` event is enriched with ``wave_breakdown``
(per-phase seconds), ``bytes`` (modeled bytes touched), and
``hbm_util_frac`` (plus measured ``exchange_payload_bytes`` /
``exchange_occupancy`` on the sharded engine) — the journal doubles as
the wave-trace stream (docs/OBSERVABILITY.md).  Child events: ``run_start``, ``run_end``,
``child_error``.  Supervisor events: ``supervisor_start``, ``crash``,
``hang``, ``relax``, ``restart``, ``wall_timeout``, ``give_up``,
``supervisor_done``.  Chaos-runtime events (``runtime/chaos.py``, see
docs/ACTORS.md): ``chaos_start``, ``chaos_drop``, ``chaos_duplicate``,
``chaos_reorder``, ``chaos_delay``, ``chaos_partition``, ``orl_give_up``,
``audit``.  Service events (``serve/``, see docs/SERVING.md):
``service_start``/``service_stop``, the ``job_*`` lifecycle family, and
``job_span`` per-job duration spans.  Incremental-store events
(``incr/``, see docs/INCREMENTAL.md): ``incr_classified`` (delta mode +
reason), ``incr_verdict_hit``, ``incr_property_recheck``,
``incr_seeded``, ``incr_stored``, ``incr_store_skipped`` — rendered by
the ``watch`` verb and obs/report.py's "Incremental re-checking"
section.  Fleet events (``fleet/``, see docs/SERVING.md "Fleet mode"):
``fleet_submitted``, ``fleet_claimed``, ``fleet_claim_lost``,
``fleet_lease``, ``fleet_requeued``, ``fleet_done``, ``fleet_failed``,
``fleet_cancelled``, ``fleet_preempted``, ``fleet_worker`` /
``fleet_worker_stop``, ``fleet_portfolio`` /
``fleet_portfolio_winner``, and the gang-batch family ``gang_dispatch``
/ ``gang_eject`` — every row carries the ``worker`` id (pid@host) that
acted, so the fleet journal alone reconstructs the full
claim/lease/requeue history of every job.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Dict, List, Optional

# The once-per-segment header pairing wall and monotonic clocks (plus
# the writer's pid@host stamp) — the alignment anchor obs/timeline.py
# uses to merge multi-process journals onto one wall-clock axis.
CLOCK_SYNC_EVENT = "clock_sync"


class Journal:
    """Appends events to a JSONL file; safe to share a path across
    processes (each instance holds its own ``O_APPEND`` descriptor) and
    to share one instance across threads (the chaos transport's actor and
    delay-timer threads — and the checking service's concurrent jobs
    (serve/scheduler.py) — all append through a single journal).

    Line atomicity is the contract concurrent writers rely on: each
    event is one ``os.write`` of the whole encoded line on an
    ``O_APPEND`` descriptor, so the kernel's atomic append (offset
    lookup + write under the inode lock) lands every line contiguously
    at the true end of file — a buffered ``TextIOWrapper`` could split
    one line across several syscalls and interleave torn halves from
    two writers (pinned by tests/test_runtime.py's interleaved-writer
    test).

    Rotation (``max_bytes``): a persistent service daemon
    (serve/server.py) journals every job forever, so an unrotated file
    grows without bound.  With ``max_bytes`` set, an append that would
    push the current segment past the cap first rolls the file over:
    ``journal.jsonl`` -> ``journal.jsonl.1`` (older segments shift to
    ``.2..max_segments``; the oldest falls off), each shift one atomic
    ``os.rename``, all under the instance lock, and the append then
    lands in a fresh segment — a record is never split across segments.
    :func:`read_journal` merges segments oldest-first, so readers see
    one continuous event stream.  Rotation is per-instance: run
    directories where the child, supervisor, and engine share one path
    through separate instances keep the default ``max_bytes=None``
    (no rotation, exactly the old behavior)."""

    def __init__(self, path: str, max_bytes: Optional[int] = None,
                 max_segments: int = 8, fsync: bool = False):
        """``fsync=True`` follows every append with an ``os.fsync`` —
        the durability discipline the fleet store (fleet/store.py)
        relies on: a ``kill -9`` immediately after ``append`` returns
        must not lose the event, because the fleet journal IS the job
        store's source of truth.  Default off: run/serve telemetry
        journals value throughput over power-loss durability."""
        self.path = str(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        if max_bytes is not None and max_bytes <= 0:
            raise ValueError("max_bytes must be positive (or None)")
        self.max_bytes = max_bytes
        self.max_segments = max(1, int(max_segments))
        self.fsync = bool(fsync)
        self._fd: Optional[int] = None
        self._lock = threading.Lock()
        self._synced = False  # this instance has stamped a clock_sync

    def _sync_line(self) -> bytes:
        """One encoded ``clock_sync`` header line: the wall/monotonic
        pair is read back-to-back so the offset between the two clocks
        is captured to within a few microseconds."""
        host = socket.gethostname()
        rec = {
            "t": time.time(),
            "event": CLOCK_SYNC_EVENT,
            "mono": time.monotonic(),
            "pid": os.getpid(),
            "host": host,
            "worker": f"{os.getpid()}@{host}",
        }
        return (json.dumps(rec, sort_keys=True) + "\n").encode("utf-8")

    def _rollover(self) -> None:
        """Shift segments up and move the live file to ``.1`` (caller
        holds the lock; the live fd is closed first so the next append
        reopens a fresh segment at the canonical path)."""
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None
        oldest = f"{self.path}.{self.max_segments}"
        if os.path.exists(oldest):
            os.unlink(oldest)
        for k in range(self.max_segments - 1, 0, -1):
            seg = f"{self.path}.{k}"
            if os.path.exists(seg):
                os.rename(seg, f"{self.path}.{k + 1}")
        if os.path.exists(self.path):
            os.rename(self.path, f"{self.path}.1")

    def append(self, event: str, **fields) -> dict:
        record = {"t": time.time(), "event": event}
        record.update(fields)
        line = (json.dumps(record, sort_keys=True, default=str) + "\n").encode(
            "utf-8"
        )
        with self._lock:
            if self._fd is None:
                self._fd = os.open(
                    self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
                )
            if event == CLOCK_SYNC_EVENT:
                self._synced = True  # the caller IS the header
            sync = b"" if self._synced else self._sync_line()
            if self.max_bytes is not None:
                size = os.fstat(self._fd).st_size
                if size > 0 and size + len(sync) + len(line) > self.max_bytes:
                    self._rollover()
                    self._fd = os.open(
                        self.path,
                        os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644,
                    )
                    # Every fresh segment re-anchors the clocks, so a
                    # reader holding any single segment can align it.
                    if event != CLOCK_SYNC_EVENT:
                        sync = self._sync_line()
            if sync:
                os.write(self._fd, sync)
                self._synced = True
            os.write(self._fd, line)
            if self.fsync:
                os.fsync(self._fd)
        return record

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def as_journal(journal) -> Optional[Journal]:
    """Engine-kwarg coercion: accept a :class:`Journal`, a path, or None."""
    if journal is None or isinstance(journal, Journal):
        return journal
    return Journal(str(journal))


def _segment_paths(path: str) -> List[str]:
    """Rotated segments oldest-first (``.N`` .. ``.1``), then the live
    file — one continuous stream for readers."""
    segs = []
    k = 1
    while os.path.exists(f"{path}.{k}"):
        segs.append(f"{path}.{k}")
        k += 1
    segs.reverse()
    segs.append(path)
    return segs


def read_journal(path: str, include_sync: bool = False) -> List[Dict]:
    """Parse a journal file into a list of event dicts, merging rotated
    segments (oldest first) when present.  Tolerates a torn trailing
    line (a writer killed mid-``write``); see
    :func:`read_journal_stats` for the skip count.

    ``clock_sync`` headers are filtered out by default — they are
    per-segment clock plumbing, not run telemetry, and every existing
    consumer indexes events positionally (``events[0]``) or asserts
    exact event sequences.  Pass ``include_sync=True`` (or use
    :func:`read_clock_syncs`) to see them."""
    return read_journal_stats(path, include_sync=include_sync)[0]


def read_clock_syncs(path: str) -> List[Dict]:
    """Just the ``clock_sync`` headers of a journal, oldest first — one
    wall/monotonic anchor per (writer instance x segment)."""
    events, _ = read_journal_stats(path, include_sync=True)
    return [e for e in events if e.get("event") == CLOCK_SYNC_EVENT]


def read_journal_stats(path: str, include_sync: bool = False):
    """Like :func:`read_journal`, but also returns how many lines were
    SKIPPED as torn/garbled (undecodable JSON, or a truncation that
    still parses but is not an event object — ``{"t": 17`` torn after
    the value decodes as the integer 17).  Consumers that summarize a
    crashed run's journal (obs/report.py, the ``watch`` verb) surface
    the count as a warning instead of silently absorbing — or worse,
    crashing on — the torn tail.

    A rollover landing BETWEEN the segment listing and the reads would
    silently skip the segment whose name shifted, so the read is
    re-attempted until the segment list is stable across it (bounded;
    one pass on a quiet journal — rotation happens at most once per
    ``max_bytes`` of appends, so two consecutive passes racing distinct
    rollovers is already pathological)."""
    events: List[Dict] = []
    skipped = 0
    for _ in range(3):
        segs = _segment_paths(str(path))
        events = []
        skipped = 0
        for seg in segs:
            try:
                with open(seg, "r", encoding="utf-8") as fh:
                    for line in fh:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            rec = json.loads(line)
                        except json.JSONDecodeError:
                            skipped += 1  # torn tail from a killed writer
                            continue
                        if not isinstance(rec, dict):
                            skipped += 1  # truncation that still parses
                            continue
                        if (not include_sync
                                and rec.get("event") == CLOCK_SYNC_EVENT):
                            continue  # per-segment clock plumbing
                        events.append(rec)
            except FileNotFoundError:
                continue  # racing a rollover; the re-check below catches it
        if _segment_paths(str(path)) == segs:
            break
    return events, skipped


def last_event(path: str, event: Optional[str] = None) -> Optional[Dict]:
    """The most recent event (optionally of one type); None if absent."""
    matched = None
    for rec in read_journal(path):
        if event is None or rec.get("event") == event:
            matched = rec
    return matched
