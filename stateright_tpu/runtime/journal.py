"""Append-only JSON-lines telemetry journal for long checking runs.

One event per line, written with a single ``write()`` each so concurrent
writers (the engine thread, the child process wrapper, and the supervisor
parent all append to the same file through O_APPEND handles) interleave at
line granularity.  The journal is both a run artifact — per-wave frontier
size, unique states, dedup occupancy, device-call wall time — and the
supervisor's liveness signal: a child whose journal stops moving past the
per-call deadline is declared hung and restarted from the last checkpoint.

Event schema (full field lists in docs/RUNTIME.md): every event carries
``t`` (unix wall time, float seconds) and ``event`` (a string tag).
Engine events: ``resume``, ``wave``, ``checkpoint``, ``grow``,
``engine_done``, and — traced runs only — ``trace_summary``.  Under
``trace=True`` each ``wave`` event is enriched with ``wave_breakdown``
(per-phase seconds), ``bytes`` (modeled bytes touched), and
``hbm_util_frac`` (plus measured ``exchange_payload_bytes`` /
``exchange_occupancy`` on the sharded engine) — the journal doubles as
the wave-trace stream (docs/OBSERVABILITY.md).  Child events: ``run_start``, ``run_end``,
``child_error``.  Supervisor events: ``supervisor_start``, ``crash``,
``hang``, ``relax``, ``restart``, ``wall_timeout``, ``give_up``,
``supervisor_done``.  Chaos-runtime events (``runtime/chaos.py``, see
docs/ACTORS.md): ``chaos_start``, ``chaos_drop``, ``chaos_duplicate``,
``chaos_reorder``, ``chaos_delay``, ``chaos_partition``, ``orl_give_up``,
``audit``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional


class Journal:
    """Appends events to a JSONL file; safe to share a path across
    processes (each instance holds its own ``O_APPEND`` descriptor) and
    to share one instance across threads (the chaos transport's actor and
    delay-timer threads — and the checking service's concurrent jobs
    (serve/scheduler.py) — all append through a single journal).

    Line atomicity is the contract concurrent writers rely on: each
    event is one ``os.write`` of the whole encoded line on an
    ``O_APPEND`` descriptor, so the kernel's atomic append (offset
    lookup + write under the inode lock) lands every line contiguously
    at the true end of file — a buffered ``TextIOWrapper`` could split
    one line across several syscalls and interleave torn halves from
    two writers (pinned by tests/test_runtime.py's interleaved-writer
    test)."""

    def __init__(self, path: str):
        self.path = str(path)
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fd: Optional[int] = None
        self._lock = threading.Lock()

    def append(self, event: str, **fields) -> dict:
        record = {"t": time.time(), "event": event}
        record.update(fields)
        line = (json.dumps(record, sort_keys=True, default=str) + "\n").encode(
            "utf-8"
        )
        with self._lock:
            if self._fd is None:
                self._fd = os.open(
                    self.path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644
                )
            os.write(self._fd, line)
        return record

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def as_journal(journal) -> Optional[Journal]:
    """Engine-kwarg coercion: accept a :class:`Journal`, a path, or None."""
    if journal is None or isinstance(journal, Journal):
        return journal
    return Journal(str(journal))


def read_journal(path: str) -> List[Dict]:
    """Parse a journal file into a list of event dicts.  Tolerates a
    torn trailing line (a writer killed mid-``write``)."""
    events: List[Dict] = []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue  # torn tail from a killed writer
    except FileNotFoundError:
        pass
    return events


def last_event(path: str, event: Optional[str] = None) -> Optional[Dict]:
    """The most recent event (optionally of one type); None if absent."""
    matched = None
    for rec in read_journal(path):
        if event is None or rec.get("event") == event:
            matched = rec
    return matched
