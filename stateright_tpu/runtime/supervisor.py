"""Run supervisor: isolated-child execution, crash/hang detection, and
checkpointed auto-resume with an adaptive geometry backoff.

The failure modes this subsystem absorbs were all observed on real runs
(BENCH_r04/r05): the TPU worker hard-crashes deterministically when one
device call exceeds ~80 s, the tunnel drops mid-compile, and the bench
driver kills the whole process at a wall deadline (rc=124).  The engines
already persist full run state (``save_snapshot`` / ``resume_from``); the
supervisor turns those primitives into resilience:

- the check runs in an isolated CHILD process, so a poisoned TPU runtime
  (a crashed worker fails every later device call in that process, retries
  included) costs one attempt, never the parent;
- the child checkpoints every N waves / T seconds through the engine's
  journal/checkpoint hooks, atomically (write + rename);
- the parent watches the child's journal for liveness: death and hangs are
  both detected, and the next attempt resumes from the latest checkpoint;
- each crash restart applies :func:`relax_geometry` — straight to
  ``dedup_factor=1``, never stepwise, because the intermediate stop was
  itself measured as a NEW worker-crash geometry (commit history: the
  dd=2-at-doubled-frontier stop crashed where dd=1 completes).

This is the swarm-verification / TLC-checkpointing recipe (PAPERS.md):
restartable workers plus durable progress state.

Observability: the child's ``result.json`` carries the checker's full
``metrics()`` snapshot, and a child spawned with ``trace=True`` in its
engine kwargs streams enriched per-wave trace records (and a final
``trace_summary`` event) into the run dir's ``journal.jsonl`` — the
wave-trace artifact (docs/OBSERVABILITY.md).  ``relax_geometry`` never
touches ``trace``: backoff changes tuning knobs only, and whether a run
is traced is a user decision, not a geometry.  Traced children never
RESUME (the engines refuse trace+resume); a restarted traced child
starts from scratch, keeping its journaled trace records.
"""

from __future__ import annotations

import os
import pickle
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .journal import Journal, last_event, read_journal

# Transient tunneled-device failure markers worth a fresh-process retry
# (observed: jax.errors.JaxRuntimeError INTERNAL "remote_compile: read
# body: response body closed before all bytes were read"; UNAVAILABLE
# "TPU worker process crashed or restarted").  Shared with bench.py so
# there is exactly one classification list.
TRANSIENT_MARKERS = (
    "read body",
    "response body closed",
    "remote_compile",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "Connection reset",
    "Broken pipe",
)

# File names inside a supervised run directory.
JOURNAL_FILE = "journal.jsonl"
CHECKPOINT_FILE = "checkpoint.npz"
SPEC_FILE = "spec.pkl"
CHILD_CONFIG_FILE = "child_config.json"
RELAX_FILE = "relax.json"
RESULT_FILE = "result.json"
ERROR_FILE = "error.txt"
CHILD_LOG_FILE = "child.log"

# Child exit code for a clean Python-level failure (written to ERROR_FILE),
# as opposed to a runtime kill (signal) or an interpreter abort.
CHILD_ERROR_RC = 3

# Exit code for a COMPLETED check that discovered a property violation
# (cli.py check-tpu / submit): nonzero so CI and service callers can
# gate on the verdict, distinct from crash (1) / usage (2) / error (3).
# The supervisor treats a CLI child exiting with this code as done —
# a found counterexample is a result, not a failure to retry.
VIOLATION_RC = 4


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def load_json_or_default(path: str, default: dict) -> dict:
    """Tolerant run-dir artifact read, shared by every relax.json /
    child_config.json consumer: a missing OR torn file (killed writer)
    degrades to the default instead of bricking the run dir."""
    import json

    try:
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)
    except (FileNotFoundError, json.JSONDecodeError):
        return default


# --- geometry backoff --------------------------------------------------------

# Engine defaults the policy assumes when the caller left a knob unset.
_DEFAULTS = {
    "tpu": {"dedup_factor": 8, "frontier_key": "max_frontier",
            "frontier": 1 << 15},
    "sharded": {"dedup_factor": 4, "frontier_key": "chunk_size",
                "frontier": 1 << 11},
}
# The tiered engine is the single-chip engine plus a cold tier — same
# knob names, same crash-relevant geometry axes.
_DEFAULTS["tiered"] = _DEFAULTS["tpu"]
# The composed engine is the sharded engine plus per-shard cold tiers:
# sharded knob names (chunk_size), sharded defaults.
_DEFAULTS["tiered-sharded"] = _DEFAULTS["sharded"]
FRONTIER_FLOOR = 2048
WAVES_PER_CALL_FLOOR = 8


def relax_geometry(engine_kwargs: dict, engine: str = "tpu") -> Optional[dict]:
    """One backoff step for a crashed run's engine geometry; None when
    nothing is left to relax.

    Ordered by what the crash evidence supports:

    1. ``dedup_factor`` goes STRAIGHT to the always-safe 1, never
       stepwise: the intermediate stop (dd=2 at a doubled frontier) was
       measured as a NEW worker-crash geometry on the 61.5M-state 2pc run,
       while dd=1 — same unique-buffer lanes — completes.
    2. The frontier/chunk halves (floor 2048): smaller chunks shorten the
       per-wave device time that kills the tunneled worker past ~80 s.
    3. ``waves_per_call`` halves (floor 8): per-call device time is
       waves_per_call x per-wave cost, the common thread across every
       observed hard crash.

    The returned dict is a NEW kwargs mapping (the input is not mutated);
    resumed runs adopt the snapshot's table/log geometry, so relaxing
    these tuning-only knobs never changes results, only overflow/crash
    behavior.

    Only ``dedup_factor`` is ever relaxed from an engine DEFAULT; the
    frontier and waves_per_call steps require the knob to be present in
    the kwargs.  Writing a frontier derived from the assumed default
    would OVERRIDE a smaller model-specific setting the caller never
    exposed here (e.g. a CLI spec's tuned ``tpu_kwargs``) with a much
    larger one — lengthening per-call device time, the very axis the
    backoff exists to shrink.
    """
    d = _DEFAULTS[engine]
    kwargs = dict(engine_kwargs)
    dd = int(kwargs.get("dedup_factor", d["dedup_factor"]))
    if dd > 1:
        kwargs["dedup_factor"] = 1
        return kwargs
    fkey = d["frontier_key"]
    frontier = kwargs.get(fkey)
    if frontier is not None and int(frontier) > FRONTIER_FLOOR:
        kwargs[fkey] = max(FRONTIER_FLOOR, int(frontier) // 2)
        return kwargs
    wpc = kwargs.get("waves_per_call")
    if wpc is not None and int(wpc) > WAVES_PER_CALL_FLOOR:
        kwargs["waves_per_call"] = max(WAVES_PER_CALL_FLOOR, int(wpc) // 2)
        return kwargs
    return None


# --- generic isolated-child execution (bench.py's one retry loop) ------------


@dataclass
class IsolatedResult:
    """Outcome of :func:`run_isolated` — the LAST attempt's process
    output plus how the run ended."""

    argv: List[str]
    returncode: Optional[int] = None
    stdout: str = ""
    stderr: str = ""
    timed_out: bool = False
    timeout: Optional[float] = None
    attempts_used: int = 0
    # True when the run ended because the caller's DEADLINE left no
    # budget for the next attempt (a crash whose retry was skipped) —
    # distinct from an attempt genuinely running out its own timeout.
    deadline_reached: bool = False


def run_isolated(
    argv: List[str],
    *,
    timeout: Optional[float] = None,
    attempts: int = 2,
    env: Optional[dict] = None,
    crash_if: Optional[Callable[[IsolatedResult], bool]] = None,
    echo_stderr: bool = True,
    label: str = "child",
    deadline: Optional[float] = None,
) -> IsolatedResult:
    """Run ``argv`` in a fresh subprocess with bounded fresh-process
    retries — the one resilience implementation for isolated work.

    - A TIMEOUT is final (deterministic slowness: a retry burns another
      budget and cannot succeed); the result carries ``timed_out`` and the
      child's stderr tail.
    - A CRASH (``crash_if(result)`` true; default: nonzero return code)
      gets a fresh-process retry up to ``attempts`` — a new process
      reconnects fine after a poisoned TPU runtime kills the old one.
    - Anything else returns immediately (success, or a deterministic
      error a retry won't fix).

    ``deadline`` (a ``time.monotonic()`` value) caps the WHOLE call,
    retries included: each attempt's effective timeout shrinks to what
    remains, and an attempt with no budget left returns ``timed_out``
    instead of starting — a late crash must not let the retry overrun
    the caller's global budget (the rc=124 driver-kill mode).
    """
    result = IsolatedResult(argv=list(argv), timeout=timeout)
    is_crash = crash_if or (lambda r: r.returncode != 0)
    for attempt in range(1, attempts + 1):
        result.attempts_used = attempt
        attempt_timeout = timeout
        if deadline is not None:
            remaining = deadline - time.monotonic()
            # A sliver of budget is as good as none: an attempt that
            # would be killed within seconds cannot do useful work and
            # would be misreported as a genuine timeout.
            if remaining <= 5.0:
                result.timed_out = True
                result.deadline_reached = True
                _log(f"{label}: retry budget deadline reached (no retry)")
                return result
            attempt_timeout = (
                remaining if timeout is None else min(timeout, remaining)
            )
        try:
            proc = subprocess.run(
                argv, capture_output=True, text=True,
                timeout=attempt_timeout, env=env,
            )
        except subprocess.TimeoutExpired as te:
            tail = te.stderr or ""
            if isinstance(tail, bytes):
                tail = tail.decode(errors="replace")
            result.timed_out = True
            result.stderr = tail
            result.returncode = None
            if deadline is not None and time.monotonic() >= deadline:
                # The attempt was cut short by the caller's deadline,
                # not by its own full-length timeout.
                result.deadline_reached = True
            _log(f"{label}: timed out after {attempt_timeout:.0f}s "
                 "(no retry)")
            return result
        result.returncode = proc.returncode
        result.stdout = proc.stdout
        result.stderr = proc.stderr
        if echo_stderr and proc.stderr:
            sys.stderr.write(proc.stderr)
        if not is_crash(result):
            return result
        if attempt < attempts:
            _log(
                f"{label}: crashed (rc={proc.returncode}, attempt "
                f"{attempt}/{attempts}); retrying in a fresh process"
            )
    return result


# --- checkpointed run supervision --------------------------------------------


@dataclass
class CheckSpec:
    """A supervised check, in picklable form (the child rebuilds it in a
    fresh process).  ``model_factory`` must be a module-level callable —
    e.g. a model class, ``functools.partial`` over one, or a helper like
    ``bench.paxos_model`` — because lambdas do not pickle."""

    model_factory: Callable
    factory_args: tuple = ()
    factory_kwargs: dict = field(default_factory=dict)
    engine: str = "tpu"  # "tpu" | "sharded" | "tiered" | "tiered-sharded"
    engine_kwargs: dict = field(default_factory=dict)
    target_state_count: Optional[int] = None
    target_max_depth: Optional[int] = None
    timeout: Optional[float] = None

    def build_model(self):
        return self.model_factory(*self.factory_args, **self.factory_kwargs)


@dataclass
class SupervisorConfig:
    run_dir: str
    # Checkpoint cadence, forwarded to the engine's checkpoint hooks.
    checkpoint_every_waves: Optional[int] = None
    checkpoint_every_sec: Optional[float] = 30.0
    # Wall deadline for the WHOLE supervised run (all attempts); on expiry
    # the child is killed and a partial result (from the journal) returned.
    wall_deadline_sec: Optional[float] = None
    # Liveness: a child whose journal stops moving for this long is hung
    # (the observed TPU hang mode leaves the process alive but stuck in a
    # device call) and is killed + restarted from the last checkpoint.
    call_deadline_sec: float = 300.0
    max_restarts: int = 3
    poll_interval_sec: float = 0.25
    resume: bool = True  # resume from an existing checkpoint in run_dir
    # Apply relax_geometry() on crash restarts (tuning-only; results are
    # unaffected because resumes adopt the snapshot's geometry).
    geometry_backoff: bool = True
    # Which engine's geometry defaults the backoff assumes when
    # supervising a child_argv (spec mode reads the spec's engine).
    engine: str = "tpu"
    # CLI mode streams the child's report lines to the parent's stdout;
    # library mode captures them to run_dir/child.log.
    inherit_output: bool = False


class SupervisorError(RuntimeError):
    pass


class RunSupervisor:
    """Supervises one checkpointed check to completion across child
    crashes, hangs, and restarts.

    Two child modes share the monitor loop: a :class:`CheckSpec` (pickled
    into the run dir; the child is ``python -m
    stateright_tpu.runtime.child RUN_DIR``) or an explicit ``child_argv``
    (the CLI re-invokes the model module's own CLI with
    ``--checkpoint-dir/--resume``).
    """

    def __init__(
        self,
        config: SupervisorConfig,
        spec: Optional[CheckSpec] = None,
        child_argv: Optional[List[str]] = None,
        engine_kwargs: Optional[dict] = None,
    ):
        """``engine_kwargs`` seeds the geometry-backoff state in
        child_argv mode, where the supervisor cannot see the child's
        actual engine settings (the CLI passes its spec's ``tpu_kwargs``
        here so the frontier relax steps can fire — the policy only
        relaxes knobs it can see).  Ignored in spec mode, which reads
        the spec's own engine_kwargs."""
        if (spec is None) == (child_argv is None):
            raise ValueError("provide exactly one of spec or child_argv")
        self.config = config
        self.spec = spec
        self._child_argv = child_argv
        self._proc: Optional[subprocess.Popen] = None
        self.run_dir = os.path.abspath(config.run_dir)
        self.journal_path = os.path.join(self.run_dir, JOURNAL_FILE)
        self.checkpoint_path = os.path.join(self.run_dir, CHECKPOINT_FILE)
        self.result_path = os.path.join(self.run_dir, RESULT_FILE)
        self._engine_kwargs = dict(
            spec.engine_kwargs if spec is not None else (engine_kwargs or {})
        )
        # The completing child's exit code ("done" outcomes only): lets
        # the CLI propagate a VIOLATION_RC verdict through supervision.
        self.last_child_rc: Optional[int] = None

    # -- setup ----------------------------------------------------------------

    def _prepare(self) -> Journal:
        import json

        os.makedirs(self.run_dir, exist_ok=True)
        if not self.config.resume:
            # A fresh (non-resume) session must not inherit ANY state
            # from a previous one — including the journal, whose stale
            # last wave event would otherwise surface as this run's
            # "partial progress" on an early wall-deadline.
            for name in (CHECKPOINT_FILE, RELAX_FILE, RESULT_FILE,
                         ERROR_FILE, JOURNAL_FILE, CHILD_LOG_FILE):
                try:
                    os.remove(os.path.join(self.run_dir, name))
                except FileNotFoundError:
                    pass
        else:
            # A resumed session inherits the previous session's proven
            # relaxation: re-seeding the backoff from the unrelaxed spec
            # kwargs would, on the next crash, overwrite relax.json with
            # a geometry already known to crash.
            self._engine_kwargs.update(
                load_json_or_default(
                    os.path.join(self.run_dir, RELAX_FILE), {}
                )
            )
        # A stale result from a previous completed run must never be
        # mistaken for this run's outcome.
        try:
            os.remove(self.result_path)
        except FileNotFoundError:
            pass
        if self.spec is not None:
            with open(os.path.join(self.run_dir, SPEC_FILE), "wb") as fh:
                pickle.dump(self.spec, fh)
            with open(
                os.path.join(self.run_dir, CHILD_CONFIG_FILE), "w",
                encoding="utf-8",
            ) as fh:
                json.dump(
                    {
                        "checkpoint_every_waves":
                            self.config.checkpoint_every_waves,
                        "checkpoint_every_sec":
                            self.config.checkpoint_every_sec,
                        # Always true for the CHILD: config.resume only
                        # governs pre-existing checkpoints, which the
                        # non-resume branch above already deleted.
                        # Within-session crash restarts must resume from
                        # their own fresh checkpoint or every restart
                        # would start from scratch.
                        "resume": True,
                    },
                    fh,
                )
        return Journal(self.journal_path)

    def _child_command(self) -> List[str]:
        if self._child_argv is not None:
            return list(self._child_argv)
        return [sys.executable, "-m", "stateright_tpu.runtime.child",
                self.run_dir]

    def _child_env(self) -> dict:
        env = dict(os.environ)
        # The child must be able to import this package even when it is
        # not installed (the repo-checkout workflow).
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        parts = [pkg_root] + (
            env.get("PYTHONPATH", "").split(os.pathsep)
            if env.get("PYTHONPATH")
            else []
        )
        env["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
        # Persistent compile cache for EVERY child mode (runtime.child
        # sets its own default, but CLI-mode children would otherwise
        # recompile identically on every restart, burning the restart
        # budget on a model whose compile approaches the call deadline).
        env.setdefault(
            "JAX_COMPILATION_CACHE_DIR",
            os.path.join(self.run_dir, ".jax_cache"),
        )
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
        return env

    # -- monitoring -----------------------------------------------------------

    @property
    def child_pid(self) -> Optional[int]:
        return self._proc.pid if self._proc is not None else None

    def _journal_activity(self) -> float:
        """Monotonic-comparable timestamp of the journal's last growth
        (file size is the signal: mtime granularity is filesystem-
        dependent)."""
        try:
            return os.stat(self.journal_path).st_size
        except FileNotFoundError:
            return -1.0

    def _kill_child(self) -> None:
        if self._proc is None or self._proc.poll() is not None:
            return
        try:
            self._proc.send_signal(signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass

    def _partial_from_journal(self) -> Dict:
        wave = last_event(self.journal_path, "wave") or {}
        return {
            "completed": False,
            "unique_state_count": wave.get("unique", 0),
            "state_count": wave.get("states", 0),
            "max_depth": wave.get("depth", 0),
            "checkpoint": (
                self.checkpoint_path
                if os.path.exists(self.checkpoint_path)
                else None
            ),
        }

    def _read_error(self) -> str:
        try:
            with open(
                os.path.join(self.run_dir, ERROR_FILE), encoding="utf-8"
            ) as fh:
                return fh.read()
        except FileNotFoundError:
            return ""

    def _log_tail(self, n: int = 2000) -> str:
        try:
            with open(
                os.path.join(self.run_dir, CHILD_LOG_FILE),
                encoding="utf-8", errors="replace",
            ) as fh:
                return fh.read()[-n:]
        except FileNotFoundError:
            return ""

    # -- main loop ------------------------------------------------------------

    def run(self) -> Dict:
        """Supervise to completion; returns the child's result dict (or a
        partial one with ``completed: False`` on wall-deadline expiry).
        Raises :class:`SupervisorError` when restarts are exhausted or the
        child reports a deterministic (non-transient) error."""
        import json

        cfg = self.config
        journal = self._prepare()
        deadline = (
            time.monotonic() + cfg.wall_deadline_sec
            if cfg.wall_deadline_sec is not None
            else None
        )
        journal.append(
            "supervisor_start",
            run_dir=self.run_dir,
            engine_kwargs=self._engine_kwargs,
            max_restarts=cfg.max_restarts,
        )
        attempts = cfg.max_restarts + 1
        try:
            for attempt in range(1, attempts + 1):
                outcome = self._run_attempt(journal, attempt, deadline)
                if outcome == "done":
                    result = self._load_result()
                    journal.append("supervisor_done", attempt=attempt,
                                   result=result)
                    return result
                if outcome == "wall_timeout":
                    partial = self._partial_from_journal()
                    journal.append("wall_timeout", attempt=attempt,
                                   partial=partial)
                    return partial
                if outcome == "fatal":
                    msg = self._read_error() or self._log_tail()
                    journal.append("give_up", attempt=attempt,
                                   reason="deterministic child error")
                    raise SupervisorError(
                        f"child failed deterministically: {msg[:2000]}"
                    )
                # outcome == "crash": maybe relax geometry, then restart.
                if attempt == attempts:
                    journal.append("give_up", attempt=attempt,
                                   reason="restart budget exhausted")
                    raise SupervisorError(
                        f"supervised run crashed {attempts} times; "
                        f"last child log tail:\n{self._log_tail()}"
                    )
                if cfg.geometry_backoff:
                    engine = (
                        self.spec.engine if self.spec is not None
                        else cfg.engine
                    )
                    relaxed = relax_geometry(self._engine_kwargs, engine)
                    if relaxed is not None and relaxed != self._engine_kwargs:
                        self._engine_kwargs = relaxed
                        # Atomic like every other run artifact: a torn
                        # relax.json would fail every later child's JSON
                        # parse and brick the run dir.
                        relax_path = os.path.join(self.run_dir, RELAX_FILE)
                        with open(
                            relax_path + ".tmp", "w", encoding="utf-8"
                        ) as fh:
                            json.dump(relaxed, fh)
                        os.replace(relax_path + ".tmp", relax_path)
                        journal.append("relax", engine_kwargs=relaxed)
                journal.append(
                    "restart",
                    attempt=attempt + 1,
                    from_checkpoint=os.path.exists(self.checkpoint_path),
                )
            raise AssertionError("unreachable")  # loop always returns/raises
        finally:
            self._kill_child()
            journal.close()

    def _run_attempt(self, journal: Journal, attempt: int,
                     deadline: Optional[float]) -> str:
        """One child lifetime; returns "done" | "crash" | "fatal" |
        "wall_timeout"."""
        cfg = self.config
        cmd = self._child_command()
        if cfg.inherit_output:
            stdout = stderr = None
        else:
            logfh = open(
                os.path.join(self.run_dir, CHILD_LOG_FILE), "ab"
            )
            stdout = stderr = logfh
        try:
            self._proc = subprocess.Popen(
                cmd, stdout=stdout, stderr=stderr, env=self._child_env(),
                cwd=self.run_dir,
            )
        finally:
            if not cfg.inherit_output:
                logfh.close()  # the child holds its own descriptor

        last_size = self._journal_activity()
        last_change = time.monotonic()
        while True:
            rc = self._proc.poll()
            if rc is not None:
                break
            now = time.monotonic()
            if deadline is not None and now >= deadline:
                self._kill_child()
                return "wall_timeout"
            size = self._journal_activity()
            if size != last_size:
                last_size = size
                last_change = now
            elif now - last_change > cfg.call_deadline_sec:
                journal.append(
                    "hang", attempt=attempt,
                    stalled_sec=round(now - last_change, 1),
                )
                self._kill_child()
                return "crash"
            time.sleep(cfg.poll_interval_sec)

        if (
            rc == 0
            or (rc == VIOLATION_RC and self._child_argv is not None)
        ) and (
            self._child_argv is not None
            or os.path.exists(self.result_path)
        ):
            # rc=VIOLATION_RC from a CLI child is a COMPLETED check whose
            # verdict was a violation — done, never a crash to retry.
            self.last_child_rc = rc
            return "done"
        if rc == CHILD_ERROR_RC:
            # A clean Python-level failure: transient tunnel errors are
            # retried like crashes, anything else is deterministic.  The
            # text-level analog of bench.py's exception-TYPE gate: a
            # marker only counts when the traceback is a JAX runtime
            # error, so a model error whose message merely mentions e.g.
            # "UNAVAILABLE" never burns the restart budget.
            err = self._read_error()
            is_jax_error = any(
                t in err
                for t in ("JaxRuntimeError", "XlaRuntimeError", "jaxlib")
            )
            if not (
                is_jax_error and any(m in err for m in TRANSIENT_MARKERS)
            ):
                journal.append("crash", attempt=attempt, returncode=rc,
                               deterministic=True, error=err[:500])
                return "fatal"
        if self._child_argv is not None and rc == 2:
            # CLI children exit 2 on usage errors — deterministic by
            # construction; retrying the identical argv cannot succeed.
            journal.append("crash", attempt=attempt, returncode=rc,
                           deterministic=True)
            return "fatal"
        journal.append("crash", attempt=attempt, returncode=rc)
        return "crash"

    def _load_result(self) -> Dict:
        import json

        if os.path.exists(self.result_path):
            with open(self.result_path, encoding="utf-8") as fh:
                return json.load(fh)
        # CLI mode: the child printed its own report; synthesize counts
        # from the journal for the caller.
        done = last_event(self.journal_path, "engine_done") or {}
        return {
            "completed": True,
            "unique_state_count": done.get("unique", 0),
            "state_count": done.get("states", 0),
            "max_depth": done.get("depth", 0),
        }


def journal_events(run_dir: str) -> List[Dict]:
    """All events of a supervised run directory's journal."""
    return read_journal(os.path.join(run_dir, JOURNAL_FILE))
