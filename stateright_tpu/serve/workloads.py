"""The service's workload registry.

Every model module under ``stateright_tpu.models`` that exposes a
module-level ``cli_spec()`` is a servable workload: the same spec that
drives its mini-binary CLI (cli.py) tells the service how to build the
model, which engines it supports, and the right-sized device knobs to
start from.  One definition per workload — the CLI, the bench, and the
service cannot drift apart on how e.g. ``paxos 3`` is constructed.

The registry is a fixed allowlist (not a blind ``importlib`` of
caller-supplied strings): a job submission names a workload, never a
module path.
"""

from __future__ import annotations

import importlib
from typing import List, Optional, Tuple

# Model modules with a cli_spec(); fixtures is the known-violating
# TrapCounter workload the service's own smoke tests submit, and
# grid_walk is the gang-batchable family (fleet/gang.py) — small,
# bound-parameterized, and exhaustive, so K differently-bounded
# submissions fold into one device dispatch.
SERVABLE = (
    "twophase",
    "paxos",
    "abd",
    "raft",
    "ping_pong",
    "lww_register",
    "single_copy_register",
    "increment",
    "fixtures",
    "grid_walk",
)


def workload_names() -> List[str]:
    return list(SERVABLE)


def cli_spec_for(workload: str):
    """The workload's CliSpec; ``ValueError`` on an unknown name."""
    if workload not in SERVABLE:
        raise ValueError(
            f"unknown workload {workload!r} "
            f"(one of: {', '.join(SERVABLE)})"
        )
    module = importlib.import_module(f"..models.{workload}", __package__)
    return module.cli_spec()


def build_model(
    workload: str, n: Optional[int] = None, network: Optional[str] = None
) -> Tuple[object, object, int]:
    """Build the workload's model: ``(model, cli_spec, resolved_n)``.
    ``n`` defaults to the spec's CLI default; ``network`` (a name from
    the actor network registry) is resolved exactly like the CLI's
    NETWORK positional — an unknown name raises, never a silent
    default."""
    from ..actor.network import Network

    spec = cli_spec_for(workload)
    resolved_n = spec.default_n if n is None else int(n)
    if spec.default_network is None:
        if network is not None:
            raise ValueError(
                f"workload {workload!r} takes no network parameter"
            )
        return spec.build(resolved_n), spec, resolved_n
    net = Network.from_name(network or spec.default_network)
    return spec.build(resolved_n, net), spec, resolved_n


def ensemble_capable(workload: str) -> bool:
    """Whether the workload supports chaos-ensemble sweeps
    (``ensemble/engine.py``): its CliSpec opted in — today that means
    the model has a compiled fault hook the ensemble can search over.
    Unknown names raise, exactly like ``cli_spec_for``."""
    return bool(getattr(cli_spec_for(workload), "ensemble", False))


def ensemble_winning_seeds(
    workload: str,
    *,
    members: int = 256,
    seed: int = 0,
    chaos=None,
    steps: int = 48,
    fault: Optional[str] = None,
    limit: int = 4,
) -> List[int]:
    """A pre-portfolio chaos sweep: run one ensemble dispatch and hand
    back up to ``limit`` failure-finding member seeds, ready to fold
    into ``portfolio.diversify(..., winning_seeds=...)``.  Returns
    ``[]`` for non-ensemble workloads instead of raising, so the
    scheduler can call it unconditionally."""
    if not ensemble_capable(workload):
        return []
    from ..ensemble import run_ensemble

    result = run_ensemble(
        members=members, seed=seed, chaos=chaos, steps=steps,
        fault=fault, shrink=False, replay=False,
    )
    return [f["seed"] for f in result.failing[:limit]]


def workload_label(workload: str, n: int, network: Optional[str],
                   symmetry: bool = False) -> str:
    """The knob-cache label for one served workload configuration
    (runtime/knob_cache.knob_key adds device + engine identity)."""
    parts = [f"serve:{workload}", str(n)]
    if network:
        parts.append(network)
    if symmetry:
        parts.append("sym")
    return ":".join(parts)
