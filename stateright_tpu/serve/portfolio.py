"""Portfolio (swarm) mode: fan one job into N diversified configs.

Straight out of Holzmann-Joshi-Groce's *Swarm Verification Techniques*
(PAPERS.md): instead of one monolithic search, run many cheap,
diversified, restartable search configurations against the same model —
different geometries, symmetry on/off, and seeded Monte-Carlo walkers
beside the exhaustive anchor — and let the first counterexample win.
The mapping onto this package is direct: the diversification axes are
exactly the engine knobs the knob cache already persists, the
"restartable" requirement is the engines' bounded/stoppable runs, and
the shared trail is the service journal every member appends to.

Semantics (pinned by tests/test_serve.py):

- ``diversify`` is a pure function of ``(size, seed, base config)`` —
  the same portfolio seed always yields the same member set.
- Member 0 is always the UNMODIFIED exhaustive config: whatever the
  swarm finds early, completeness is anchored by construction.
- First failure-classified discovery wins; every other member is
  cancelled — running members via the engines' cooperative
  ``request_stop``, queued members without ever starting.
- With ``parallelism=1`` (the default: one mesh, one device job at a
  time) members run in index order, so the winning member — and its
  counterexample — is deterministic given the seed set.
- The winner (member config + discovery) is journaled
  (``portfolio_winner``) and folded back into the knob cache by the
  scheduler, so the next job on this workload starts from the config
  that actually found the bug.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional

# Member terminal statuses.
WON = "won"
COMPLETED = "completed"
STOPPED = "stopped"  # was running when another member won
CANCELLED = "cancelled"  # never started: a winner existed first
MEMBER_FAILED = "failed"

# Simulation members must terminate on clean models; this caps their
# walk when the job itself sets no target.
_SIM_DEFAULT_TARGET = 200_000


@dataclass
class MemberConfig:
    """One diversified search configuration."""

    index: int
    kind: str  # "exhaustive" | "simulation"
    engine: str  # tpu | bfs | dfs | tpu_simulation | simulation
    engine_kwargs: dict = field(default_factory=dict)
    symmetry: bool = False
    seed: int = 0
    target_state_count: Optional[int] = None

    def describe(self) -> dict:
        return {
            "index": self.index,
            "kind": self.kind,
            "engine": self.engine,
            "engine_kwargs": dict(self.engine_kwargs),
            "symmetry": self.symmetry,
            "seed": self.seed,
        }


def diversify(
    size: int,
    seed: int,
    base_engine: str,
    base_kwargs: dict,
    symmetry_capable: bool = False,
    include_simulation: bool = True,
    winning_seeds: Optional[List[int]] = None,
) -> List[MemberConfig]:
    """The deterministic member set for one portfolio.

    Axes (Swarm §3's "search diversification" menu, mapped to this
    package): dedup/probe geometry, frontier chunk size, device symmetry
    reduction on/off, and seeded simulation walkers vs exhaustive
    search.  Everything derives from ``random.Random(seed)`` — same
    seed, same portfolio.

    ``winning_seeds`` folds failure-finding seeds from a chaos-ensemble
    sweep (``ensemble/engine.py``) into the swarm: the first simulation
    members take the listed seeds (masked to the 31-bit walker-seed
    range) in order instead of their derived draws.  Determinism is
    preserved — the result is still a pure function of the arguments —
    and the derived-seed stream still advances for every simulation
    member, so members beyond the list are identical to the
    no-``winning_seeds`` portfolio."""
    if size < 2:
        raise ValueError("portfolio size must be >= 2")
    rng = random.Random(seed)
    device_engine = base_engine in ("tpu", "sharded")
    sim_engine = "tpu_simulation" if device_engine else "simulation"
    won = [int(s) & ((1 << 31) - 1) for s in (winning_seeds or [])]
    members = [
        MemberConfig(
            index=0, kind="exhaustive", engine=base_engine,
            engine_kwargs=dict(base_kwargs),
        )
    ]
    for i in range(1, size):
        if include_simulation and i % 3 == 2:
            # Every third member is a Monte-Carlo walker with its own
            # derived seed — the cheap, restartable random searches of
            # the swarm recipe.  Ensemble-found winning seeds preempt
            # the derived draws (which are still consumed, keeping the
            # rest of the stream aligned).
            drawn = rng.randrange(1 << 31)
            members.append(
                MemberConfig(
                    index=i, kind="simulation", engine=sim_engine,
                    seed=won.pop(0) if won else drawn,
                    target_state_count=_SIM_DEFAULT_TARGET,
                )
            )
            continue
        kwargs = dict(base_kwargs)
        if device_engine:
            kwargs["dedup_factor"] = rng.choice([1, 2, 4, 8])
            mf = int(kwargs.get("max_frontier", 1 << 15))
            shift = rng.choice([-1, 0, 1])
            kwargs["max_frontier"] = max(
                64, mf >> 1 if shift < 0 else mf << shift
            )
        members.append(
            MemberConfig(
                index=i, kind="exhaustive", engine=base_engine,
                engine_kwargs=kwargs,
                symmetry=bool(symmetry_capable and rng.random() < 0.5),
            )
        )
    return members


def run_portfolio(
    members: List[MemberConfig],
    spawn_member: Callable[[MemberConfig], object],
    cancel_event: threading.Event,
    journal=None,
    parallelism: int = 1,
    poll_interval: float = 0.02,
) -> dict:
    """Race the members; first failure-classified discovery wins.

    ``spawn_member(member)`` builds and spawns a checker for one config
    (the scheduler owns model construction).  Returns the portfolio
    result dict; raises nothing member-related — a member that errors is
    recorded as ``failed`` and the race continues (one bad geometry must
    not sink the swarm)."""
    stop = threading.Event()  # a winner exists (or the job was cancelled)
    lock = threading.Lock()
    state = {"winner": None}
    entries: List[Optional[dict]] = [None] * len(members)
    next_index = {"i": 0}

    def log(event: str, **fields) -> None:
        if journal is not None:
            journal.append(event, **fields)

    def claim() -> Optional[MemberConfig]:
        with lock:
            if stop.is_set() or cancel_event.is_set():
                return None
            i = next_index["i"]
            if i >= len(members):
                return None
            next_index["i"] = i + 1
            return members[i]

    def run_one(member: MemberConfig) -> None:
        log("portfolio_member_start", member=member.index,
            **{"config": member.describe()})
        t0 = time.monotonic()
        entry = {"status": MEMBER_FAILED, **member.describe()}
        entries[member.index] = entry
        try:
            checker = spawn_member(member)
        except Exception as exc:  # bad geometry/config: race continues
            entry["error"] = f"{type(exc).__name__}: {exc}"
            log("portfolio_member_failed", member=member.index,
                error=entry["error"])
            return
        stopped_early = False
        while not checker.is_done():
            if stop.is_set() or cancel_event.is_set():
                checker.request_stop()
                stopped_early = True
            time.sleep(poll_interval)
        try:
            checker.join()
        except Exception as exc:
            entry["error"] = f"{type(exc).__name__}: {exc}"
            log("portfolio_member_failed", member=member.index,
                error=entry["error"])
            return
        summary = checker_summary(checker)
        entry.update(
            unique_state_count=summary["unique_state_count"],
            state_count=summary["state_count"],
            max_depth=summary["max_depth"],
            violation=summary["violation"],
            sec=round(time.monotonic() - t0, 3),
        )
        entry["checker"] = checker
        entry["summary"] = summary
        with lock:
            if (
                summary["violation"] is not None
                and state["winner"] is None
                and not cancel_event.is_set()
            ):
                state["winner"] = member.index
                entry["status"] = WON
                stop.set()
            elif stopped_early:
                entry["status"] = STOPPED
            else:
                entry["status"] = COMPLETED
        log("portfolio_member_done", member=member.index,
            status=entry["status"], unique=entry["unique_state_count"],
            violation=entry["violation"])

    def worker() -> None:
        while True:
            member = claim()
            if member is None:
                return
            run_one(member)

    parallelism = max(1, min(int(parallelism), len(members)))
    if parallelism == 1:
        worker()  # in-line: index order, fully deterministic
    else:
        threads = [
            threading.Thread(target=worker, daemon=True,
                             name=f"portfolio-{i}")
            for i in range(parallelism)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    for member in members:  # never-started members were cancelled
        if entries[member.index] is None:
            entries[member.index] = {
                "status": CANCELLED, **member.describe(),
            }
            log("portfolio_member_cancelled", member=member.index)

    winner_idx = state["winner"]
    result = {
        "members": [
            {k: v for k, v in e.items() if k not in ("checker", "summary")}
            for e in entries
        ],
        "winner": None,
    }
    if winner_idx is not None:
        win = entries[winner_idx]
        result["winner"] = {
            "member": winner_idx,
            "config": members[winner_idx].describe(),
            "violation": win["violation"],
            "discovery": win["summary"]["discoveries"].get(win["violation"]),
        }
        log("portfolio_winner", **result["winner"])
    return {
        "portfolio": result,
        "entries": entries,  # scheduler-internal (checkers, summaries)
        "winner_index": winner_idx,
    }


def checker_summary(checker) -> dict:
    """The common result shape for one finished checker: counts, per-
    property verdicts, encoded discoveries, and the first failure-
    classified discovery (in the model's property order — the
    deterministic 'violation' the portfolio race keys on).  The
    verdict/violation computation is the shared
    core/checker.property_verdicts — the incremental store's records
    (incr/store.py) use the same one."""
    from ..core.checker import property_verdicts

    model = checker.model()
    discoveries = checker.discoveries()
    props, violation = property_verdicts(checker)
    return {
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "properties": props,
        "discoveries": {
            name: {
                "classification": checker.discovery_classification(name),
                "fingerprints": path.encode(model),
                "actions": repr(path.into_actions()),
            }
            for name, path in discoveries.items()
        },
        "violation": violation,
    }
