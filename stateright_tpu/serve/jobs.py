"""Job store for the persistent checking service.

A *job* is one check request against the service's mesh: a registered
workload (serve/workloads.py) plus engine/config overrides, optionally
fanned into a diversified portfolio (serve/portfolio.py).  Jobs move
through a fixed lifecycle::

    queued -> running -> done | failed | cancelled

``cancelled`` is reachable from both ``queued`` (the job never starts)
and ``running`` (the scheduler forwards the cancel to the engine's
cooperative ``request_stop``, core/checker.py).  Every transition is
appended to the service journal (runtime/journal.py) as a ``job_*``
event, so the journal is the durable record of what the service did —
the swarm-verification requirement that restartable work leave an
auditable trail (PAPERS.md, Holzmann-Joshi-Groce).

The store itself is deliberately in-memory: the service owns one
process-lifetime mesh, and a job's expensive artifacts (compiled
programs, tuned knobs) persist in the program cache and knob cache, not
here.  docs/SERVING.md documents the lifecycle and the JSON shapes.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Dict, List, Optional

# Lifecycle states.
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"


def worker_id() -> str:
    """This process's worker identity (``pid@host``) — stamped on every
    ``job_*`` / ``fleet_*`` journal event so a multi-worker journal can
    attribute each lifecycle step to the process that performed it
    (without it, a failed job in a merged fleet journal names no
    culprit).  Computed per call: a forked worker must not inherit its
    parent's pid."""
    return f"{os.getpid()}@{socket.gethostname()}"

_ENGINES = (
    "tpu", "tiered", "sharded", "tiered-sharded", "bfs", "dfs",
    "simulation", "tpu_simulation",
)
_FINISH_WHEN = ("all", "any", "any_failures", "all_failures")


class JobCancelled(Exception):
    """Raised inside a job runner when its cancel event fired; carries
    the partial counts collected before the engine wound down."""

    def __init__(self, partial: Optional[dict] = None):
        super().__init__("job cancelled")
        self.partial = partial or {}


class JobSpec:
    """A validated check request (the ``POST /jobs`` body).

    Validation is loud and total: an unknown field, engine, or
    finish_when is a ``ValueError`` at submit time, never a dead job
    discovered minutes later on the worker thread.
    """

    FIELDS = (
        "workload", "n", "network", "engine", "engine_kwargs", "symmetry",
        "target_max_depth", "target_state_count", "timeout", "finish_when",
        "seed", "threads", "priority", "portfolio", "use_knob_cache",
        "store",
    )

    def __init__(
        self,
        workload: str,
        n: Optional[int] = None,
        network: Optional[str] = None,
        engine: str = "tpu",
        engine_kwargs: Optional[dict] = None,
        symmetry: bool = False,
        target_max_depth: Optional[int] = None,
        target_state_count: Optional[int] = None,
        timeout: Optional[float] = None,
        finish_when: Optional[str] = None,
        seed: int = 0,
        threads: Optional[int] = None,
        priority: int = 0,
        portfolio: Optional[dict] = None,
        use_knob_cache: bool = True,
        store: bool = False,
    ):
        if not workload or not isinstance(workload, str):
            raise ValueError("workload must be a nonempty string")
        if engine not in _ENGINES:
            raise ValueError(
                f"unknown engine {engine!r} (one of {', '.join(_ENGINES)})"
            )
        if finish_when is not None and finish_when not in _FINISH_WHEN:
            raise ValueError(
                f"unknown finish_when {finish_when!r} "
                f"(one of {', '.join(_FINISH_WHEN)})"
            )
        if portfolio is not None:
            if not isinstance(portfolio, dict):
                raise ValueError("portfolio must be an object")
            unknown = set(portfolio) - {
                "size", "seed", "parallelism", "simulation",
            }
            if unknown:
                raise ValueError(
                    f"unknown portfolio field(s): {', '.join(sorted(unknown))}"
                )
            if int(portfolio.get("size", 0)) < 2:
                raise ValueError("portfolio.size must be >= 2")
        if engine_kwargs is not None and not isinstance(engine_kwargs, dict):
            raise ValueError("engine_kwargs must be an object")
        if engine_kwargs and engine in ("bfs", "dfs", "simulation"):
            # The host engines take no spawn kwargs; silently dropping
            # them would let a misplaced knob pass unreported.
            raise ValueError(
                f"engine {engine!r} takes no engine_kwargs "
                "(host-engine tuning is the threads field)"
            )
        if store:
            # The verification store journals single-chip wavefront
            # runs (docs/INCREMENTAL.md): a portfolio's diversified
            # members explore property-dependently, and other engines
            # don't produce the store's snapshot format — silently
            # running them un-stored would make `store: true` a lie.
            if engine != "tpu":
                raise ValueError(
                    "store requires engine 'tpu' (the verification "
                    "store journals single-chip wavefront runs)"
                )
            if portfolio is not None:
                raise ValueError(
                    "store does not combine with portfolio jobs"
                )
        self.workload = workload
        self.n = None if n is None else int(n)
        self.network = network
        self.engine = engine
        self.engine_kwargs = dict(engine_kwargs or {})
        self.symmetry = bool(symmetry)
        self.target_max_depth = (
            None if target_max_depth is None else int(target_max_depth)
        )
        self.target_state_count = (
            None if target_state_count is None else int(target_state_count)
        )
        self.timeout = None if timeout is None else float(timeout)
        self.finish_when = finish_when
        self.seed = int(seed)
        self.threads = None if threads is None else int(threads)
        self.priority = int(priority)
        self.portfolio = None if portfolio is None else dict(portfolio)
        self.use_knob_cache = bool(use_knob_cache)
        self.store = bool(store)

    @classmethod
    def from_dict(cls, data: dict) -> "JobSpec":
        if not isinstance(data, dict):
            raise ValueError("job spec must be a JSON object")
        unknown = set(data) - set(cls.FIELDS)
        if unknown:
            raise ValueError(
                f"unknown job field(s): {', '.join(sorted(unknown))}"
            )
        if "workload" not in data:
            raise ValueError("job spec requires a workload")
        return cls(**data)

    def to_dict(self) -> dict:
        return {k: getattr(self, k) for k in self.FIELDS}

    def finish_when_policy(self):
        from ..core.has_discoveries import HasDiscoveries

        return {
            None: None,
            "all": HasDiscoveries.ALL,
            "any": HasDiscoveries.ANY,
            "any_failures": HasDiscoveries.ANY_FAILURES,
            "all_failures": HasDiscoveries.ALL_FAILURES,
        }[self.finish_when]


class Job:
    """One submitted check and its lifecycle state.  The completed
    checker object is retained (``job.checker``) so the Explorer can be
    attached to it afterwards (serve/server.py ``/jobs/<id>/explore``)."""

    def __init__(self, job_id: str, spec: JobSpec):
        self.id = job_id
        self.spec = spec
        self.state = QUEUED
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.checker = None  # retained after completion for the Explorer
        self.explorer_address = None
        self.cancel = threading.Event()
        self._finished = threading.Event()

    def snapshot(self) -> dict:
        """JSON view served by ``GET /jobs/<id>``.  While the job is
        RUNNING and its checker is attached, a ``vitals`` key carries
        the engine's live counters (``Checker.metrics()`` is documented
        mid-run-safe — it reads already-synced scalars, never the
        device), so a client watching one job no longer needs the
        aggregated ``/.metrics`` to see whether ITS check is moving."""
        out = {
            "id": self.id,
            "state": self.state,
            "spec": self.spec.to_dict(),
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "result": self.result,
            "error": self.error,
        }
        if self.state == RUNNING and self.checker is not None:
            from ..obs.metrics import vitals_view

            vitals = vitals_view(self.checker)
            if vitals is not None:
                out["vitals"] = vitals
        if self.explorer_address is not None:
            out["explorer_address"] = list(self.explorer_address)
        return out

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the job reaches a terminal state."""
        return self._finished.wait(timeout)

    @property
    def terminal(self) -> bool:
        return self.state in (DONE, FAILED, CANCELLED)


class JobStore:
    """Thread-safe id -> Job map with journaled state transitions."""

    def __init__(self, journal=None):
        self._lock = threading.Lock()
        self._jobs: Dict[str, Job] = {}
        self._seq = 0
        self._journal = journal

    def create(self, spec: JobSpec) -> Job:
        with self._lock:
            self._seq += 1
            job = Job(f"job-{self._seq:06d}", spec)
            self._jobs[job.id] = job
        self._log("job_submitted", job, workload=spec.workload,
                  engine=spec.engine, priority=spec.priority,
                  portfolio=bool(spec.portfolio))
        return job

    def get(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def list(self) -> List[Job]:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda j: j.id)

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in (QUEUED, RUNNING, DONE, FAILED, CANCELLED)}
        with self._lock:
            for job in self._jobs.values():
                out[job.state] += 1
        return out

    def try_start(self, job: Job) -> bool:
        """Atomically move a queued job to running; False when a cancel
        (or anything else) got there first — the worker must drop it.
        Without this compare-and-set, a cancel landing between the
        worker's pop and its RUNNING transition would be silently
        overwritten and the job would run cancelled."""
        with self._lock:
            if job.state != QUEUED or job.cancel.is_set():
                return False
            job.state = RUNNING
            job.started_at = time.time()
        self._log("job_running", job)
        return True

    def try_cancel_queued(self, job: Job) -> bool:
        """The cancel-side compare-and-set paired with :meth:`try_start`:
        atomically move a still-queued job to cancelled.  False when the
        job already left QUEUED — the caller then relies on the cancel
        EVENT, which the runner's poll loop forwards to the engine (one
        terminal transition either way, never two)."""
        with self._lock:
            if job.state != QUEUED:
                return False
            job.state = CANCELLED
            job.finished_at = time.time()
        self._log("job_cancelled", job, reason="while queued")
        job._finished.set()
        return True

    def transition(self, job: Job, state: str, **fields) -> None:
        """Move ``job`` to ``state``, journal it, and release waiters on
        terminal states.  Transitions are scheduler-serialized per job;
        the lock here only guards the map's consistency view."""
        with self._lock:
            job.state = state
            if state == RUNNING:
                job.started_at = time.time()
            if state in (DONE, FAILED, CANCELLED):
                job.finished_at = time.time()
        self._log(f"job_{state}", job, **fields)
        if job.terminal:
            job._finished.set()

    def _log(self, event: str, job: Job, **fields) -> None:
        if self._journal is not None:
            self._journal.append(
                event, job=job.id, worker=worker_id(), **fields
            )
