"""HTTP surface of the checking service (sibling of explorer/server.py).

Endpoints (JSON everywhere; full shapes in docs/SERVING.md):

- ``POST /jobs`` — submit a :class:`~stateright_tpu.serve.jobs.JobSpec`
  body; returns ``{"id", "state"}`` immediately (the check runs on the
  scheduler's workers).
- ``GET /jobs`` — every job's snapshot, id-ordered.
- ``GET /jobs/{id}`` — one job's snapshot (state, spec, result, error).
- ``GET /jobs/{id}/result`` — blocks up to ``?wait=SECONDS`` (default 0)
  for a terminal state, then returns the snapshot; the natural client
  poll loop collapses to one request.
- ``POST /jobs/{id}/cancel`` — cancel queued or running; returns the
  snapshot (409 when already terminal).
- ``POST /jobs/{id}/explore`` — attach the interactive Explorer to a
  COMPLETED job's retained checker (explorer/server.serve_checker) on an
  ephemeral port; returns its address.
- ``GET /.metrics`` — the aggregated service view: job counts by state,
  scheduler counters (``knob_cache_hits``, ``jobs_completed``, ...), and
  the process-global compiled-program cache counters
  (``program_cache_hits``) that evidence warm-start reuse.
- ``GET /.status`` — uptime, worker count, job counts, workload names.

The server is a ThreadingHTTPServer like the Explorer's: requests are
cheap metadata operations; all checking happens on the scheduler's
workers against the one mesh this process owns.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from ..obs.metrics import GLOBAL
from ..runtime.journal import as_journal
from .jobs import DONE, JobSpec, JobStore
from .scheduler import Scheduler
from .workloads import workload_names


class CheckService:
    """Composition root: store + journal + scheduler, one per mesh."""

    def __init__(
        self,
        journal=None,
        knob_cache_dir: Optional[str] = None,
        workers: int = 1,
        retain_checkers: int = 4,
        store_dir: Optional[str] = None,
    ):
        self.journal = as_journal(journal)
        self.store = JobStore(journal=self.journal)
        self.scheduler = Scheduler(
            self.store,
            journal=self.journal,
            knob_cache_dir=knob_cache_dir,
            workers=workers,
            retain_checkers=retain_checkers,
            store_dir=store_dir,
        )
        self.store_dir = store_dir
        self.started_at = time.time()
        self.workers = max(1, workers)
        self.http_server = None
        self.address = None
        if self.journal is not None:
            self.journal.append(
                "service_start", workers=self.workers,
                knob_cache_dir=knob_cache_dir,
                store_dir=store_dir,
            )

    def submit(self, spec) -> "object":
        if isinstance(spec, dict):
            spec = JobSpec.from_dict(spec)
        if spec.store and self.store_dir is None:
            # Submit-time, like every other invalid spec (HTTP 400) —
            # never a job that queues only to fail on a worker.
            raise ValueError(
                "store: true requires a service started with a "
                "verification store (serve --store-dir DIR; "
                "docs/INCREMENTAL.md)"
            )
        return self.scheduler.submit(spec)

    def cancel(self, job_id: str) -> bool:
        return self.scheduler.cancel(job_id)

    def get(self, job_id: str):
        return self.store.get(job_id)

    def metrics(self) -> dict:
        out = {
            "service": "stateright-tpu-serve",
            "uptime_sec": round(time.time() - self.started_at, 1),
            "workers": self.workers,
            "jobs": self.store.counts(),
        }
        out.update(self.scheduler.metrics.snapshot())
        # The process-global counters: compiled-program cache hits are
        # the direct evidence that a repeat submission reused the first
        # run's programs instead of recompiling.
        out.update(GLOBAL.snapshot())
        # Job SLO surface (docs/SERVING.md "Job SLO metrics"): the
        # scheduler's span histograms plus the derived operator gauges —
        # queue p95 straight off the wait histogram, and the
        # warm-vs-cold start ratio off the knob-cache counters.
        # Process-global histograms ride along too — ``compile_sec``
        # (wave_common.cached_program's first-call compile timings) is
        # the distribution behind the warm-start evidence.
        hists = dict(GLOBAL.snapshot_histograms())
        hists.update(self.scheduler.metrics.snapshot_histograms())
        if hists:
            out["histograms"] = hists
            qw = hists.get("job_queue_wait_sec")
            if qw:
                out["queue_wait_p95_sec"] = qw["p95"]
        starts = out.get("knob_cache_hits", 0) + out.get(
            "knob_cache_misses", 0
        )
        if starts:
            out["warm_start_ratio"] = round(
                out.get("knob_cache_hits", 0) / starts, 4
            )
        return out

    def status(self) -> dict:
        return {
            "service": "stateright-tpu-serve",
            "uptime_sec": round(time.time() - self.started_at, 1),
            "workers": self.workers,
            "jobs": self.store.counts(),
            "workloads": workload_names(),
            "store_dir": self.store_dir,
        }

    def explore(self, job, port: int = 0):
        """Attach the Explorer to a completed job's retained checker;
        returns the (host, port) it serves on."""
        if job.state != DONE or job.checker is None:
            raise ValueError(
                f"job {job.id} has no attached checker (state "
                f"{job.state}; completed checkers past the retention "
                "cap are released — resubmit the job to explore it)"
            )
        if job.explorer_address is not None:
            return job.explorer_address
        from ..explorer.server import serve_checker

        serve_checker(job.checker, ("127.0.0.1", port), block=False)
        job.explorer_address = job.checker.explorer_address
        if self.journal is not None:
            self.journal.append(
                "explorer_attached", job=job.id,
                address=list(job.explorer_address),
            )
        return job.explorer_address

    def shutdown(self) -> None:
        self.scheduler.shutdown()
        if self.http_server is not None:
            self.http_server.shutdown()
        if self.journal is not None:
            self.journal.append("service_stop")
            self.journal.close()


def serve(
    address,
    block: bool = True,
    journal=None,
    knob_cache_dir: Optional[str] = None,
    workers: int = 1,
    retain_checkers: int = 4,
    store_dir: Optional[str] = None,
    fleet_dir: Optional[str] = None,
) -> CheckService:
    """Start the checking service on ``address`` ((host, port); port 0
    binds an ephemeral one).  ``block=False`` serves on a background
    thread and returns the service immediately (``service.address``
    carries the bound port).  ``store_dir`` enables the persistent
    verification store for ``store: true`` jobs (docs/INCREMENTAL.md).

    ``fleet_dir`` swaps the backend: the HTTP surface is unchanged, but
    jobs are appended to the durable fleet store at that directory and
    run by separately-launched ``fleet-worker`` processes instead of
    this process's scheduler threads (fleet/, docs/SERVING.md "Fleet
    mode").  The other backend knobs don't apply in that mode."""
    if fleet_dir is not None:
        from ..fleet.service import FleetService

        service = FleetService(fleet_dir)
    else:
        service = CheckService(
            journal=journal, knob_cache_dir=knob_cache_dir,
            workers=workers, retain_checkers=retain_checkers,
            store_dir=store_dir,
        )

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            pass

        def _send(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _error(self, code: int, message: str) -> None:
            self._send(code, {"error": message})

        def _body(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            if length == 0:
                return {}
            raw = self.rfile.read(length)
            data = json.loads(raw)
            if not isinstance(data, dict):
                raise ValueError("request body must be a JSON object")
            return data

        def _job_or_404(self, job_id: str):
            job = service.get(job_id)
            if job is None:
                self._error(404, f"unknown job {job_id!r}")
            return job

        def _query(self) -> dict:
            from urllib.parse import parse_qsl, urlsplit

            return dict(parse_qsl(urlsplit(self.path).query))

        def do_GET(self) -> None:
            path = self.path.split("?", 1)[0].rstrip("/")
            try:
                if path == "/.metrics":
                    # JSON by default; ``?format=prometheus`` (or a
                    # scraper's Accept header) selects the text
                    # exposition so the service plugs into standard
                    # scrapers (obs/prometheus.py, docs/SERVING.md).
                    from ..obs.prometheus import (
                        CONTENT_TYPE, render_prometheus, wants_prometheus,
                    )

                    if wants_prometheus(
                        self._query(), self.headers.get("Accept")
                    ):
                        body = render_prometheus(
                            service.metrics()
                        ).encode()
                        self.send_response(200)
                        self.send_header("Content-Type", CONTENT_TYPE)
                        self.send_header(
                            "Content-Length", str(len(body))
                        )
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._send(200, service.metrics())
                elif path in ("", "/.status"):
                    self._send(200, service.status())
                elif path == "/jobs":
                    self._send(
                        200,
                        [j.snapshot() for j in service.store.list()],
                    )
                elif path.startswith("/jobs/"):
                    parts = path.split("/")[2:]
                    job = self._job_or_404(parts[0])
                    if job is None:
                        return
                    if len(parts) == 1:
                        self._send(200, job.snapshot())
                    elif parts[1] == "result":
                        wait = float(self._query().get("wait", 0) or 0)
                        if wait > 0:
                            job.wait(min(wait, 600.0))
                        self._send(200, job.snapshot())
                    else:
                        self._error(404, f"unknown endpoint {path!r}")
                else:
                    self._error(404, f"unknown endpoint {path!r}")
            except Exception as e:  # surface, don't reset the connection
                self._error(500, f"{type(e).__name__}: {e}")

        def do_POST(self) -> None:
            path = self.path.split("?", 1)[0].rstrip("/")
            try:
                if path == "/jobs":
                    try:
                        job = service.submit(self._body())
                    except (ValueError, json.JSONDecodeError) as e:
                        return self._error(400, str(e))
                    self._send(
                        202, {"id": job.id, "state": job.state}
                    )
                elif path.startswith("/jobs/") and path.endswith("/cancel"):
                    job = self._job_or_404(path.split("/")[2])
                    if job is None:
                        return
                    if not service.cancel(job.id):
                        return self._error(
                            409, f"job {job.id} is already {job.state}"
                        )
                    self._send(200, job.snapshot())
                elif path.startswith("/jobs/") and path.endswith("/explore"):
                    job = self._job_or_404(path.split("/")[2])
                    if job is None:
                        return
                    try:
                        addr = service.explore(
                            job, int(self._body().get("port", 0))
                        )
                    except ValueError as e:
                        return self._error(409, str(e))
                    self._send(
                        200, {"id": job.id, "explorer_address": list(addr)}
                    )
                else:
                    self._error(404, f"unknown endpoint {path!r}")
            except Exception as e:
                self._error(500, f"{type(e).__name__}: {e}")

    server = ThreadingHTTPServer(tuple(address), Handler)
    service.http_server = server
    service.address = server.server_address
    if block:  # serve on the calling thread (reference Explorer behavior)
        try:
            server.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            service.shutdown()
    else:
        t = threading.Thread(
            target=server.serve_forever, daemon=True, name="serve-http"
        )
        t.start()
    return service
