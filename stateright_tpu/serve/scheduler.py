"""Job scheduler: priority queue + worker pool over the engines.

One scheduler owns one mesh.  Jobs run IN-PROCESS on worker threads
(default one — a single device runs one wavefront at a time), which is
what makes the warm-start story real: the engines' compiled-program
cache (parallel/wave_common.cached_program) and the persisted knob
cache (runtime/knob_cache.py) are process-level, so the second
submission of a workload skips both the auto-tune discovery and the
compile that made the first one slow — the 126 s -> ~0 warmup
collapse the ROADMAP names, asserted by the ``knob_cache_hits`` /
``program_cache_hits`` counters in the aggregated metrics
(docs/SERVING.md).

Cancellation is cooperative end to end: a queued job is simply marked
cancelled; a running job's cancel event is forwarded to the engine's
``request_stop`` (core/checker.py), which winds the run down like a
deadline — partial counts stand and are reported with the cancelled
job.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from typing import List, Optional

from ..obs.metrics import GLOBAL, MetricsRegistry
from ..runtime.knob_cache import (
    drop_knobs, knob_key, load_knobs, store_knobs,
)
from .jobs import (
    CANCELLED, DONE, FAILED, QUEUED, RUNNING,
    Job, JobCancelled, JobSpec, JobStore, worker_id,
)
from .portfolio import checker_summary, diversify, run_portfolio
from .workloads import build_model, workload_label

_SIM_ENGINES = ("simulation", "tpu_simulation")
# A simulation job with no stopping condition would walk forever; the
# service bounds it like the CLI's check-simulation does.
_SIM_DEFAULT_TARGET = 1_000_000


# -- builder assembly (module-level: shared by the in-process scheduler
# and the fleet worker, fleet/worker.py — one definition of how a
# JobSpec maps onto the CheckerBuilder, so the two run paths cannot
# drift) -----------------------------------------------------------------------


def make_builder(spec: JobSpec, engine: str, symmetry: bool):
    """(model, cli_spec, builder, resolved_n) for one run — the one
    place job fields map onto the CheckerBuilder, shared by single
    runs, every portfolio member, and fleet workers."""
    model, cli, n = build_model(spec.workload, spec.n, spec.network)
    builder = model.checker().threads(
        spec.threads or (os.cpu_count() or 1)
    )
    device = engine in (
        "tpu", "tiered", "sharded", "tiered-sharded", "tpu_simulation",
    )
    depth = spec.target_max_depth
    if depth is None:
        depth = (
            cli.tpu_target_max_depth
            if device and cli.tpu_target_max_depth is not None
            else cli.target_max_depth
        )
    if depth is not None:
        builder = builder.target_max_depth(depth)
    if spec.target_state_count is not None:
        builder = builder.target_state_count(spec.target_state_count)
    if spec.timeout is not None:
        builder = builder.timeout(spec.timeout)
    policy = spec.finish_when_policy()
    if policy is not None:
        builder = builder.finish_when(policy)
    if symmetry:
        builder = builder.symmetry()
    return model, cli, builder, n


def spawn_engine(builder, spec: JobSpec, engine: str,
                 engine_kwargs: dict, seed: int):
    if engine == "tpu":
        return builder.spawn_tpu(**engine_kwargs)
    if engine == "tiered":
        return builder.spawn_tpu_tiered(**engine_kwargs)
    if engine == "sharded":
        return builder.spawn_tpu_sharded(**engine_kwargs)
    if engine == "tiered-sharded":
        return builder.spawn_tpu_tiered_sharded(**engine_kwargs)
    if engine == "bfs":
        return builder.spawn_bfs()
    if engine == "dfs":
        return builder.spawn_dfs()
    if engine == "tpu_simulation":
        return builder.spawn_tpu_simulation(seed, **engine_kwargs)
    if engine == "simulation":
        return builder.spawn_simulation(seed)
    raise ValueError(engine)


def bound_simulation(builder, spec: JobSpec) -> None:
    """Simulation engines only stop on a policy/target/timeout; give
    unbounded specs the service default instead of an immortal job."""
    from ..core.has_discoveries import HasDiscoveries

    if spec.finish_when is None:
        builder.finish_when(HasDiscoveries.ANY_FAILURES)
    if spec.target_state_count is None and spec.timeout is None:
        builder.target_state_count(_SIM_DEFAULT_TARGET)


def knob_engine_tag(engine: str) -> str:
    """The knob_key engine tag for a job's engine: sharded and
    tiered entries live under their own tags (their knob sets and
    sizing rules differ from the single-chip engine's); everything
    else uses the single-chip default (simulation winners only ever
    land under the portfolio-only label, so the tag is inert for
    them)."""
    from ..runtime.knob_cache import (
        SHARDED_ENGINE, SINGLE_CHIP_ENGINE, TIERED_ENGINE,
        TIERED_SHARDED_ENGINE,
    )

    if engine == "sharded":
        return SHARDED_ENGINE
    if engine == "tiered":
        return TIERED_ENGINE
    if engine == "tiered-sharded":
        return TIERED_SHARDED_ENGINE
    return SINGLE_CHIP_ENGINE


def final_geometry(checker) -> dict:
    # The keys are exactly the engines' spawn kwargs: single-chip
    # (and tiered, whose budget-derived capacity lands here as the
    # capacity it pinned) exposes capacity/log_capacity/
    # max_frontier/dedup_factor/sort_lanes, the sharded engine
    # capacity/chunk_size/dedup_factor/bucket_slack/sort_lanes (the
    # discovered exchange-bucket and sort-geometry rungs —
    # persisting them is what lets a warm repeat skip the
    # overflow-retry ramps, not just the auto-tune growth).  Each
    # engine's metrics() emits its own subset; the `in m` filter
    # picks the right one.
    m = checker.metrics()
    out = {
        k: int(m[k])
        for k in ("capacity", "log_capacity", "max_frontier",
                  "chunk_size", "dedup_factor", "bucket_slack")
        if k in m
    }
    # The rungs persist ONLY when the run actually pinned one
    # (sort_lanes_rung/step_lanes_rung; 0 = full buffer, tuner
    # armed): storing the live full width from a too-short-to-tune
    # run would spawn every warm repeat with an explicit rung and
    # disarm its tuner.  The dedup PATH persists always — a
    # sortless→sort fallback is a per-workload selection a warm
    # repeat must not re-discover with another aborted wave.
    # ...and the sort rung NEVER persists off a sortless run: there
    # it is the claim compaction buffer's tuner detail, and an
    # explicit sort_lanes under sortless is the fallback-forcing
    # budget cap — a warm repeat must re-arm the tuner instead.
    rung = int(m.get("sort_lanes_rung", 0) or 0)
    if rung and not m.get("sortless"):
        out["sort_lanes"] = rung
    step_rung = int(m.get("step_lanes_rung", 0) or 0)
    if step_rung:
        out["step_lanes"] = step_rung
    if "sortless" in m:
        out["sortless"] = int(bool(m["sortless"]))
    # The tiered-sharded engine's PER-SHARD budget is part of its
    # geometry identity (it derives cap_s, which the snapshot and
    # the warm start must agree on); a float, so it bypasses the
    # int() cast above.  The budget-keyed cache label already
    # separates budgets — storing it here makes the warm-started
    # spawn self-describing even without the label.
    if m.get("engine") == "tpu-tiered-sharded" and \
            m.get("memory_budget_mb") is not None:
        out["memory_budget_mb"] = float(m["memory_budget_mb"])
    return out


class Scheduler:
    def __init__(
        self,
        store: JobStore,
        journal=None,
        knob_cache_dir: Optional[str] = None,
        workers: int = 1,
        poll_interval: float = 0.02,
        retain_checkers: int = 4,
        store_dir: Optional[str] = None,
    ):
        """``retain_checkers`` caps how many completed jobs keep their
        checker alive for Explorer attach: a finished wavefront checker
        pins its whole device table + row log, so a long-lived daemon
        retaining every job's checker is an unbounded memory leak.  The
        oldest unexplored checkers past the cap are released (their job
        results remain; only ``/jobs/{id}/explore`` stops working)."""
        self.store = store
        self.journal = journal
        self.knob_cache_dir = knob_cache_dir
        # The persistent verification store (incr/, docs/INCREMENTAL.md)
        # jobs opt into with ``store: true``: identical resubmissions
        # short-circuit to the journaled verdict, near-identical ones
        # take the cheapest sound re-check path.  The recheck-mode
        # counters below are the /.metrics evidence.
        self.store_dir = store_dir
        self._retain = max(0, retain_checkers)
        self._retained: List[Job] = []  # oldest first
        self._retain_lock = threading.Lock()
        self.metrics = MetricsRegistry(
            jobs_submitted=0, jobs_completed=0, jobs_failed=0,
            jobs_cancelled=0, knob_cache_hits=0, knob_cache_misses=0,
            portfolio_wins=0, violations_found=0, unique_states_total=0,
            verdict_cache_hits=0, recheck_property_only=0,
            recheck_constant_widening=0, recheck_cold=0,
        )
        self._poll = poll_interval
        self._cond = threading.Condition()
        self._heap: List[tuple] = []  # (-priority, seq, job_id)
        self._seq = 0
        self._shutdown = threading.Event()
        self._workers = [
            threading.Thread(
                target=self._worker, daemon=True, name=f"serve-worker-{i}"
            )
            for i in range(max(1, workers))
        ]
        for t in self._workers:
            t.start()

    # -- submission surface ---------------------------------------------------

    def submit(self, spec: JobSpec) -> Job:
        job = self.store.create(spec)
        self.metrics.inc("jobs_submitted")
        with self._cond:
            self._seq += 1
            heapq.heappush(
                self._heap, (-spec.priority, self._seq, job.id)
            )
            self._cond.notify()
        return job

    def cancel(self, job_id: str) -> bool:
        """Cancel a job: False when unknown or already terminal.  Queued
        jobs die immediately; running jobs get a cooperative stop and
        finish as ``cancelled`` with their partial counts."""
        job = self.store.get(job_id)
        if job is None or job.terminal:
            return False
        job.cancel.set()
        # Atomic vs the worker's try_start: exactly one side wins, so a
        # job is either cancelled-while-queued here or runs and gets the
        # cooperative stop — never both terminal transitions.
        if self.store.try_cancel_queued(job):
            self.metrics.inc("jobs_cancelled")
            self._finish_spans(job)
        return True

    # -- per-job spans / SLO aggregation --------------------------------------

    def _span(self, job: Job, span: str, sec: float) -> None:
        """One lifecycle span: a ``job_span`` journal event (the durable
        per-job trace, docs/SERVING.md) plus the matching SLO histogram
        (``job_<span>_sec``) the aggregated ``/.metrics`` serves —
        queue p95 and end-to-end latency distributions come from
        exactly these."""
        from ..obs.metrics import LATENCY_BUCKETS

        sec = max(0.0, sec)
        self.metrics.observe(
            f"job_{span}_sec", sec, boundaries=LATENCY_BUCKETS
        )
        if self.journal is not None:
            self.journal.append(
                "job_span", job=job.id, span=span,
                sec=round(sec, 6), state=job.state,
                worker=worker_id(),
            )

    def _finish_spans(self, job: Job) -> None:
        """Terminal-state spans: ``run`` (running -> terminal; absent
        for a job cancelled while still queued) and ``total``
        (submit -> terminal, the end-to-end latency a client saw)."""
        if job.finished_at is None:
            return
        if job.started_at is not None:
            self._span(job, "run", job.finished_at - job.started_at)
        self._span(job, "total", job.finished_at - job.submitted_at)

    def shutdown(self, wait: bool = True) -> None:
        """Stop the workers: cancel any RUNNING job (the poll loop
        forwards the cancel to the engine's cooperative stop, so
        workers actually come home) and, with ``wait`` (default), join
        them.  The join matters beyond politeness: worker frames hold
        references into engine state (the running job's checker), and
        tearing the scheduler down while a worker is mid-exit lets the
        GC free device buffers in an order the XLA runtime's teardown
        aborts on (observed as ``terminate called without an active
        exception`` at interpreter exit)."""
        self._shutdown.set()
        for job in self.store.list():
            if job.state == RUNNING:
                job.cancel.set()
        with self._cond:
            self._cond.notify_all()
        if wait:
            for t in self._workers:
                if t is not threading.current_thread():
                    t.join(timeout=60.0)

    # -- worker loop ----------------------------------------------------------

    def _worker(self) -> None:
        while not self._shutdown.is_set():
            job = self._next_job()
            if job is None:
                continue
            self._run_job(job)

    def _next_job(self) -> Optional[Job]:
        with self._cond:
            while not self._heap and not self._shutdown.is_set():
                self._cond.wait(0.25)
            if self._shutdown.is_set() or not self._heap:
                return None
            _, _, job_id = heapq.heappop(self._heap)
        job = self.store.get(job_id)
        if job is None or job.state != QUEUED:
            return None  # cancelled while queued
        return job

    def _run_job(self, job: Job) -> None:
        if not self.store.try_start(job):
            return  # cancelled between pop and start
        self._span(job, "queue_wait", job.started_at - job.submitted_at)
        t0 = time.monotonic()
        prog_hits0 = GLOBAL.get("program_cache_hits", 0)
        try:
            if job.spec.portfolio is not None:
                result = self._run_portfolio(job)
            else:
                result = self._run_single(job)
        except JobCancelled as c:
            result = dict(c.partial)
            result["completed"] = False
            # Result lands BEFORE the terminal transition releases
            # waiters: a client woken by /result?wait= must see the
            # partial counts, not "cancelled" with result null.
            job.result = result
            job.checker = None  # explore() refuses non-DONE jobs; don't pin
            self.metrics.inc("jobs_cancelled")
            self.store.transition(
                job, CANCELLED,
                unique=result.get("unique_state_count"),
            )
            self._finish_spans(job)
            return
        except Exception as exc:
            import traceback

            job.error = f"{type(exc).__name__}: {exc}"
            job.result = {"completed": False, "error": job.error}
            job.checker = None
            self.metrics.inc("jobs_failed")
            if self.journal is not None:
                self.journal.append(
                    "job_error", job=job.id,
                    traceback=traceback.format_exc(limit=5)[-2000:],
                )
            self.store.transition(job, FAILED, error=job.error[:500])
            self._finish_spans(job)
            return
        result["completed"] = True
        result["elapsed_sec"] = round(time.monotonic() - t0, 3)
        # Per-job attribution of the process-global counter is only
        # meaningful when jobs run one at a time; with concurrent
        # workers another job's compiles/hits would land in this
        # window, so the per-job delta is withheld (the aggregated
        # /.metrics totals stay correct either way).
        result["program_cache_hits_delta"] = (
            GLOBAL.get("program_cache_hits", 0) - prog_hits0
            if len(self._workers) == 1 else None
        )
        job.result = result
        self.metrics.inc("jobs_completed")
        self.metrics.inc(
            "unique_states_total", result.get("unique_state_count", 0)
        )
        if result.get("violation"):
            self.metrics.inc("violations_found")
        self.store.transition(
            job, DONE,
            unique=result.get("unique_state_count"),
            violation=result.get("violation"),
        )
        self._finish_spans(job)
        self._enforce_checker_retention(job)

    def _enforce_checker_retention(self, job: Job) -> None:
        with self._retain_lock:
            if job.checker is not None:
                self._retained.append(job)
            excess = len(self._retained) - self._retain
            if excess <= 0:
                return
            keep = []
            for j in self._retained:
                # Explorer-attached checkers stay pinned: releasing one
                # would break a UI someone is looking at.
                if excess > 0 and j.explorer_address is None:
                    j.checker = None
                    excess -= 1
                else:
                    keep.append(j)
            self._retained = keep

    # -- builder assembly (delegates to the module-level helpers shared
    # with fleet/worker.py) ---------------------------------------------------

    def _make_builder(self, spec: JobSpec, engine: str,
                      symmetry: bool):
        return make_builder(spec, engine, symmetry)

    def _spawn(self, builder, spec: JobSpec, engine: str,
               engine_kwargs: dict, seed: int):
        return spawn_engine(builder, spec, engine, engine_kwargs, seed)

    def _bound_simulation(self, builder, spec: JobSpec) -> None:
        bound_simulation(builder, spec)

    # -- single-run jobs ------------------------------------------------------

    def _run_single(self, job: Job, _retry: bool = False) -> dict:
        spec = job.spec
        if spec.store:
            return self._run_stored(job)
        model, cli, builder, n = self._make_builder(
            spec, spec.engine, spec.symmetry
        )
        if spec.engine in _SIM_ENGINES:
            self._bound_simulation(builder, spec)

        # Engine kwargs: workload defaults < cached tuned knobs <
        # explicit request overrides.  The knob cache is the cross-job
        # warm start: the first job's auto-tune discovery is persisted,
        # so the second identical job spawns right-sized and skips the
        # growth pauses entirely (asserted by tests/test_serve.py).
        engine_kwargs = (
            dict(cli.tpu_kwargs)
            if spec.engine in ("tpu", "tiered")
            else {}
        )
        cache_key = None
        cache_hit = False
        # Every device engine warm-starts from the knob cache; sharded
        # and tiered entries live under their own engine tags (the
        # sharded knob set — chunk_size/bucket_slack — is disjoint from
        # the single-chip one, and tiered entries pin the budget-derived
        # capacity, which must never shadow the in-HBM right-sizing).
        device_engine = spec.engine in (
            "tpu", "tiered", "sharded", "tiered-sharded",
        )
        if (
            device_engine
            and spec.use_knob_cache
            and self.knob_cache_dir is not None
        ):
            label = workload_label(
                spec.workload, n, spec.network, spec.symmetry
            )
            if spec.engine in ("tiered", "tiered-sharded"):
                # Tiered entries pin a budget-DERIVED capacity (and a
                # possibly budget-shrunk frontier), so the budget is
                # part of the entry's identity: without it, one
                # budget's tiny pinned table would silently warm-start
                # the same workload at a different (or no) budget.
                # Tiered-sharded budgets are PER SHARD, but the engine
                # tag already separates the two entry families.
                label += ":mb={}".format(
                    spec.engine_kwargs.get("memory_budget_mb")
                )
            cache_key = knob_key(
                label, engine=self._knob_engine_tag(spec.engine),
            )
            cached = None if _retry else load_knobs(
                self.knob_cache_dir, cache_key
            )
            if cached is not None:
                engine_kwargs.update(cached)
                cache_hit = True
                self.metrics.inc("knob_cache_hits")
            elif not _retry:
                self.metrics.inc("knob_cache_misses")
        engine_kwargs.update(spec.engine_kwargs)

        try:
            checker = self._spawn(
                builder, spec, spec.engine, engine_kwargs, spec.seed
            )
            job.checker = checker
            self._poll_to_completion(job, checker)
        except JobCancelled:
            raise
        except Exception:
            if cache_hit and cache_key is not None:
                # Stale cached geometry (engine defaults moved under
                # it): drop the entry and rerun once from a fresh
                # discovery — the knob-cache staleness contract
                # (runtime/knob_cache.py).
                drop_knobs(self.knob_cache_dir, cache_key)
                if self.journal is not None:
                    self.journal.append(
                        "knobs_dropped", job=job.id, key=cache_key
                    )
                return self._run_single(job, _retry=True)
            raise

        summary = checker_summary(checker)
        summary["engine"] = spec.engine
        summary["n"] = n
        summary["knob_cache_hit"] = cache_hit
        # Explicit knobs aren't "tuned" and are never persisted — EXCEPT
        # memory_budget_mb, which is a budget, not a geometry: it is the
        # normal way a tiered job arrives, the engine re-derives capacity
        # from it deterministically, and withholding the store would make
        # the TIERED_ENGINE warm start unreachable for exactly the jobs
        # it exists for (the discovered log_capacity/max_frontier are
        # what the repeat would otherwise re-pay auto-tune for).
        hand_tuned = set(spec.engine_kwargs) - {"memory_budget_mb"}
        if (
            cache_key is not None
            and not cache_hit
            and device_engine
            and not hand_tuned
        ):
            # Persist the run's FINAL geometry (post any auto-tune
            # growth), not the shrunk tuned_kwargs: an identical repeat
            # then reproduces the exact compiled-program cache keys, so
            # the second job skips both the growth pauses AND the
            # compiles — the full warmup collapse the serving bench
            # phase measures.
            knobs = self._final_geometry(checker)
            if knobs:
                t_kc = time.monotonic()
                store_knobs(
                    self.knob_cache_dir, cache_key, knobs,
                    unique=summary["unique_state_count"],
                    depth=summary["max_depth"], source=f"serve:{job.id}",
                )
                # The knob-cache write is part of the job's host tail:
                # journaled like every other lifecycle span so the
                # timeline exporter can place it.
                self._span(job, "knob_cache", time.monotonic() - t_kc)
        return summary

    # -- verification-store jobs (incr/, docs/INCREMENTAL.md) -----------------

    def _run_stored(self, job: Job) -> dict:
        """One ``store: true`` job: classify the spec against the
        persistent verification store and take the cheapest sound path.
        An identical resubmission is the SCHEDULER SHORT-CIRCUIT — the
        journaled verdict + counterexample paths come back with zero
        device dispatches (the content-addressed verdict cache, ROADMAP
        #3c); property-only edits re-evaluate over the stored row log;
        declared constant widenings explore only the new region;
        anything else runs cold with the reason journaled AND surfaced
        in the job result (``recheck_mode`` / ``recheck_reason``)."""
        from ..incr.recheck import StoredVerdictChecker, incremental_check

        spec = job.spec
        if self.store_dir is None:
            raise ValueError(
                "job requested the verification store (store: true), "
                "but this service was started without one (serve "
                "--store-dir DIR)"
            )
        _model, cli, builder, n = self._make_builder(
            spec, spec.engine, spec.symmetry
        )
        # Same kwargs layering as _run_single: workload defaults <
        # cached tuned knobs < explicit request overrides.  Engine
        # geometry is excluded from spec matching (incr/spec_hash.py),
        # so the knob cache's warm start composes freely with the
        # store: a cold-classified repeat of a once-seen workload still
        # skips the auto-tune growth pauses.
        engine_kwargs = dict(cli.tpu_kwargs)
        cache_key = None
        cache_hit = False
        if spec.use_knob_cache and self.knob_cache_dir is not None:
            cache_key = knob_key(workload_label(
                spec.workload, n, spec.network, spec.symmetry
            ))
            cached = load_knobs(self.knob_cache_dir, cache_key)
            if cached is not None:
                engine_kwargs.update(cached)
                cache_hit = True
                self.metrics.inc("knob_cache_hits")
            else:
                self.metrics.inc("knob_cache_misses")
        engine_kwargs.update(spec.engine_kwargs)

        def attach(ck):
            # Live vitals for RUNNING store jobs, same as _run_single's
            # at-spawn attach (jobs.py reads checker.metrics() for the
            # /jobs/{id} vitals key).
            job.checker = ck

        checker, info = incremental_check(
            builder,
            self.store_dir,
            engine_kwargs=engine_kwargs,
            journal=self.journal,
            reuse=True,
            cancel=job.cancel,
            on_spawn=attach,
        )
        if job.cancel.is_set():
            # Same contract as every other job path: a cancelled run
            # reports its partial counts as CANCELLED (the store's
            # completeness gate already refused the partial verdict).
            raise JobCancelled(partial=checker_summary(checker))
        counter = {
            "identical": "verdict_cache_hits",
            "property_only": "recheck_property_only",
            "constant_widening": "recheck_constant_widening",
            "cold": "recheck_cold",
        }.get(info["mode"])
        if counter:
            self.metrics.inc(counter)
        # Cache-served checkers hold no device state worth exploring;
        # retaining them would only shadow the retention cap.
        if not isinstance(checker, StoredVerdictChecker):
            job.checker = checker
        summary = checker_summary(checker)
        # Persist a cold run's FINAL geometry on a knob-cache miss,
        # exactly like _run_single: the next cold-classified job of
        # this workload then spawns right-sized AND reproduces the
        # compiled-program cache keys.
        if (
            info["mode"] == "cold"
            and cache_key is not None
            and not cache_hit
            and not spec.engine_kwargs
            and job.checker is not None
        ):
            knobs = self._final_geometry(job.checker)
            if knobs:
                t_kc = time.monotonic()
                store_knobs(
                    self.knob_cache_dir, cache_key, knobs,
                    unique=summary["unique_state_count"],
                    depth=summary["max_depth"],
                    source=f"serve:{job.id}:store",
                )
                self._span(job, "knob_cache", time.monotonic() - t_kc)
        summary["engine"] = spec.engine
        summary["n"] = n
        summary["knob_cache_hit"] = cache_hit
        summary["recheck_mode"] = info["mode"]
        summary["recheck_reason"] = info["reason"]
        if "seeded_states" in info:
            summary["recheck_seeded_states"] = info["seeded_states"]
        return summary

    @staticmethod
    def _knob_engine_tag(engine: str) -> str:
        return knob_engine_tag(engine)

    @staticmethod
    def _final_geometry(checker) -> dict:
        return final_geometry(checker)

    def _poll_to_completion(self, job: Job, checker) -> None:
        while not checker.is_done():
            if job.cancel.is_set():
                checker.request_stop()
            time.sleep(self._poll)
        checker.join()
        if job.cancel.is_set():
            raise JobCancelled(partial=checker_summary(checker))

    # -- portfolio jobs -------------------------------------------------------

    def _run_portfolio(self, job: Job) -> dict:
        from ..core.has_discoveries import HasDiscoveries

        spec = job.spec
        pf = spec.portfolio
        _, cli, n = build_model(spec.workload, spec.n, spec.network)
        base_kwargs = dict(cli.tpu_kwargs) if spec.engine == "tpu" else {}
        base_kwargs.update(spec.engine_kwargs)
        members = diversify(
            size=int(pf["size"]),
            seed=int(pf.get("seed", 0)),
            base_engine=spec.engine,
            base_kwargs=base_kwargs,
            symmetry_capable=self._symmetry_capable(spec),
            include_simulation=bool(pf.get("simulation", True)),
        )
        if self.journal is not None:
            self.journal.append(
                "portfolio_start", job=job.id, size=len(members),
                seed=int(pf.get("seed", 0)),
                parallelism=int(pf.get("parallelism", 1)),
            )

        def spawn_member(member):
            _, _, builder, _ = self._make_builder(
                spec, member.engine, member.symmetry
            )
            # Swarm semantics: every member stops at the first
            # failure-classified discovery; clean exhaustive members run
            # out their full space (the completeness anchor).
            builder.finish_when(HasDiscoveries.ANY_FAILURES)
            if member.kind == "simulation":
                target = (
                    spec.target_state_count or member.target_state_count
                )
                builder.target_state_count(target)
            return self._spawn(
                builder, spec, member.engine, member.engine_kwargs,
                member.seed or spec.seed,
            )

        res = run_portfolio(
            members, spawn_member, job.cancel, journal=self.journal,
            parallelism=int(pf.get("parallelism", 1)),
            poll_interval=self._poll,
        )
        if job.cancel.is_set():
            raise JobCancelled(partial={"portfolio": res["portfolio"]})

        winner_idx = res["winner_index"]
        entries = res["entries"]
        # The authoritative counts: the winner's run, else the
        # exhaustive anchor (member 0), else the first member that
        # completed at all.
        authoritative = None
        if winner_idx is not None:
            authoritative = entries[winner_idx]
            self.metrics.inc("portfolio_wins")
        else:
            for e in entries:
                if e and e.get("summary") is not None:
                    authoritative = e
                    break
        if authoritative is None or authoritative.get("summary") is None:
            raise RuntimeError(
                "every portfolio member failed; see the service journal"
            )
        job.checker = authoritative.get("checker")
        summary = dict(authoritative["summary"])
        # Label the counts with the engine that PRODUCED them: a
        # simulation-member winner's counts are a sampled walk, and
        # reporting them under the requested exhaustive engine would
        # misrepresent a Monte-Carlo number as a full search.
        summary["engine"] = authoritative.get("engine", spec.engine)
        summary["sampled"] = authoritative.get("kind") == "simulation"
        summary["authoritative_member"] = authoritative.get("index")
        summary["n"] = n
        summary["portfolio"] = res["portfolio"]
        self._fold_winner_knobs(job, spec, n, members, winner_idx, entries)
        return summary

    def _symmetry_capable(self, spec: JobSpec) -> bool:
        """May portfolio members toggle symmetry on?  Device members
        need a compiled canonicalization (parallel/canon.py); host DFS
        members need the model's representative()."""
        try:
            model, cli, _ = build_model(spec.workload, spec.n, spec.network)
        except Exception:
            return False
        if not cli.symmetry:
            return False
        if spec.engine in ("tpu", "sharded"):
            try:
                from ..parallel.canon import make_canon
                from ..parallel.compiled import compiled_model_for

                return make_canon(compiled_model_for(model)) is not None
            except Exception:
                return False
        return spec.engine == "dfs"

    def _fold_winner_knobs(self, job, spec, n, members, winner_idx,
                           entries) -> None:
        """Swarm feedback loop: the config that found the counterexample
        becomes the workload's warm-start entry, so the next job on this
        model starts from the geometry that actually worked."""
        if winner_idx is None or self.knob_cache_dir is None:
            return
        member = members[winner_idx]
        checker = entries[winner_idx].get("checker")
        label = workload_label(
            spec.workload, n, spec.network, member.symmetry
        )
        if member.engine in ("tpu", "sharded") and checker is not None:
            knobs = self._final_geometry(checker) or member.engine_kwargs
        else:
            # A simulation winner's "config" is its seed/bounds, which
            # are not spawn_tpu knobs: record it under a portfolio-only
            # label so plain jobs never load it as engine geometry.
            label += ":portfolio-winner"
            knobs = member.engine_kwargs or {"seed": member.seed}
        key = knob_key(label, engine=self._knob_engine_tag(member.engine))
        t_kc = time.monotonic()
        store_knobs(
            self.knob_cache_dir, key, knobs,
            portfolio_winner=True, member=member.index,
            member_engine=member.engine, job=job.id,
            violation=entries[winner_idx].get("violation"),
        )
        self._span(job, "knob_cache", time.monotonic() - t_kc)
