"""Checking-service daemon entry point::

    python -m stateright_tpu.serve [HOST:PORT]
        [--journal PATH] [--journal-max-mb MB] [--knob-cache DIR]
        [--workers N] [--store-dir DIR] [--fleet-dir DIR]

``--journal-max-mb`` size-caps the journal into rotated segments
(``journal.jsonl.1..N``, runtime/journal.py) so a long-lived daemon
cannot grow one unbounded file; readers (``report``, read_journal)
merge segments transparently.  ``--store-dir`` enables the persistent
verification store for jobs submitted with ``store: true``
(docs/INCREMENTAL.md): identical resubmissions short-circuit to the
journaled verdict, near-identical ones take the cheapest sound
re-check path.

``--workers N`` (N ≥ 1) sizes the in-process scheduler pool.  These
workers are THREADS sharing the one accelerator mesh this process
owns — more of them overlaps host-side work (spec validation, journal
writes, knob-cache lookups) around serialized device runs; it does not
multiply device throughput.  For workers that each own a backend, use
fleet mode instead: ``--fleet-dir DIR`` makes this server a thin front
over the durable fleet store at DIR, with jobs run by separately
launched ``fleet-worker`` processes — one per CPU container, GPU box,
or TPU mesh (fleet/, docs/SERVING.md "Fleet mode").  ``--fleet-dir``
replaces the in-process backend, so it cannot be combined with
``--workers``, ``--journal``, ``--knob-cache``, or ``--store-dir``
(the fleet store has its own journal; knob caches belong to the worker
processes).

Serves until interrupted.  docs/SERVING.md documents the endpoints,
the job lifecycle, and the journal layout.
"""

from __future__ import annotations

import sys

DEFAULT_ADDRESS = "localhost:3100"


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] in ("-h", "--help", "help"):
        print(__doc__.strip())
        return 0
    address = DEFAULT_ADDRESS
    journal = None
    journal_max_mb = None
    knob_cache = None
    store_dir = None
    fleet_dir = None
    workers = None
    positional = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--journal":
            i += 1
            if i >= len(args):
                print("--journal requires a path", file=sys.stderr)
                return 2
            journal = args[i]
        elif a == "--journal-max-mb":
            i += 1
            try:
                journal_max_mb = float(args[i])
            except (IndexError, ValueError):
                print("--journal-max-mb requires a number of MB",
                      file=sys.stderr)
                return 2
            if journal_max_mb <= 0:
                print("--journal-max-mb must be positive", file=sys.stderr)
                return 2
        elif a == "--knob-cache":
            i += 1
            if i >= len(args):
                print("--knob-cache requires a directory", file=sys.stderr)
                return 2
            knob_cache = args[i]
        elif a == "--store-dir":
            i += 1
            if i >= len(args):
                print("--store-dir requires a directory", file=sys.stderr)
                return 2
            store_dir = args[i]
        elif a == "--fleet-dir":
            i += 1
            if i >= len(args):
                print("--fleet-dir requires a directory", file=sys.stderr)
                return 2
            fleet_dir = args[i]
        elif a == "--workers":
            i += 1
            try:
                workers = int(args[i])
            except (IndexError, ValueError):
                print("--workers requires an integer", file=sys.stderr)
                return 2
            if workers < 1:
                # A pool of zero threads would accept jobs that can
                # never run; refuse at the CLI boundary, loudly.
                print(
                    f"--workers must be >= 1, got {workers} (in-process "
                    "workers are threads sharing this process's one "
                    "mesh; for per-backend workers use --fleet-dir and "
                    "fleet-worker processes)",
                    file=sys.stderr,
                )
                return 2
        else:
            positional.append(a)
        i += 1
    if positional:
        address = positional[0]
    host, _, port = address.partition(":")
    try:
        port = int(port or DEFAULT_ADDRESS.rpartition(":")[2])
    except ValueError:
        print(f"invalid ADDRESS port: {address!r}", file=sys.stderr)
        return 2

    from .server import serve
    from .workloads import workload_names

    if fleet_dir is not None:
        incompatible = [
            flag for flag, val in (
                ("--workers", workers), ("--journal", journal),
                ("--journal-max-mb", journal_max_mb),
                ("--knob-cache", knob_cache), ("--store-dir", store_dir),
            ) if val is not None
        ]
        if incompatible:
            print(
                "--fleet-dir replaces the in-process backend and cannot "
                "be combined with " + ", ".join(incompatible) +
                " (the fleet store journals itself; knob caches and "
                "worker counts belong to the fleet-worker processes)",
                file=sys.stderr,
            )
            return 2
        print(
            f"Checking service on http://{host}:{port} "
            f"(fleet mode, store: {fleet_dir}, workloads: "
            f"{', '.join(workload_names())})",
            flush=True,
        )
        serve((host, port), block=True, fleet_dir=fleet_dir)
        return 0
    workers = 1 if workers is None else workers

    if journal_max_mb is not None:
        if journal is None:
            # Silently journaling nothing is the opposite of what the
            # size cap asks for; fail loudly at the CLI boundary.
            print(
                "--journal-max-mb requires --journal PATH (it size-caps "
                "that journal into rotated segments)",
                file=sys.stderr,
            )
            return 2
        from ..runtime.journal import Journal

        journal = Journal(
            journal, max_bytes=int(journal_max_mb * 1024 * 1024)
        )

    print(
        f"Checking service on http://{host}:{port} "
        f"(workers={workers}, workloads: {', '.join(workload_names())})",
        flush=True,
    )
    serve(
        (host, port), block=True, journal=journal,
        knob_cache_dir=knob_cache, workers=workers, store_dir=store_dir,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
