"""Checking-service daemon entry point::

    python -m stateright_tpu.serve [HOST:PORT]
        [--journal PATH] [--journal-max-mb MB] [--knob-cache DIR]
        [--workers N] [--store-dir DIR]

``--journal-max-mb`` size-caps the journal into rotated segments
(``journal.jsonl.1..N``, runtime/journal.py) so a long-lived daemon
cannot grow one unbounded file; readers (``report``, read_journal)
merge segments transparently.  ``--store-dir`` enables the persistent
verification store for jobs submitted with ``store: true``
(docs/INCREMENTAL.md): identical resubmissions short-circuit to the
journaled verdict, near-identical ones take the cheapest sound
re-check path.

Serves until interrupted.  docs/SERVING.md documents the endpoints,
the job lifecycle, and the journal layout.
"""

from __future__ import annotations

import sys

DEFAULT_ADDRESS = "localhost:3100"


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] in ("-h", "--help", "help"):
        print(__doc__.strip())
        return 0
    address = DEFAULT_ADDRESS
    journal = None
    journal_max_mb = None
    knob_cache = None
    store_dir = None
    workers = 1
    positional = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--journal":
            i += 1
            if i >= len(args):
                print("--journal requires a path", file=sys.stderr)
                return 2
            journal = args[i]
        elif a == "--journal-max-mb":
            i += 1
            try:
                journal_max_mb = float(args[i])
            except (IndexError, ValueError):
                print("--journal-max-mb requires a number of MB",
                      file=sys.stderr)
                return 2
            if journal_max_mb <= 0:
                print("--journal-max-mb must be positive", file=sys.stderr)
                return 2
        elif a == "--knob-cache":
            i += 1
            if i >= len(args):
                print("--knob-cache requires a directory", file=sys.stderr)
                return 2
            knob_cache = args[i]
        elif a == "--store-dir":
            i += 1
            if i >= len(args):
                print("--store-dir requires a directory", file=sys.stderr)
                return 2
            store_dir = args[i]
        elif a == "--workers":
            i += 1
            try:
                workers = int(args[i])
            except (IndexError, ValueError):
                print("--workers requires an integer", file=sys.stderr)
                return 2
        else:
            positional.append(a)
        i += 1
    if positional:
        address = positional[0]
    host, _, port = address.partition(":")
    try:
        port = int(port or DEFAULT_ADDRESS.rpartition(":")[2])
    except ValueError:
        print(f"invalid ADDRESS port: {address!r}", file=sys.stderr)
        return 2

    from .server import serve
    from .workloads import workload_names

    if journal_max_mb is not None:
        if journal is None:
            # Silently journaling nothing is the opposite of what the
            # size cap asks for; fail loudly at the CLI boundary.
            print(
                "--journal-max-mb requires --journal PATH (it size-caps "
                "that journal into rotated segments)",
                file=sys.stderr,
            )
            return 2
        from ..runtime.journal import Journal

        journal = Journal(
            journal, max_bytes=int(journal_max_mb * 1024 * 1024)
        )

    print(
        f"Checking service on http://{host}:{port} "
        f"(workers={workers}, workloads: {', '.join(workload_names())})",
        flush=True,
    )
    serve(
        (host, port), block=True, journal=journal,
        knob_cache_dir=knob_cache, workers=workers, store_dir=store_dir,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
