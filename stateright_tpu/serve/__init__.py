"""The persistent multi-job checking service (docs/SERVING.md).

Verification as a service, not a script: one process owns the mesh and
serves many check jobs over it — job queueing with priorities and
cancellation (serve/jobs.py, serve/scheduler.py), compiled-program and
knob-cache reuse across requests (the warm-start story), a swarm
portfolio mode racing diversified configs to the first counterexample
(serve/portfolio.py, after Holzmann-Joshi-Groce's Swarm Verification),
and an HTTP surface with aggregated metrics (serve/server.py).

Run the daemon with ``python -m stateright_tpu.serve`` or a model
module's ``serve`` subcommand; submit from the CLI with ``submit``.
"""

from .jobs import (  # noqa: F401
    CANCELLED, DONE, FAILED, QUEUED, RUNNING, Job, JobSpec, JobStore,
)
from .portfolio import MemberConfig, diversify  # noqa: F401
from .scheduler import Scheduler  # noqa: F401
from .server import CheckService, serve  # noqa: F401
from .workloads import workload_names  # noqa: F401
