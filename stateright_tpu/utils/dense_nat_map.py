"""DenseNatMap: a type-safe vector keyed by index-like values.

Reference: src/util/densenatmap.rs — a ``Vec`` keyed by newtypes convertible
to/from ``usize`` (e.g. actor ``Id``), insert-in-order only; the basis of
``RewritePlan``.
"""

from __future__ import annotations

from typing import Any, Generic, Iterable, Iterator, List, Tuple, TypeVar

K = TypeVar("K")
V = TypeVar("V")


class DenseNatMap(Generic[K, V]):
    __slots__ = ("_values",)

    def __init__(self, values: Iterable[V] = ()):
        self._values: List[V] = list(values)

    def insert(self, key: K, value: V) -> None:
        i = int(key)
        if i != len(self._values):
            raise KeyError(
                f"DenseNatMap requires in-order insertion; next={len(self._values)}, got {i}"
            )
        self._values.append(value)

    def get(self, key: K) -> V:
        return self._values[int(key)]

    def __getitem__(self, key: K) -> V:
        return self._values[int(key)]

    def values(self) -> List[V]:
        return list(self._values)

    def items(self) -> Iterator[Tuple[int, V]]:
        return enumerate(self._values)

    def __iter__(self) -> Iterator[V]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, DenseNatMap) and self._values == other._values

    def __hash__(self) -> int:
        return hash(tuple(self._values))

    def __canon_words__(self, out: List[int]) -> None:
        from ..ops.fingerprint import canon_words

        canon_words(tuple(self._values), out)

    def __repr__(self) -> str:
        return f"DenseNatMap({self._values!r})"
