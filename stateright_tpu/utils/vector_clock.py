"""Vector clocks: a partial causal order on distributed events.

Reference: src/util/vector_clock.rs.  Trailing zeros are insignificant —
equality, hashing, fingerprinting, and comparison all ignore them, so
``VectorClock([1, 0])`` equals ``VectorClock([1])``.
"""

from __future__ import annotations

from typing import Iterable, Optional, Tuple


class VectorClock:
    __slots__ = ("_elems",)

    def __init__(self, elems: Iterable[int] = ()):
        self._elems: Tuple[int, ...] = tuple(elems)

    def merge_max(self, other: "VectorClock") -> "VectorClock":
        """Element-wise maximum (reference:18-30)."""
        n = max(len(self._elems), len(other._elems))
        return VectorClock(
            max(self._get(i), other._get(i)) for i in range(n)
        )

    def incremented(self, index: int) -> "VectorClock":
        """A copy with component ``index`` incremented (reference:32-39)."""
        elems = list(self._elems)
        if index >= len(elems):
            elems.extend([0] * (index + 1 - len(elems)))
        elems[index] += 1
        return VectorClock(elems)

    def _get(self, i: int) -> int:
        return self._elems[i] if i < len(self._elems) else 0

    def _significant(self) -> Tuple[int, ...]:
        cutoff = 0
        for i, e in enumerate(self._elems):
            if e != 0:
                cutoff = i + 1
        return self._elems[:cutoff]

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, VectorClock)
            and self._significant() == other._significant()
        )

    def __hash__(self) -> int:
        # Trailing zeros ignored so equal clocks hash equal (reference:53-63).
        return hash(self._significant())

    def __canon_words__(self, out) -> None:
        from ..ops.fingerprint import canon_words

        canon_words(("VectorClock", self._significant()), out)

    def partial_cmp(self, other: "VectorClock") -> Optional[int]:
        """-1 / 0 / 1 for happens-before / equal / happens-after; None when
        incomparable (concurrent).  Reference:84-106."""
        if not isinstance(other, VectorClock):
            raise TypeError(
                f"cannot compare VectorClock with {type(other).__name__}"
            )
        expected = 0
        n = max(len(self._elems), len(other._elems))
        for i in range(n):
            a, b = self._get(i), other._get(i)
            ordering = (a > b) - (a < b)
            if expected == 0:
                expected = ordering
            elif ordering != expected and ordering != 0:
                return None
        return expected

    def __lt__(self, other) -> bool:
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self.partial_cmp(other) == -1

    def __le__(self, other) -> bool:
        if not isinstance(other, VectorClock):
            return NotImplemented
        c = self.partial_cmp(other)
        return c is not None and c <= 0

    def __gt__(self, other) -> bool:
        if not isinstance(other, VectorClock):
            return NotImplemented
        return self.partial_cmp(other) == 1

    def __ge__(self, other) -> bool:
        if not isinstance(other, VectorClock):
            return NotImplemented
        c = self.partial_cmp(other)
        return c is not None and c >= 0

    def __repr__(self) -> str:
        return f"VectorClock({list(self._elems)!r})"

    def __str__(self) -> str:
        # Reference Display (reference:42-51).
        return "<" + "".join(f"{c}, " for c in self._elems) + "...>"
