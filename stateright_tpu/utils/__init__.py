"""Utility data structures (reference: src/util.rs and submodules).

Python's built-in ``frozenset`` / ``dict`` / ``tuple`` already provide the
hashable-collection semantics of the reference's ``HashableHashSet`` /
``HashableHashMap`` (the canonical fingerprint encoding hashes sets and
maps order-insensitively — ops/fingerprint.py:157-169); ``DenseNatMap``
and ``VectorClock`` are ported explicitly.
"""

from .dense_nat_map import DenseNatMap
from .vector_clock import VectorClock

__all__ = ["DenseNatMap", "VectorClock"]
