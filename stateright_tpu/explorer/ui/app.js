// Explorer single-page app: status polling, lazy next-step fetches keyed by
// the fingerprint path in the URL hash, and keyboard navigation.  Mirrors the
// behavior of the reference UI (ui/app.js): status poll every 5 s, routing
// via "#/steps/fp1/fp2", j/k/enter/u keys, per-state property verdicts, and a
// run-to-completion button.

"use strict";

let selected = 0;
let steps = [];

function fpPath() {
  const h = window.location.hash;
  const m = h.match(/^#\/steps\/?(.*)$/);
  return m && m[1] ? m[1].replace(/\/+$/, "") : "";
}

function setHash(path) {
  window.location.hash = path ? "#/steps/" + path : "#/steps";
}

async function refreshStatus() {
  try {
    const res = await fetch("/.status");
    const s = await res.json();
    document.getElementById("st-model").textContent = s.model;
    document.getElementById("st-states").textContent = s.state_count;
    document.getElementById("st-unique").textContent = s.unique_state_count;
    document.getElementById("st-depth").textContent = s.max_depth;
    const prog = document.getElementById("st-progress");
    prog.textContent = s.done ? "done" : "checking";
    prog.title = "Recent path: " + (s.recent_path || "(none)");
    const props = document.getElementById("properties");
    props.innerHTML = "";
    for (const [expectation, name, discovery] of s.properties) {
      const li = document.createElement("li");
      const label = expectation + " “" + name + "”";
      if (discovery) {
        const a = document.createElement("a");
        a.href = "#/steps/" + discovery;
        a.textContent = label + " (discovery)";
        li.appendChild(a);
      } else {
        li.textContent = label;
      }
      props.appendChild(li);
    }
  } catch (e) {
    /* server briefly unavailable; retry on next poll */
  }
}

function renderPathCrumbs() {
  const ol = document.getElementById("path");
  ol.innerHTML = "";
  const fps = fpPath() ? fpPath().split("/") : [];
  const root = document.createElement("li");
  const rootLink = document.createElement("a");
  rootLink.href = "#/steps";
  rootLink.textContent = "(init)";
  root.appendChild(rootLink);
  ol.appendChild(root);
  let acc = [];
  for (const fp of fps) {
    acc.push(fp);
    const li = document.createElement("li");
    const a = document.createElement("a");
    a.href = "#/steps/" + acc.join("/");
    a.textContent = fp;
    a.className = "font-code";
    li.appendChild(a);
    ol.appendChild(li);
  }
}

function renderSteps() {
  const ul = document.getElementById("next-steps");
  ul.innerHTML = "";
  steps.forEach((st, i) => {
    const li = document.createElement("li");
    li.className = i === selected ? "step selected" : "step";
    const head = document.createElement("div");
    head.className = "step-head";
    head.textContent =
      (st.action ? st.action : "(init state)") +
      (st.fingerprint ? "  → " + st.fingerprint : "  (ignored)");
    li.appendChild(head);
    if (st.outcome) {
      const out = document.createElement("pre");
      out.textContent = st.outcome;
      li.appendChild(out);
    } else if (st.state) {
      const pre = document.createElement("pre");
      pre.textContent = st.state;
      li.appendChild(pre);
    }
    li.onclick = () => follow(i);
    ul.appendChild(li);
  });
  const svgView = document.getElementById("svg-view");
  const cur = steps[selected];
  svgView.innerHTML = cur && cur.svg ? cur.svg : "";
}

async function refreshSteps() {
  const path = fpPath();
  const res = await fetch("/.states/" + path);
  if (!res.ok) {
    document.getElementById("next-steps").innerHTML =
      "<li class='error'>" + (await res.text()) + "</li>";
    return;
  }
  steps = await res.json();
  selected = Math.min(selected, Math.max(steps.length - 1, 0));
  renderPathCrumbs();
  renderSteps();
}

function follow(i) {
  const st = steps[i];
  if (!st || !st.fingerprint) return;
  selected = 0;
  const path = fpPath();
  setHash(path ? path + "/" + st.fingerprint : st.fingerprint);
}

function goUp() {
  const fps = fpPath() ? fpPath().split("/") : [];
  fps.pop();
  selected = 0;
  setHash(fps.join("/"));
}

document.addEventListener("keydown", (e) => {
  if (e.key === "j") {
    selected = Math.min(selected + 1, steps.length - 1);
    renderSteps();
  } else if (e.key === "k") {
    selected = Math.max(selected - 1, 0);
    renderSteps();
  } else if (e.key === "Enter") {
    follow(selected);
  } else if (e.key === "u") {
    goUp();
  }
});

document.getElementById("run-to-completion").onclick = async () => {
  await fetch("/.runtocompletion", { method: "POST" });
  refreshStatus();
};

window.addEventListener("hashchange", refreshSteps);
refreshStatus();
refreshSteps();
setInterval(refreshStatus, 5000);
