"""The interactive state-space Explorer web service.

Reference: src/checker/explorer.rs.  ``CheckerBuilder.serve`` wraps the
builder with a recent-path sampling visitor, spawns an **on-demand**
checker, and serves:

- ``GET /`` (and ``/app.js``, ``/app.css``) — the single-page UI;
- ``GET /.status`` — ``StatusView`` JSON: done, model type name, counts,
  properties with encoded discovery paths, a recently-visited path
  (src/checker/explorer.rs:171-190);
- ``GET /.metrics`` — the checker's live ``metrics()`` snapshot (this
  package's addition; the reference has no metrics surface): counts for
  every engine, plus wave cadence / table occupancy / device-call time
  for the TPU engines and the roofline trace summary under ``trace=True``
  (docs/OBSERVABILITY.md);
- ``GET /.states/{fp1}/{fp2}/...`` — the successor ``StateView`` list for
  the state reached by re-executing the fingerprint path (404 on a bad
  path), each visit nudging the background checker via
  ``check_fingerprint`` so it follows the user
  (src/checker/explorer.rs:224-320);
- ``POST /.runtocompletion`` — switch the on-demand checker to exhaustive
  mode (src/checker/explorer.rs:192-202).
"""

from __future__ import annotations

import json
import pathlib
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional

from ..core.path import NondeterminismError, Path
from ..core.visitor import CheckerVisitor

_UI_DIR = pathlib.Path(__file__).resolve().parent / "ui"


class _Snapshot(CheckerVisitor):
    """Samples one recently-visited path every ``period`` seconds.

    Reference: src/checker/explorer.rs:61-98.
    """

    def __init__(self, period: float = 4.0):
        self._lock = threading.Lock()
        self._take = True
        self.path_repr: Optional[str] = None
        t = threading.Thread(
            target=self._rearm, args=(period,), daemon=True, name="snapshot"
        )
        t.start()

    def _rearm(self, period: float) -> None:
        while True:
            time.sleep(period)
            with self._lock:
                self._take = True

    def visit(self, model, path: Path) -> None:
        with self._lock:
            if not self._take:
                return
            self._take = False
            self.path_repr = repr(path.into_actions())


def _properties_view(checker) -> List[List[Any]]:
    """[[expectation, name, encoded discovery path or None], ...]
    (src/checker/explorer.rs:205-222)."""
    model = checker.model()
    out = []
    for p in model.properties():
        disc = checker.try_discovery(p.name)
        out.append(
            [
                p.expectation.name.capitalize(),
                p.name,
                disc.encode(model) if disc is not None else None,
            ]
        )
    return out


def _status_view(checker, snapshot: _Snapshot) -> dict:
    out = {
        "done": checker.is_done(),
        "model": type(checker.model()).__name__,
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "properties": _properties_view(checker),
        "recent_path": snapshot.path_repr,
    }
    # Live vitals beside the counts (the same mid-run-safe subset the
    # checking service embeds in a running job's snapshot —
    # obs/metrics.vitals_view): one /.status poll answers "is this run
    # moving, and how fast" without a second /.metrics request.
    from ..obs.metrics import vitals_view

    vitals = vitals_view(checker)
    if vitals is not None:
        out["vitals"] = vitals
    return out


def _state_views(checker, fp_path: str) -> List[dict]:
    """src/checker/explorer.rs:224-320; raises ValueError on bad input."""
    model = checker.model()
    fps_str = fp_path.rstrip("/")
    parts = [p for p in fps_str.split("/") if p != ""]
    fps = []
    for part in parts:
        try:
            fps.append(int(part))
        except ValueError:
            raise ValueError(f"Unable to parse fingerprints {fps_str}")

    results = []
    # The property view is per-checker, not per-successor; discovery paths
    # are reconstructed by re-execution, so compute it once per request.
    properties = _properties_view(checker)
    if not fps:
        for state in model.init_states():
            fp = model.fingerprint(state)
            checker.check_fingerprint(fp)
            try:
                svg = model.as_svg(Path.from_fingerprints(model, [fp]))
            except NondeterminismError:
                svg = None
            results.append(
                {
                    "action": None,
                    "outcome": None,
                    "state": repr(state),
                    "fingerprint": str(fp),
                    "properties": properties,
                    "svg": svg,
                }
            )
        return results

    last_state = Path.final_state(model, fps)
    if last_state is None:
        raise ValueError(f"Unable to find state following fingerprints {fps_str}")
    actions: List[Any] = []
    model.actions(last_state, actions)
    for action in actions:
        outcome = model.format_step(last_state, action)
        state = model.next_state(last_state, action)
        if state is None:
            # "Action ignored" is still returned for debugging
            # (src/checker/explorer.rs:299-306).
            results.append(
                {
                    "action": model.format_action(action),
                    "outcome": None,
                    "state": None,
                    "properties": properties,
                    "svg": None,
                }
            )
            continue
        fp = model.fingerprint(state)
        checker.check_fingerprint(fp)
        try:
            svg = model.as_svg(Path.from_fingerprints(model, fps + [fp]))
        except NondeterminismError:
            svg = None
        results.append(
            {
                "action": model.format_action(action),
                "outcome": outcome,
                "state": repr(state),
                "fingerprint": str(fp),
                "properties": properties,
                "svg": svg,
            }
        )
    return results


def serve(builder, address, block: bool = True, engine: str = "on_demand",
          **engine_kwargs):
    """Serve the Explorer; returns the underlying checker.

    ``address``: ``(host, port)``.  ``block=True`` (reference behavior,
    src/checker/explorer.rs:163-165) serves forever on the calling thread;
    ``block=False`` serves on a background thread and returns immediately
    (the checker gains ``explorer_server`` and ``explorer_address``
    attributes for shutdown and port discovery).

    ``engine``: ``"on_demand"`` (reference behavior — the checker expands
    only what the user browses, ``check_fingerprint`` following each
    click) or ``"tpu"`` — an exhaustive TPU wavefront run proceeds in the
    background while the UI browses its live counts; state views are
    host-re-executed either way, and discovery paths appear in the status
    once the device run completes.  Extra kwargs go to the spawn call.
    """
    snapshot = _Snapshot()
    if engine == "on_demand":
        checker = builder.visitor(snapshot).spawn_on_demand(**engine_kwargs)
    elif engine == "tpu":
        # Deliberately NO snapshot visitor: a visitor forces the traced
        # per-wave loop (docs/OBSERVABILITY.md), which would slow the
        # exhaustive background run the UI is watching.  The recent-path
        # pane stays empty; live counts come from /.status and /.metrics.
        checker = builder.spawn_tpu(**engine_kwargs)
    else:
        raise ValueError(f"unknown explorer engine {engine!r}")
    return serve_checker(checker, address, block=block, snapshot=snapshot)


def serve_checker(checker, address, block: bool = True, snapshot=None):
    """Serve the Explorer UI over an EXISTING checker — the attach path
    the checking service uses to open a browser on a completed job's
    checker (serve/server.py ``POST /jobs/<id>/explore``) without
    re-running the check.  ``snapshot`` is the recent-path sampling
    visitor when the caller wired one into the spawn; state views are
    host-re-executed against the checker's model exactly as in
    :func:`serve`."""
    snapshot = snapshot or _Snapshot()

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):  # quiet
            pass

        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, obj) -> None:
            self._send(200, json.dumps(obj).encode(), "application/json")

        def do_GET(self) -> None:
            url, _, querystr = self.path.partition("?")
            if url == "/":
                url = "/index.htm"
            if url in ("/index.htm", "/app.js", "/app.css"):
                f = _UI_DIR / url[1:]
                ctype = {
                    ".htm": "text/html",
                    ".js": "text/javascript",
                    ".css": "text/css",
                }[f.suffix]
                self._send(200, f.read_bytes(), ctype)
            elif url == "/.status":
                try:
                    self._send_json(_status_view(checker, snapshot))
                except Exception as e:  # surface, don't reset the connection
                    self._send(500, str(e).encode(), "text/plain")
            elif url == "/.metrics":
                # The live observability surface beside /.status: the
                # checker's metrics() snapshot (counts for every engine;
                # the device engines add wave cadence, table occupancy,
                # device-call totals, the always-on vitals histograms,
                # and — traced — the roofline summary).  JSON by
                # default; ``?format=prometheus`` (or a scraper's
                # Accept header) selects the standard text exposition
                # (obs/prometheus.py).  Names: docs/OBSERVABILITY.md.
                from urllib.parse import parse_qsl

                from ..obs.prometheus import (
                    CONTENT_TYPE, render_prometheus, wants_prometheus,
                )

                try:
                    query = dict(parse_qsl(querystr))
                    m = checker.metrics()
                    if wants_prometheus(
                        query, self.headers.get("Accept")
                    ):
                        self._send(
                            200, render_prometheus(m).encode(),
                            CONTENT_TYPE,
                        )
                    else:
                        self._send_json(m)
                except Exception as e:
                    self._send(500, str(e).encode(), "text/plain")
            elif url.startswith("/.states"):
                try:
                    self._send_json(_state_views(checker, url[len("/.states"):]))
                except ValueError as e:
                    self._send(404, str(e).encode(), "text/plain")
            else:
                self._send(404, b"", "text/plain")

        def do_POST(self) -> None:
            if self.path == "/.runtocompletion":
                checker.run_to_completion()
                self._send(200, b"", "text/plain")
            else:
                self._send(404, b"", "text/plain")

    server = ThreadingHTTPServer(tuple(address), Handler)
    checker.explorer_server = server
    checker.explorer_address = server.server_address
    if block:
        server.serve_forever()
    else:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
    return checker
