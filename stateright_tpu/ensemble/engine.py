"""The vmapped chaos-ensemble engine.

One device dispatch evaluates **K independent fault schedules** against a
compiled actor-style model: the ``parallel/simulation_tpu`` walk body is
vmapped over ``(walker key, member fault parameters)``, with
:class:`FateLaneHook` masking deliverable FIFO lanes by each member's
exact host fate stream (``fate.py``).  Member parameters are dispatch
*inputs* (link-seed limbs, uint32 thresholds, partition step-windows,
horizon), so shrink candidates re-verify without recompiling.

Device→host bridge, in order:

1. a member "fails on device" when its walk latches an ALWAYS-property
   violation (for the register workloads that is the *same* exact
   linearizability DP the checker uses, evaluated per walked state);
2. the auto-shrinker minimizes the failing schedule — horizon prefix and
   per-kind rate zeroing re-verified on device, duplicate/delay/partition
   zeroing re-verified by host replay (those kinds never mask a lane
   on device, so only the host can vouch for dropping them);
3. the member's seed replays through the host ``FaultyTransport`` +
   ``LiveAuditor`` path (``run_chaos_register_system``) — bit-identical
   fault schedule by the fate-function purity argument — and only a
   host-REJECTED history counts as a confirmed failing seed.  The replay
   journals the ``audit`` event whose ``fault_links`` table is the
   attribution evidence, and the run journals ``ensemble_repro`` with
   everything needed to rebuild the repro from that event alone
   (:func:`replay_repro`).

Device fault semantics (documented contract, docs/CHAOS_ENSEMBLES.md):
a masked lane holds its head and *consumes one fate index per step* —
the device image of the host's ordered-reliable-link retransmitting a
dropped/held datagram, where every retransmission is a fresh datagram
index on the link.  Drop and reorder both mask (a reorder-hold delays
delivery; a drop delays it until a retransmission survives); duplicate
and delay never mask — they exist on device only as schedule parameters
carried to the host replay.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..runtime.chaos import FATE_DROP, FATE_REORDER, ChaosSpec
from ..runtime.journal import as_journal
from .fate import (
    device_fault_fate,
    link_seed_limbs,
    partition_cuts,
    rate_threshold,
)
from .schedule import EnsembleSchedule, derive_schedule

NO_STEP = 0xFFFFFFFF

# Shrink candidates verified on device (they mask lanes) vs by host
# replay (they only shape the host transport's schedule).
_DEVICE_KINDS = ("drop", "reorder")
_REPLAY_KINDS = ("duplicate", "delay")


class FateLaneHook:
    """``build_walk`` fault hook: per-step lane masking by the member's
    fault schedule, consulting the exact host fate stream."""

    def __init__(self, n_lanes: int):
        self.n_lanes = n_lanes

    def init(self, params):
        import jax.numpy as jnp

        del params
        # Per-lane datagram counters: the (src, dst) link's next fate index.
        return jnp.zeros((self.n_lanes,), jnp.uint32)

    def apply(self, t, state, valid, n_ctr, params):
        import jax.numpy as jnp

        del state
        drop_fate = device_fault_fate(
            params["link_hi"], params["link_lo"], n_ctr, FATE_DROP
        )
        reorder_fate = device_fault_fate(
            params["link_hi"], params["link_lo"], n_ctr, FATE_REORDER
        )
        cut = partition_cuts(
            params["src_group"], params["dst_group"], t,
            params["part_at"], params["part_heal"],
        )
        masked = (
            cut
            | params["drop_always"] | (drop_fate < params["drop_thr"])
            | params["reorder_always"] | (reorder_fate < params["reorder_thr"])
        )
        new_valid = valid & ~masked & (t < params["horizon"])
        # One datagram attempt per deliverable lane per step: every
        # masked attempt consumes a fate index, exactly as a host
        # retransmission would (module docstring).
        n_ctr = n_ctr + valid.astype(jnp.uint32)
        return new_valid, n_ctr


def _member_params(pairs, schedules: List[EnsembleSchedule]) -> Dict[str, np.ndarray]:
    """The dispatch-input parameter pack: one row per member, one column
    per FIFO lane (the compiled model's ``pairs``)."""
    n_lanes = len(pairs)
    k = len(schedules)
    out = {
        "link_hi": np.zeros((k, n_lanes), np.uint32),
        "link_lo": np.zeros((k, n_lanes), np.uint32),
        "drop_thr": np.zeros((k, n_lanes), np.uint32),
        "drop_always": np.zeros((k, n_lanes), np.bool_),
        "reorder_thr": np.zeros((k, n_lanes), np.uint32),
        "reorder_always": np.zeros((k, n_lanes), np.bool_),
        "src_group": np.full((k, n_lanes), -1, np.int32),
        "dst_group": np.full((k, n_lanes), -1, np.int32),
        "part_at": np.zeros((k,), np.int32),
        "part_heal": np.full((k,), -1, np.int32),
        "horizon": np.zeros((k,), np.int32),
    }
    for mi, sch in enumerate(schedules):
        group_of: Dict[int, int] = {}
        if sch.spec.partitions:
            for gi, g in enumerate(sch.spec.partitions[0].groups):
                for node in g:
                    group_of[node] = gi
        for li, (src, dst, _depth, _off) in enumerate(pairs):
            hi, lo = link_seed_limbs(sch.seed, src, dst)
            out["link_hi"][mi, li] = hi
            out["link_lo"][mi, li] = lo
            f = sch.spec.faults_for(src, dst)
            thr, always = rate_threshold(f.drop)
            out["drop_thr"][mi, li] = thr
            out["drop_always"][mi, li] = always
            thr, always = rate_threshold(f.reorder)
            out["reorder_thr"][mi, li] = thr
            out["reorder_always"][mi, li] = always
            out["src_group"][mi, li] = group_of.get(src, -1)
            out["dst_group"][mi, li] = group_of.get(dst, -1)
        out["part_at"][mi] = sch.partition_at
        out["part_heal"][mi] = sch.partition_heal
        out["horizon"][mi] = sch.steps
    return out


def _zero_kind(spec: ChaosSpec, kind: str) -> ChaosSpec:
    def z(f):
        if kind == "delay":
            return dataclasses.replace(f, delay=(0.0, 0.0))
        return dataclasses.replace(f, **{kind: 0.0})

    return ChaosSpec(
        default=z(spec.default),
        links=tuple((k, z(f)) for k, f in spec.links),
        partitions=spec.partitions,
    )


def _spec_is_meaningful(spec: ChaosSpec, kind: str) -> bool:
    """Is there anything to shrink for this kind?"""
    faults = [spec.default] + [f for _k, f in spec.links]
    if kind == "delay":
        return any(f.delay[1] > 0 for f in faults)
    return any(getattr(f, kind) > 0 for f in faults)


@dataclass
class EnsembleResult:
    """One ensemble run: the sweep, the failing members, and (when a
    failure was found) the shrunk + host-confirmed repro."""

    members: int
    steps: int
    seed: int
    workload: str
    fault: Optional[str]
    states_walked: int = 0
    elapsed_sec: float = 0.0
    schedules_per_sec: float = 0.0
    ttff_sec: Optional[float] = None  # time to first failing seed
    failing: List[dict] = field(default_factory=list)
    confirmed: List[dict] = field(default_factory=list)
    shrink_steps: int = 0
    repro: Optional[dict] = None
    dispatches: int = 1

    def to_dict(self) -> dict:
        return {
            "members": self.members,
            "steps": self.steps,
            "seed": self.seed,
            "workload": self.workload,
            "fault": self.fault,
            "states_walked": self.states_walked,
            "elapsed_sec": round(self.elapsed_sec, 3),
            "schedules_per_sec": round(self.schedules_per_sec, 1),
            "ttff_sec": self.ttff_sec,
            "failing": self.failing,
            "confirmed": self.confirmed,
            "shrink_steps": self.shrink_steps,
            "repro": self.repro,
            "dispatches": self.dispatches,
        }


def _abd_model(client_count: int, fault: Optional[str]):
    from ..actor import Network
    from ..models.abd import AbdModelCfg

    return AbdModelCfg(
        client_count=client_count,
        server_count=2,
        network=Network.new_ordered(),
        fault=fault,
    ).into_model()


def replay_schedule(
    sch: EnsembleSchedule,
    *,
    fault: Optional[str] = None,
    client_count: int = 2,
    put_count: int = 1,
    journal=None,
    deadline_sec: float = 8.0,
    quiesce_sec: float = 0.75,
) -> dict:
    """Host replay of one member schedule: the same seed through the
    real ``FaultyTransport`` + ``LiveAuditor`` stack — the confirmation
    oracle, and the producer of the journaled ``audit`` event whose
    ``fault_links`` table is the repro's attribution evidence."""
    from ..actor.register import RegisterServer
    from ..models.abd import (
        AbdActor,
        AckQuery,
        AckRecord,
        NULL_VALUE,
        Query,
        Record,
    )
    from ..actor.register import Internal
    from ..runtime.chaos import run_chaos_register_system
    from ..semantics import LinearizabilityTester, Register

    return run_chaos_register_system(
        lambda peers: RegisterServer(AbdActor(peers, fault=fault)),
        server_count=2,
        client_count=client_count,
        put_count=put_count,
        spec=sch.spec,
        seed=sch.seed,
        tester_factory=lambda: LinearizabilityTester(Register(NULL_VALUE)),
        wire_types=(Internal, Query, AckQuery, Record, AckRecord),
        journal=journal,
        deadline_sec=deadline_sec,
        quiesce_sec=quiesce_sec,
    )


def replay_repro(repro: dict, *, journal=None, deadline_sec: float = 8.0,
                 quiesce_sec: float = 0.75) -> dict:
    """Rebuild and replay a repro from its ``ensemble_repro`` journal
    payload ALONE — no reference to the ensemble run that found it."""
    sch = EnsembleSchedule.from_repro(repro)
    return replay_schedule(
        sch,
        fault=repro.get("fault"),
        client_count=int(repro.get("client_count", 2)),
        put_count=int(repro.get("put_count", 1)),
        journal=journal,
        deadline_sec=deadline_sec,
        quiesce_sec=quiesce_sec,
    )


def run_ensemble(
    *,
    members: int = 1024,
    seed: int = 0,
    chaos=None,
    steps: int = 64,
    fault: Optional[str] = None,
    client_count: int = 2,
    put_count: int = 1,
    journal=None,
    shrink: bool = True,
    replay: bool = True,
    max_replays: int = 3,
    replay_deadline_sec: float = 8.0,
    replay_quiesce_sec: float = 0.75,
    max_journaled_failures: int = 32,
    device=None,
) -> EnsembleResult:
    """Sweep ``members`` independent fault schedules over the compiled
    ABD workload in one device dispatch; shrink and host-confirm the
    best failing member.  ``chaos`` is the base ChaosSpec (object, dict,
    or JSON string) each member's effective spec derives from;
    ``fault`` forwards to the replicas (``"skip_ack"`` is the
    known-violating workload).  See the module docstring for the
    device→host bridge semantics."""
    import jax

    spec = chaos if isinstance(chaos, ChaosSpec) else ChaosSpec.from_json(chaos)
    journal = as_journal(journal)
    model = _abd_model(client_count, fault)
    from ..models.abd_compiled import AbdCompiled
    from ..parallel.simulation_tpu import build_walk

    cm = AbdCompiled(model)
    if not cm.ordered:
        raise ValueError("the ensemble engine needs the ordered FIFO fabric")
    props = model.properties()
    from ..core.model import Expectation

    always_idx = [
        i for i, p in enumerate(props)
        if p.expectation is Expectation.ALWAYS
    ]

    schedules = [
        derive_schedule(seed, m, spec, steps) for m in range(members)
    ]
    params_np = _member_params(cm.pairs, schedules)

    result = EnsembleResult(
        members=members, steps=steps, seed=int(seed),
        workload="abd", fault=fault,
    )
    if journal is not None:
        journal.append(
            "ensemble_start",
            members=members, seed=int(seed), steps=steps,
            workload="abd", fault=fault, client_count=client_count,
            spec=spec.to_dict(),
        )

    dev = device or jax.devices()[0]
    with jax.default_device(dev):
        import jax.numpy as jnp

        walk = build_walk(cm, props, steps, fault_hook=FateLaneHook(len(cm.pairs)))
        batch = jax.jit(jax.vmap(walk))
        keys = jax.vmap(
            lambda w: jax.random.fold_in(jax.random.PRNGKey(int(seed)), w)
        )(np.arange(members))
        params = {k: jnp.asarray(v) for k, v in params_np.items()}

        t0 = time.monotonic()
        _trace, disc_dev, counted_dev, _appended, flag_dev = batch(keys, params)
        disc = np.asarray(disc_dev)  # blocks: the dispatch is done here
        elapsed = time.monotonic() - t0
        counted = np.asarray(counted_dev)
        if bool(np.asarray(flag_dev).any()):
            raise RuntimeError(
                "the model step kernel flagged an encoding-capacity "
                "overflow during an ensemble sweep"
            )

        result.states_walked = int(counted.sum())
        result.elapsed_sec = elapsed
        result.schedules_per_sec = members / elapsed if elapsed > 0 else 0.0

        # Failing members: any ALWAYS-property latch.
        fail_step = np.full(members, NO_STEP, np.uint32)
        fail_prop = np.full(members, -1, np.int32)
        for p in always_idx:
            col = disc[:, p]
            better = col < fail_step
            fail_prop = np.where(better, p, fail_prop)
            fail_step = np.minimum(fail_step, col)
        failing_members = np.flatnonzero(fail_step != NO_STEP)
        if len(failing_members):
            result.ttff_sec = round(elapsed, 3)
        for mi in failing_members:
            entry = {
                "member": int(mi),
                "seed": schedules[mi].seed,
                "property": props[int(fail_prop[mi])].name,
                "step": int(fail_step[mi]),
            }
            result.failing.append(entry)
            if journal is not None and len(result.failing) <= max_journaled_failures:
                journal.append("ensemble_failing", **entry)
        if journal is not None:
            journal.append(
                "ensemble_sweep",
                members=members,
                failing=len(result.failing),
                states=result.states_walked,
                elapsed_sec=round(elapsed, 3),
                schedules_per_sec=round(result.schedules_per_sec, 1),
                ttff_sec=result.ttff_sec,
            )
        if not len(failing_members):
            return result

        # --- shrink the earliest-latching failing member ------------------
        best = int(failing_members[np.argmin(fail_step[failing_members])])
        best_prop = props[int(fail_prop[best])].name
        sch = schedules[best]
        single = jax.jit(walk)
        best_key = jax.random.fold_in(jax.random.PRNGKey(int(seed)), best)

        def verify(candidate: EnsembleSchedule) -> bool:
            """Re-run ONE member on device; True if it still fails."""
            row_np = _member_params(cm.pairs, [candidate])
            row = {k: jnp.asarray(v[0]) for k, v in row_np.items()}
            _t, d, _c, _a, f = single(best_key, row)
            if bool(np.asarray(f)):
                return False
            d = np.asarray(d)
            return any(int(d[p]) != NO_STEP for p in always_idx)

        if shrink:
            # 1. Horizon prefix: the latch step bounds the needed walk.
            cand = dataclasses.replace(sch, steps=int(fail_step[best]) + 1)
            ok = verify(cand)
            result.shrink_steps += 1
            if journal is not None:
                journal.append(
                    "ensemble_shrink", member=best, candidate="prefix",
                    steps=cand.steps, accepted=ok,
                )
            if ok:
                sch = cand
            # 2. Per-kind rate zeroing, device-verified.
            for kind in _DEVICE_KINDS:
                if not _spec_is_meaningful(sch.spec, kind):
                    continue
                cand = dataclasses.replace(sch, spec=_zero_kind(sch.spec, kind))
                ok = verify(cand)
                result.shrink_steps += 1
                if journal is not None:
                    journal.append(
                        "ensemble_shrink", member=best, candidate=kind,
                        accepted=ok,
                    )
                if ok:
                    sch = cand

    # --- host replay: confirmation + replay-verified shrink ----------------
    def do_replay(candidate: EnsembleSchedule) -> dict:
        return replay_schedule(
            candidate,
            fault=fault,
            client_count=client_count,
            put_count=put_count,
            journal=journal,
            deadline_sec=replay_deadline_sec,
            quiesce_sec=replay_quiesce_sec,
        )

    repro_context = {
        "workload": "abd",
        "fault": fault,
        "client_count": client_count,
        "put_count": put_count,
        "server_count": 2,
        "property": best_prop,
        "base_seed": int(seed),
    }
    if replay:
        replays = 0
        verdict = do_replay(sch)
        replays += 1
        rejected = not verdict["consistent"]
        if journal is not None:
            journal.append(
                "ensemble_replay", member=best, seed=sch.seed,
                consistent=verdict["consistent"],
                violations=len(verdict.get("violations", [])),
            )
        if rejected and shrink:
            # Replay-verified shrink for the kinds the device can't vouch
            # for (they never mask a lane): duplicate, delay, partitions.
            for kind in _REPLAY_KINDS:
                if replays >= max_replays:
                    break
                if not _spec_is_meaningful(sch.spec, kind):
                    continue
                cand = dataclasses.replace(sch, spec=_zero_kind(sch.spec, kind))
                v = do_replay(cand)
                replays += 1
                ok = not v["consistent"]
                result.shrink_steps += 1
                if journal is not None:
                    journal.append(
                        "ensemble_shrink", member=best, candidate=kind,
                        accepted=ok,
                    )
                if ok:
                    sch, verdict = cand, v
            if replays < max_replays and sch.spec.partitions:
                cand = dataclasses.replace(
                    sch,
                    spec=ChaosSpec(
                        default=sch.spec.default, links=sch.spec.links,
                        partitions=(),
                    ),
                    partition_at=-1, partition_heal=-1,
                )
                v = do_replay(cand)
                replays += 1
                ok = not v["consistent"]
                result.shrink_steps += 1
                if journal is not None:
                    journal.append(
                        "ensemble_shrink", member=best,
                        candidate="partitions", accepted=ok,
                    )
                if ok:
                    sch, verdict = cand, v
        if rejected:
            result.confirmed.append(
                {
                    "member": best,
                    "seed": sch.seed,
                    "property": best_prop,
                    "invoked": verdict.get("invoked", 0),
                    "returned": verdict.get("returned", 0),
                    "violations": len(verdict.get("violations", [])),
                    "fault_links": verdict.get("fault_links", {}),
                }
            )
    result.repro = {**sch.to_repro(), **repro_context}
    if journal is not None:
        journal.append("ensemble_repro", **result.repro)
    return result
