"""Device implementation of the host fault-fate function.

``runtime/chaos.py`` decides the fate of the n-th datagram on a directed
link as a pure function of ``(seed, src, dst, n)``: a counter-mode
splitmix64 finalizer evaluated at counter ``4n + k + 1`` over the link
seed, top 32 bits kept (``fault_fate_u32``).  This module is the same
function transcribed to uint32 limb arithmetic (TPUs have no u64 vector
lanes — the (hi, lo) pair idiom of ``ops/device_fp.py``), so a vmapped
ensemble step can evaluate the *identical* fault schedule the host
``FaultyTransport`` would inject.  That bit-equality is the load-bearing
bridge of the chaos-ensemble engine: any failing seed found on device
replays exactly in the host transport + ``LiveAuditor`` path.

Why the compare transfers exactly (the purity/rounding argument, also in
docs/CHAOS_ENSEMBLES.md): the host draws are ``fate / 2**32`` — exact in
float64, since dividing a 32-bit integer by a power of two only adjusts
the exponent — and the host decision is ``draw < rate``.  For integer
``fate``, ``fate / 2**32 < rate  ⟺  fate < ceil(rate * 2**32)``, and
``rate * 2**32`` is itself exact in float64.  :func:`rate_threshold`
computes that ceiling once on host; the device compares uint32 words.
The one edge is ``ceil(rate * 2**32) == 2**32`` (rates within 2**-32 of
1.0), which does not fit a uint32 threshold — ``rate_threshold`` returns
a separate ``always`` flag for it.

Partition windows are handled at a different layer: host windows are
measured in elapsed *wall time* (explicitly excluded from the host
reproducibility guarantee), so the ensemble engine assigns each member a
deterministic step-indexed window instead and :func:`partition_cuts`
evaluates the same group-crossing predicate ``Partition.cuts`` applies.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax.numpy as jnp

from ..runtime.chaos import (  # noqa: F401  (re-exported for callers)
    FATE_DELAY,
    FATE_DRAWS,
    FATE_DROP,
    FATE_DUPLICATE,
    FATE_REORDER,
    _link_rng_seed,
)

_U32 = jnp.uint32
_MASK32 = 0xFFFFFFFF

# splitmix64 constants, split into uint32 limbs.
_GAMMA_HI, _GAMMA_LO = 0x9E3779B9, 0x7F4A7C15  # 0x9E3779B97F4A7C15
_MIX1_HI, _MIX1_LO = 0xBF58476D, 0x1CE4E5B9  # 0xBF58476D1CE4E5B9
_MIX2_HI, _MIX2_LO = 0x94D049BB, 0x133111EB  # 0x94D049BB133111EB


def _mul32x32(a, b):
    """Full 32x32 -> 64 product of uint32 arrays, as a (hi, lo) pair.

    16-bit half decomposition; every intermediate fits (or harmlessly
    wraps) in uint32."""
    a = a.astype(_U32)
    b = b.astype(_U32)
    a_lo, a_hi = a & _U32(0xFFFF), a >> _U32(16)
    b_lo, b_hi = b & _U32(0xFFFF), b >> _U32(16)
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    t = (ll >> _U32(16)) + (lh & _U32(0xFFFF)) + (hl & _U32(0xFFFF))
    lo = (ll & _U32(0xFFFF)) | ((t & _U32(0xFFFF)) << _U32(16))
    hi = hh + (lh >> _U32(16)) + (hl >> _U32(16)) + (t >> _U32(16))
    return hi, lo


def _add64(a_hi, a_lo, b_hi, b_lo):
    lo = a_lo + b_lo
    carry = (lo < a_lo).astype(_U32)
    return a_hi + b_hi + carry, lo


def _mul64_lo(a_hi, a_lo, b_hi, b_lo):
    """Low 64 bits of a 64x64 product (hi limbs wrap, as mod-2**64 does)."""
    hi, lo = _mul32x32(a_lo, b_lo)
    hi = hi + a_lo * b_hi + a_hi * b_lo
    return hi, lo


def _xorshr64(hi, lo, r: int):
    """``z ^ (z >> r)`` for 0 < r < 32 on a (hi, lo) pair."""
    return hi ^ (hi >> _U32(r)), lo ^ ((lo >> _U32(r)) | (hi << _U32(32 - r)))


def device_fault_fate(seed_hi, seed_lo, n, k):
    """The fate word for draw ``k`` of datagram ``n`` on the link whose
    64-bit seed is the ``(seed_hi, seed_lo)`` uint32 pair.

    Bit-identical to ``runtime.chaos.fault_fate_u32(link_seed, n, k)``
    for ``4n + k + 1 < 2**32`` (datagram indices far beyond any ensemble
    horizon).  All arguments broadcast; returns uint32.
    """
    c = _U32(4) * jnp.asarray(n).astype(_U32) + jnp.asarray(k).astype(_U32) + _U32(1)
    d_hi, d_lo = _mul32x32(c, _U32(_GAMMA_LO))
    d_hi = d_hi + c * _U32(_GAMMA_HI)
    z_hi, z_lo = _add64(
        jnp.asarray(seed_hi).astype(_U32), jnp.asarray(seed_lo).astype(_U32),
        d_hi, d_lo,
    )
    z_hi, z_lo = _xorshr64(z_hi, z_lo, 30)
    z_hi, z_lo = _mul64_lo(z_hi, z_lo, _U32(_MIX1_HI), _U32(_MIX1_LO))
    z_hi, z_lo = _xorshr64(z_hi, z_lo, 27)
    z_hi, z_lo = _mul64_lo(z_hi, z_lo, _U32(_MIX2_HI), _U32(_MIX2_LO))
    z_hi, _ = _xorshr64(z_hi, z_lo, 31)
    return z_hi


def link_seed_limbs(seed: int, src: int, dst: int) -> Tuple[int, int]:
    """The host per-link seed (``runtime.chaos._link_rng_seed``) as the
    (hi, lo) uint32 pair the device kernel consumes."""
    s = _link_rng_seed(int(seed), src, dst)
    return (s >> 32) & _MASK32, s & _MASK32


def rate_threshold(rate: float) -> Tuple[int, bool]:
    """``(threshold, always)`` such that the host decision
    ``fate / 2**32 < rate`` equals ``always or fate < threshold`` for
    every uint32 ``fate`` — the exact-rounding bridge (module docstring).

    ``always`` covers rates within 2**-32 of 1.0, whose ceiling (2**32)
    does not fit the uint32 threshold word."""
    rate = float(rate)
    if not 0.0 <= rate <= 1.0 or math.isnan(rate):
        raise ValueError(f"fault rate must be in [0, 1]: {rate!r}")
    thr = math.ceil(rate * 4294967296.0)  # exact: power-of-two multiply
    if thr >= 1 << 32:
        return 0, True
    return int(thr), False


def partition_cuts(src_group, dst_group, step, at_step, heal_step):
    """Device transcription of ``Partition.cuts`` with step-indexed
    windows: True where the window is active (``at_step <= step``, and
    ``step < heal_step`` unless ``heal_step < 0`` meaning never-heal)
    and src/dst sit in *different* groups (group id < 0 = in no group:
    unaffected).  All arguments broadcast int32; returns bool."""
    step = jnp.asarray(step).astype(jnp.int32)
    at_step = jnp.asarray(at_step).astype(jnp.int32)
    heal_step = jnp.asarray(heal_step).astype(jnp.int32)
    src_group = jnp.asarray(src_group).astype(jnp.int32)
    dst_group = jnp.asarray(dst_group).astype(jnp.int32)
    active = (step >= at_step) & ((heal_step < 0) | (step < heal_step))
    return (
        active
        & (src_group >= 0)
        & (dst_group >= 0)
        & (src_group != dst_group)
    )
