"""Chaos-ensemble engine: vmapped fault-schedule sweeps at device scale.

One process, one seeded fault schedule is how ``runtime/chaos.py``
explores faults; this package runs **K independent fault schedules** in a
single device dispatch by vmapping the ``parallel/simulation_tpu`` walker
loop over per-member fault parameters.  The bridge that makes device
findings actionable is ``fate.py``: a bit-exact uint32-limb transcription
of the host fault-fate function, so any failing member's seed replays
identically through the host ``FaultyTransport`` + ``LiveAuditor`` path
(``engine.py`` does that replay and journals the attribution-table
evidence).  See docs/CHAOS_ENSEMBLES.md.

Submodule imports are lazy: ``fate`` alone pulls in jax.numpy only, and
the engine's model imports stay off the path of callers that just need
the kernel (e.g. the host parity tests).
"""

_EXPORTS = {
    "device_fault_fate": "fate",
    "link_seed_limbs": "fate",
    "partition_cuts": "fate",
    "rate_threshold": "fate",
    "EnsembleSchedule": "schedule",
    "member_seed": "schedule",
    "EnsembleResult": "engine",
    "replay_repro": "engine",
    "run_ensemble": "engine",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module

    return getattr(import_module(f".{module}", __name__), name)
