"""Per-member fault-schedule derivation — pure functions of the seed.

An ensemble member is fully described by ``(base_seed, member index,
base ChaosSpec, step horizon)``: the member's 64-bit transport seed is a
splitmix64 finalizer of the base seed at counter ``member + 1``
(:func:`member_seed`), and its effective chaos spec scales every fault
rate of the base spec by per-member unit-interval factors drawn from
that seed (:func:`member_spec`).  Nothing is sampled statefully, so the
``ensemble_repro`` journal event — which records the member seed and the
*effective* spec — rebuilds the exact host transport schedule with no
reference to the ensemble run that found it (docs/CHAOS_ENSEMBLES.md,
"Repro artifact").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

from ..runtime.chaos import (
    _MASK64,
    _SPLITMIX_GAMMA,
    ChaosSpec,
    LinkFaults,
    fault_fate_u32,
)

# Draw positions for the member-level parameters, on the member seed
# itself (link fate streams run on per-link seeds derived from it, so
# the streams never collide).  n=0 holds the four rate scales at the
# FATE_* slots; n=1 holds the device partition-window draws.
_N_SCALES = 0
_N_PARTITION = 1


def _splitmix64(z: int) -> int:
    z &= _MASK64
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
    return z ^ (z >> 31)


def member_seed(base_seed: int, member: int) -> int:
    """The member's 64-bit transport seed: counter-mode splitmix64 over
    the base seed — the same generator family as the fate function, so
    the whole ensemble derives from one integer."""
    return _splitmix64(
        (int(base_seed) + (int(member) + 1) * _SPLITMIX_GAMMA) & _MASK64
    )


def _scales(seed: int) -> Tuple[float, float, float, float]:
    """Per-member rate multipliers (drop, reorder, duplicate, delay),
    each uniform in [0, 1) — the ensemble's intensity diversification."""
    return tuple(
        fault_fate_u32(seed, _N_SCALES, k) / 4294967296.0 for k in range(4)
    )


def _scale_faults(f: LinkFaults, s: Tuple[float, float, float, float]) -> LinkFaults:
    return LinkFaults(
        drop=f.drop * s[0],
        reorder=f.reorder * s[1],
        duplicate=f.duplicate * s[2],
        delay=(f.delay[0] * s[3], f.delay[1] * s[3]),
    )


def member_spec(base: ChaosSpec, seed: int) -> ChaosSpec:
    """The member's effective chaos spec: every rate (default and
    per-link overrides) scaled by the member's factors; partition
    *groups* pass through (their device step-windows are drawn
    separately — host windows stay wall-time, see partition_window)."""
    s = _scales(seed)
    return ChaosSpec(
        default=_scale_faults(base.default, s),
        links=tuple((k, _scale_faults(f, s)) for k, f in base.links),
        partitions=base.partitions,
    )


def partition_window(seed: int, steps: int) -> Tuple[int, int]:
    """The member's device partition window, in step units: a start in
    [0, steps) and a heal at start + [1, steps-start] (or -1 = never
    heals, when the second draw lands in its top eighth).  Host windows
    are wall-time and excluded from the host reproducibility guarantee,
    so the device sweep diversifies its own step-indexed windows
    instead."""
    steps = max(1, int(steps))
    w0 = fault_fate_u32(seed, _N_PARTITION, 0)
    w1 = fault_fate_u32(seed, _N_PARTITION, 1)
    at = w0 % steps
    if w1 >= (7 << 29):  # top eighth: permanent partition
        return at, -1
    return at, at + 1 + w1 % (steps - at)


@dataclass(frozen=True)
class EnsembleSchedule:
    """One member's complete, self-contained schedule description."""

    member: int
    seed: int  # the member's 64-bit transport seed
    spec: ChaosSpec  # the member's EFFECTIVE (scaled) spec
    steps: int  # walk horizon (and shrink dimension)
    partition_at: int = -1  # device window, step units (-1: no window)
    partition_heal: int = -1

    def to_repro(self) -> dict:
        """The ``ensemble_repro`` payload: everything a later process
        needs to rebuild the host transport schedule, with no reference
        to the run that found it."""
        return {
            "member": self.member,
            "seed": self.seed,
            "spec": self.spec.to_dict(),
            "steps": self.steps,
            "partition_at": self.partition_at,
            "partition_heal": self.partition_heal,
        }

    @staticmethod
    def from_repro(d: dict) -> "EnsembleSchedule":
        return EnsembleSchedule(
            member=int(d["member"]),
            seed=int(d["seed"]),
            spec=ChaosSpec.from_json(d["spec"]),
            steps=int(d["steps"]),
            partition_at=int(d.get("partition_at", -1)),
            partition_heal=int(d.get("partition_heal", -1)),
        )


def derive_schedule(
    base_seed: int,
    member: int,
    base_spec: Optional[ChaosSpec],
    steps: int,
) -> EnsembleSchedule:
    """Member ``member``'s schedule — THE pure function the whole
    subsystem leans on: same (base_seed, member, base spec, steps),
    same schedule, on every host and every run."""
    base_spec = base_spec if base_spec is not None else ChaosSpec()
    seed = member_seed(base_seed, member)
    at, heal = (
        partition_window(seed, steps) if base_spec.partitions else (-1, -1)
    )
    return EnsembleSchedule(
        member=member,
        seed=seed,
        spec=member_spec(base_spec, seed),
        steps=int(steps),
        partition_at=at,
        partition_heal=heal,
    )
