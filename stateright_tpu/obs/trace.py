"""Per-wave phase-timed trace spans for the wavefront engines.

With ``trace=True`` an engine runs each wave as separately-dispatched
phase programs and hands the tracer one ``(phase -> seconds, phase ->
bytes)`` record per wave.  The tracer:

- enriches the engine's journal ``wave`` event with ``wave_breakdown``
  (seconds per phase), ``bytes`` (modeled bytes touched per phase) and
  ``hbm_util_frac`` for that wave;
- accumulates run totals, reduced by :meth:`WaveTracer.summary` into the
  shape ``bench.py`` and ``Checker.metrics()`` emit.

Phase names are part of the observable surface (docs/OBSERVABILITY.md):

====================  =======================================================
``step``              chunk slice + step kernel (successor expansion,
                      property conds, valid-lane compaction)
``canon``             canonicalization (identity when symmetry is off) +
                      fingerprinting of the candidate buffer
``dedup``             sort pre-dedup + claim-plane probe rounds + table
                      insert (parallel/hashset.py)
``exchange``          owner bucketing + the packed all_to_all (sharded
                      engine only; elided on a 1-shard mesh)
``append``            row/parent/ebits block appends at the log tail
``readback``          host-side scalar sync + (visitor runs) the chunk
                      state transfer — host time, excluded from HBM util
====================  =======================================================
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from .roofline import hbm_util_frac, peaks_for_device

# Canonical display order; engines may omit phases they don't have.
# ``cold_probe`` is the tiered engines' pre-commit merge-join against
# the evicted runs (host searchsorted + device window filter).
PHASE_ORDER = (
    "step", "canon", "dedup", "exchange", "cold_probe", "append",
    "readback",
)

# Host-side phases: excluded from the HBM-utilization denominator (they
# are not device time) but included in wave/call wall time.  Public so
# consumers picking a "bottleneck" phase (bench.py) can exclude the
# trace instrumentation's own cost the same way.
HOST_PHASES = frozenset({"readback", "cold_probe"})
_HOST_PHASES = HOST_PHASES


class WaveTracer:
    """Accumulates per-wave phase records into run totals.

    One engine host loop writes (``record_wave``); ``summary()`` may be
    called concurrently from any thread — the Explorer's ``/.metrics``
    handler polls it mid-run — so both sides serialize on an internal
    lock (a per-wave lock acquisition is noise next to a device
    dispatch).
    """

    def __init__(self, device, engine: str):
        self.engine = engine
        self.peaks = peaks_for_device(device)
        self.waves = 0
        self.phase_sec: Dict[str, float] = {}
        self.phase_bytes: Dict[str, int] = {}
        self._extra_totals: Dict[str, float] = {}
        self._lock = threading.Lock()

    def record_wave(
        self,
        phases: Dict[str, float],
        bytes_touched: Optional[Dict[str, int]] = None,
        **extra_counters: float,
    ) -> dict:
        """Fold one wave's record into the totals; returns the journal
        enrichment for that wave (``wave_breakdown`` / ``bytes`` /
        ``hbm_util_frac``).  ``extra_counters`` accumulate into the
        summary (e.g. the sharded engine's per-wave exchange payload)."""
        bytes_touched = bytes_touched or {}
        with self._lock:
            self.waves += 1
            for name, sec in phases.items():
                self.phase_sec[name] = self.phase_sec.get(name, 0.0) + sec
            for name, b in bytes_touched.items():
                self.phase_bytes[name] = (
                    self.phase_bytes.get(name, 0) + int(b)
                )
            for name, v in extra_counters.items():
                self._extra_totals[name] = (
                    self._extra_totals.get(name, 0) + v
                )
        device_sec = sum(
            s for n, s in phases.items() if n not in _HOST_PHASES
        )
        util = hbm_util_frac(
            sum(bytes_touched.values()), device_sec,
            self.peaks["hbm_bytes_per_sec"],
        )
        record = {
            "wave_breakdown": {
                n: round(phases[n], 6)
                for n in PHASE_ORDER if n in phases
            },
            "hbm_util_frac": round(util, 6),
        }
        if bytes_touched:
            record["bytes"] = {
                n: int(bytes_touched[n])
                for n in PHASE_ORDER if n in bytes_touched
            }
        record.update(
            {k: round(v, 6) if isinstance(v, float) else v
             for k, v in extra_counters.items()}
        )
        return record

    def summary(self) -> dict:
        """Run-total reduction: phase seconds (and each phase's fraction
        of traced wall time), modeled bytes, and the aggregate
        ``hbm_util_frac`` over device phases.  Safe to call from any
        thread mid-run (snapshots under the tracer lock)."""
        with self._lock:
            waves = self.waves
            phase_sec = dict(self.phase_sec)
            phase_bytes = dict(self.phase_bytes)
            extra = dict(self._extra_totals)
        total = sum(phase_sec.values())
        device_sec = sum(
            s for n, s in phase_sec.items() if n not in _HOST_PHASES
        )
        out = {
            "engine": self.engine,
            "traced_waves": waves,
            "traced_sec": round(total, 4),
            "wave_breakdown": {
                n: round(phase_sec[n], 4)
                for n in PHASE_ORDER if n in phase_sec
            },
            "wave_breakdown_frac": {
                n: round(phase_sec[n] / total, 4)
                for n in PHASE_ORDER if n in phase_sec
            } if total > 0 else {},
            "bytes": {
                n: int(phase_bytes[n])
                for n in PHASE_ORDER if n in phase_bytes
            },
            "hbm_util_frac": round(
                hbm_util_frac(
                    sum(phase_bytes.values()), device_sec,
                    self.peaks["hbm_bytes_per_sec"],
                ), 6,
            ),
            "hbm_peak_bytes_per_sec": self.peaks["hbm_bytes_per_sec"],
            "hbm_peak_estimated": self.peaks["estimated"],
            "device_kind": self.peaks["device_kind"],
        }
        # The roofline verdict, named: the dominant DEVICE phase (host
        # readback is the trace instrumentation's own cost, excluded
        # like the HBM denominator above).  Part of the `trace:` line
        # check-tpu --trace prints, so supervised children surface it
        # without journal digging.
        device_phases = {
            n: s for n, s in phase_sec.items() if n not in _HOST_PHASES
        }
        if device_phases:
            out["bottleneck_phase"] = max(
                device_phases, key=device_phases.get
            )
        out.update({
            k: round(v, 4) if isinstance(v, float) else v
            for k, v in extra.items()
        })
        return out
