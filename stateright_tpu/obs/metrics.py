"""A small thread-safe metrics registry, with histograms.

Every checker carries one (the engine host loop writes, the Explorer's
``GET /.metrics`` endpoint and ``Checker.metrics()`` read).  Deliberately
minimal — flat names, numeric values, one lock — because the write side
sits on the engine host loop: a wave record is a handful of dict stores,
never a device sync.  Metric names are part of the observable surface and
documented in docs/OBSERVABILITY.md; changing one is a breaking change to
anything scraping ``/.metrics``.

Histograms are fixed-boundary (Prometheus classic style: cumulative
``le`` buckets plus ``sum``/``count``) so an observation is one bisect
and one integer increment — cheap enough for the always-on fused-loop
vitals — and the snapshot carries a p50/p95/p99 readback estimated by
linear interpolation inside the owning bucket.  The snapshot shape
(``boundaries``/``counts``/``sum``/``count``/``p50``/``p95``/``p99``)
is what obs/prometheus.py renders as ``_bucket``/``_sum``/``_count``
series.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, Optional, Sequence, Union

Number = Union[int, float]

# Shared boundary ladders (seconds / counts).  Latency buckets span the
# observed range of one fused device call — sub-millisecond on a local
# CPU backend up to tens of seconds for a tunneled-device quantum.
LATENCY_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)
# Waves-between-growth-events ladder (powers of two, like the geometry).
COUNT_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 2048, 4096)
# Fractions-of-a-buffer ladder (valid density vs the worst-case U
# buffer, hot-table load factor): log-spaced below 10% — where the
# measured densities actually live (docs/OBSERVABILITY.md "Density
# telemetry") — then coarse to 1.0.
FRACTION_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.2, 0.3, 0.4, 0.5, 0.75, 1.0,
)

# The live-vitals subset of ``Checker.metrics()`` that per-job / per-run
# status surfaces embed (serve ``GET /jobs/{id}``, the Explorer's
# ``/.status``): progress + health, small enough to poll without
# shipping the whole snapshot.  One definition so the two surfaces and
# docs/SERVING.md cannot drift.
VITALS_KEYS = (
    "unique_state_count", "state_count", "max_depth", "waves",
    "uniq_per_sec_ema", "waves_per_sec_ema", "table_load_factor",
    "valid_density_ema", "grows", "overflow_retries",
)


def vitals_view(checker):
    """The :data:`VITALS_KEYS` subset of ``checker.metrics()``, or None
    when it cannot be read (a checker mid-teardown whose device buffers
    are already freed must never break a status snapshot).  The one
    extraction both embedding surfaces share."""
    try:
        m = checker.metrics()
    except Exception:
        return None
    return {k: m[k] for k in VITALS_KEYS if k in m}


class Histogram:
    """Fixed-boundary cumulative histogram with quantile readback.

    ``boundaries`` are the bucket upper bounds (ascending); one implicit
    ``+Inf`` bucket catches the tail.  Not self-locking: the owning
    :class:`MetricsRegistry` serializes access under its lock.
    """

    def __init__(self, boundaries: Sequence[float]):
        b = tuple(float(x) for x in boundaries)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(
                "histogram boundaries must be strictly ascending"
            )
        self.boundaries = b
        self.counts = [0] * (len(b) + 1)  # last = +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: Number, count: int = 1) -> None:
        """Fold ``count`` observations of ``value`` (the weighted form
        lets the wave loop record one quantum as waves_per_call equal
        per-wave latencies with a single call)."""
        self.counts[bisect_left(self.boundaries, float(value))] += count
        self.sum += float(value) * count
        self.count += count

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (0..1): find the bucket holding the
        rank, interpolate linearly inside it (Prometheus
        ``histogram_quantile`` semantics; the +Inf bucket reports its
        lower bound)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            if acc + c >= rank:
                lo = self.boundaries[i - 1] if i > 0 else 0.0
                if i >= len(self.boundaries):
                    return lo  # +Inf bucket: report its lower bound
                hi = self.boundaries[i]
                return lo + (hi - lo) * max(0.0, rank - acc) / c
            acc += c
        return self.boundaries[-1]

    def snapshot(self) -> dict:
        return {
            "boundaries": list(self.boundaries),
            "counts": list(self.counts),
            "sum": round(self.sum, 6),
            "count": self.count,
            "p50": round(self.quantile(0.50), 6),
            "p95": round(self.quantile(0.95), 6),
            "p99": round(self.quantile(0.99), 6),
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        """Rehydrate a histogram from its :meth:`snapshot` dict — the
        fleet metrics merge (fleet/service.py) folds per-worker
        snapshots shipped through journal ``fleet_worker_vitals``
        events back into live histograms this way."""
        h = cls(snap["boundaries"])
        counts = list(snap.get("counts") or ())
        if len(counts) != len(h.counts):
            raise ValueError(
                "snapshot counts do not match boundaries "
                f"({len(counts)} buckets for {len(h.counts)} expected)"
            )
        h.counts = [int(c) for c in counts]
        h.sum = float(snap.get("sum", 0.0))
        h.count = int(snap.get("count", 0))
        return h

    def merge(self, snap: dict) -> None:
        """Bucket-wise addition of another histogram's snapshot.  Only
        identical boundary ladders merge — the ladders are module
        constants shared by every writer, so a mismatch means two
        incompatible schema versions, surfaced loudly rather than
        silently misbinned."""
        if tuple(float(b) for b in snap["boundaries"]) != self.boundaries:
            raise ValueError("histogram boundary ladders differ")
        counts = list(snap.get("counts") or ())
        if len(counts) != len(self.counts):
            raise ValueError("histogram bucket counts differ")
        for i, c in enumerate(counts):
            self.counts[i] += int(c)
        self.sum += float(snap.get("sum", 0.0))
        self.count += int(snap.get("count", 0))


def merge_histogram_snapshots(*snaps: Dict[str, dict]) -> Dict[str, dict]:
    """Merge several ``{name: histogram-snapshot}`` maps bucket-wise
    into one (quantiles recomputed from the summed buckets).
    Commutative and associative by construction — bucket addition is —
    which the fleet ``/.metrics`` merge relies on: the merged view must
    not depend on worker enumeration order (pinned in
    tests/test_timeline.py)."""
    merged: Dict[str, Histogram] = {}
    for snap_map in snaps:
        for name in sorted(snap_map or {}):
            snap = snap_map[name]
            if not isinstance(snap, dict) or "boundaries" not in snap:
                continue
            h = merged.get(name)
            if h is None:
                merged[name] = Histogram.from_snapshot(snap)
            else:
                h.merge(snap)
    return {n: h.snapshot() for n, h in merged.items()}


class MetricsRegistry:
    """Flat name -> value store with counter and gauge semantics.

    ``inc`` accumulates (counters: monotone over a run), ``set``
    overwrites (gauges: last-value-wins).  ``snapshot()`` returns a plain
    dict copy safe to serialize while writers keep running.
    """

    def __init__(self, **initial: Number):
        self._lock = threading.Lock()
        self._values: Dict[str, Number] = dict(initial)
        self._hists: Dict[str, Histogram] = {}

    def inc(self, name: str, delta: Number = 1) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0) + delta

    def set(self, name: str, value: Number) -> None:
        with self._lock:
            self._values[name] = value

    def update(self, **values: Number) -> None:
        """Set several gauges under one lock acquisition (the per-wave
        hot path writes ~10 values)."""
        with self._lock:
            self._values.update(values)

    def get(self, name: str, default: Optional[Number] = None):
        with self._lock:
            return self._values.get(name, default)

    def observe(
        self,
        name: str,
        value: Number,
        count: int = 1,
        boundaries: Sequence[float] = LATENCY_BUCKETS,
    ) -> None:
        """Record ``value`` into the named histogram, creating it with
        ``boundaries`` on first use (later calls keep the original
        boundaries — one ladder per name for the life of the
        registry)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(boundaries)
            h.observe(value, count)

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._lock:
            return self._hists.get(name)

    def snapshot(self) -> Dict[str, Number]:
        with self._lock:
            return dict(self._values)

    def snapshot_histograms(self) -> Dict[str, dict]:
        """Plain-dict copies of every histogram (the ``histograms`` key
        of ``Checker.metrics()``; obs/prometheus.py renders them as
        ``_bucket``/``_sum``/``_count`` series)."""
        with self._lock:
            return {n: h.snapshot() for n, h in self._hists.items()}


# Process-global registry for counters that outlive any one checker —
# the compiled-program cache's hit/miss counters in particular
# (parallel/wave_common.cached_program), which are the measured evidence
# behind the serving layer's warm-start story: a second identical job
# reuses the first job's compiled programs, so its hit counter moves and
# its warmup does not (docs/SERVING.md).  Served by the check service's
# aggregated ``GET /.metrics`` (serve/server.py).
GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    return GLOBAL
