"""A small thread-safe metrics registry.

Every checker carries one (the engine host loop writes, the Explorer's
``GET /.metrics`` endpoint and ``Checker.metrics()`` read).  Deliberately
minimal — flat names, numeric values, one lock — because the write side
sits on the engine host loop: a wave record is a handful of dict stores,
never a device sync.  Metric names are part of the observable surface and
documented in docs/OBSERVABILITY.md; changing one is a breaking change to
anything scraping ``/.metrics``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Union

Number = Union[int, float]


class MetricsRegistry:
    """Flat name -> value store with counter and gauge semantics.

    ``inc`` accumulates (counters: monotone over a run), ``set``
    overwrites (gauges: last-value-wins).  ``snapshot()`` returns a plain
    dict copy safe to serialize while writers keep running.
    """

    def __init__(self, **initial: Number):
        self._lock = threading.Lock()
        self._values: Dict[str, Number] = dict(initial)

    def inc(self, name: str, delta: Number = 1) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0) + delta

    def set(self, name: str, value: Number) -> None:
        with self._lock:
            self._values[name] = value

    def update(self, **values: Number) -> None:
        """Set several gauges under one lock acquisition (the per-wave
        hot path writes ~10 values)."""
        with self._lock:
            self._values.update(values)

    def get(self, name: str, default: Optional[Number] = None):
        with self._lock:
            return self._values.get(name, default)

    def snapshot(self) -> Dict[str, Number]:
        with self._lock:
            return dict(self._values)


# Process-global registry for counters that outlive any one checker —
# the compiled-program cache's hit/miss counters in particular
# (parallel/wave_common.cached_program), which are the measured evidence
# behind the serving layer's warm-start story: a second identical job
# reuses the first job's compiled programs, so its hit counter moves and
# its warmup does not (docs/SERVING.md).  Served by the check service's
# aggregated ``GET /.metrics`` (serve/server.py).
GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    return GLOBAL
