"""Roofline accounting: per-device peaks and the bytes-touched model.

The wave loop is bandwidth-bound, not compute-bound: every phase is
sorts, gathers, scatters, and block copies over uint32 planes, with a few
integer ALU ops per word.  So the roofline that matters is the HBM one —
``hbm_util_frac`` is the fraction of the device's peak HBM bandwidth the
measured wave achieved, computed as ``modeled bytes touched / (measured
seconds x peak bytes/sec)``.

The byte model is ANALYTIC, derived from the engine's static shapes and
the per-wave counts the host reads back anyway — TPUs expose no
per-kernel DRAM counters through JAX, and the model is what lets the
breakdown say *which phase* to optimize (a sort pass at 40% of peak is
healthy; a probe round at 2% says the gathers dominate).  Modeling
choices, documented here because `hbm_util_frac` inherits them:

- the XLA TPU sort is modeled as a bitonic network: ``k(k+1)/2`` passes
  for ``k = ceil(log2(lanes))``, each pass streaming every key plane
  once in and once out.  This is an upper-bound pass count; real XLA
  sorts fuse stages, so sort bytes (and util) may overestimate by a
  small constant factor;
- random-index gathers/scatters are charged their payload bytes only
  (lanes x word), not the touched-cacheline amplification — on TPU the
  serialization cost of scatter shows up as *time*, which the measured
  denominator already carries;
- phase wall-times come from ``block_until_ready`` around each phase
  dispatch, so they include per-dispatch launch overhead — with
  ``trace=True`` the loop is deliberately un-fused, and utilization reads
  LOWER than the fused ``trace=False`` loop achieves.  The breakdown's
  *relative* shape is the signal; docs/OBSERVABILITY.md discusses the
  bias.

Peaks are public per-chip numbers keyed by JAX ``device_kind``; unknown
devices (including the CPU backend the tests run on) fall back to a
conservative estimate flagged ``estimated`` so a util number can never
masquerade as a measured-hardware claim.
"""

from __future__ import annotations

import math
from typing import Dict

# Public per-chip peak HBM bandwidth, bytes/sec.  Keys are matched as
# case-insensitive substrings of jax's ``device.device_kind``.
DEVICE_PEAKS: Dict[str, float] = {
    "v6e": 1.64e12,      # Trillium: 1,640 GB/s
    "v5p": 2.765e12,     # 2,765 GB/s
    "v5e": 8.19e11,      # 819 GB/s
    "v5 lite": 8.19e11,  # v5e's device_kind spells it out
    "v4": 1.228e12,      # 1,228 GB/s
    "v3": 9.0e11,        # 900 GB/s
    "v2": 7.0e11,        # 700 GB/s
}

# Fallback for unknown/CPU devices: a conservative host-DRAM figure so
# the ratio stays meaningful on the virtual CPU meshes the tests run on.
_FALLBACK_PEAK = 2.0e10  # 20 GB/s


def peaks_for_device(device) -> Dict:
    """Peak table entry for a JAX device: ``{"device_kind", "platform",
    "hbm_bytes_per_sec", "estimated"}``.  ``estimated`` is True whenever
    the kind did not match the table — util fractions derived from an
    estimated peak are labeled as such everywhere they surface."""
    kind = str(getattr(device, "device_kind", "") or "")
    platform = str(getattr(device, "platform", "") or "")
    low = kind.lower()
    for key, peak in DEVICE_PEAKS.items():
        if key in low:
            return {
                "device_kind": kind,
                "platform": platform,
                "hbm_bytes_per_sec": peak,
                "estimated": False,
            }
    return {
        "device_kind": kind or platform or "unknown",
        "platform": platform,
        "hbm_bytes_per_sec": _FALLBACK_PEAK,
        "estimated": True,
    }


def hbm_util_frac(bytes_touched: float, seconds: float,
                  peak_bytes_per_sec: float) -> float:
    """Achieved fraction of peak HBM bandwidth; 0.0 for degenerate
    inputs (a wave too fast to time is reported as unknown-low, never
    infinite)."""
    if seconds <= 0 or peak_bytes_per_sec <= 0:
        return 0.0
    return float(bytes_touched) / (seconds * peak_bytes_per_sec)


def sort_passes(lanes: int) -> int:
    """Bitonic-network pass count for a ``lanes``-wide sort."""
    if lanes <= 1:
        return 0
    k = max(1, math.ceil(math.log2(lanes)))
    return k * (k + 1) // 2


def sort_bytes(lanes: int, planes: int, word_bytes: int = 4) -> int:
    """Bytes streamed by sorting ``planes`` co-sorted u32 planes of
    ``lanes`` elements: every pass reads and writes every plane once."""
    return 2 * sort_passes(lanes) * planes * lanes * word_bytes


def probe_bytes(lanes: int, rounds: int, word_bytes: int = 4) -> int:
    """Bytes touched by ``rounds`` claim-plane probe rounds over a
    ``lanes``-wide key buffer (parallel/hashset.py stage 2/3): per round
    each unresolved lane gathers both key planes (2 reads), contends the
    claim plane (1 scatter + 1 gather-back), and winners scatter both key
    words (2 writes) — 6 lane-words a round, charging every lane as
    unresolved (an upper bound; resolved lanes drop out of later
    rounds)."""
    return 6 * max(0, rounds) * lanes * word_bytes


def copy_bytes(lanes: int, width: int, word_bytes: int = 4) -> int:
    """Read+write bytes of moving ``lanes`` rows of ``width`` u32 words
    (gathers and block appends both stream payload in and out)."""
    return 2 * lanes * width * word_bytes
