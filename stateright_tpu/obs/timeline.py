"""Unified timeline: host-tail span decomposition + Perfetto export.

Three pieces, one clock discipline (docs/OBSERVABILITY.md "Timeline
export and profiling"):

- :class:`SpanRecorder` — the zero-new-readback span layer the fused
  host loop (parallel/wave_loop.py) threads through its per-quantum
  tail: every named sub-phase (``journal`` append, ``checkpoint``
  write, tiered ``spill`` drain, sort/step ``retune``, overflow
  ``grow``, the previous record's own ``flush`` write) is timed with
  two ``time.monotonic()`` calls and journaled as ONE ``host_span``
  event per quantum plus per-phase ``host_<phase>_sec`` histograms —
  so ``host_sec_total`` decomposes into named parts.  Engines report
  in-call host work (the ``readback`` decode, the tiered engine's
  ``cold_probe`` windowing) through the same record under
  ``call_spans``.  No device traffic anywhere: the trace=False fused
  program stays byte-for-byte pinned.

- :func:`export_timeline` — fold any run / serve / fleet journal
  (or several) into Chrome trace-event JSON loadable in Perfetto /
  ``chrome://tracing``: one process track per ``pid@host`` worker
  stamp (aligned via the journal's ``clock_sync`` wall+monotonic
  epoch, runtime/journal.py), device-call and host-tail slices, job
  spans, and job/gang flow arrows submit -> claim -> dispatch ->
  result.  :func:`validate_trace` is the CI/test gate (well-nested
  ``X`` slices per track, balanced ``B``/``E``, resolving flow ids).

- xprof hooks — ``check-tpu --xprof-dir`` flips :func:`set_xprof`;
  the loops then wrap each quantum in
  ``jax.profiler.StepTraceAnnotation`` and the recorder mirrors every
  host span as a ``jax.profiler.TraceAnnotation`` named exactly like
  the journal phase, so a hardware profile aligns with the journal
  timeline for free.
"""

from __future__ import annotations

import json
import os
import socket
import time
from contextlib import contextmanager, nullcontext
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ..runtime.journal import (
    CLOCK_SYNC_EVENT, read_journal_stats,
)

# The journal event one fused-loop quantum's host tail folds into.
SPAN_EVENT = "host_span"

# Host-tail sub-phases (between device calls; their durations sum — with
# the residual ``other`` and the previous record's own ``flush`` write —
# to the quantum's ``host_sec`` gap, the same gap LoopVitals accounts
# into ``host_sec_total``).
TAIL_PHASES = ("journal", "spill", "retune", "checkpoint", "grow", "other")
# Spans measured INSIDE the device-call window (host-observed, but part
# of ``device_call_sec_total``, not the host tail): the stats readback
# decode and the tiered engine's host-side cold windowing.
CALL_PHASES = ("readback", "cold_probe")
# Run-scoped one-shot spans (outside the wave loop): knob-cache writes.
ONESHOT_PHASES = ("knob_cache",)

_US = 1_000_000.0


def default_worker() -> str:
    """The ``pid@host`` worker stamp (same shape as fleet/store.py)."""
    return f"{os.getpid()}@{socket.gethostname()}"


# --- hardware profiler hooks -----------------------------------------------

_xprof_on = False


def set_xprof(enabled: bool) -> None:
    """Process-wide xprof toggle (``check-tpu --xprof-dir``): loops
    started after this wrap quanta in ``StepTraceAnnotation`` and
    mirror host spans as ``TraceAnnotation``s.  Process-wide because
    ``jax.profiler.start_trace`` is."""
    global _xprof_on
    _xprof_on = bool(enabled)


def xprof_enabled() -> bool:
    return _xprof_on


def step_annotation(step: int, name: str = "wave_quantum"):
    """A ``jax.profiler.StepTraceAnnotation`` for one loop quantum when
    xprof is on; a no-op context otherwise (or when jax's profiler is
    unavailable) — the loops call this unconditionally."""
    if not _xprof_on:
        return nullcontext()
    try:
        from jax.profiler import StepTraceAnnotation
    except Exception:
        return nullcontext()
    return StepTraceAnnotation(name, step_num=int(step))


def phase_annotation(name: str):
    """A named ``jax.profiler.TraceAnnotation`` when xprof is on —
    host spans carry the SAME names into the hardware profile as into
    the journal, so the two timelines align by string."""
    if not _xprof_on:
        return nullcontext()
    try:
        from jax.profiler import TraceAnnotation
    except Exception:
        return nullcontext()
    return TraceAnnotation(str(name))


# --- the span layer ---------------------------------------------------------


class SpanRecorder:
    """Per-quantum host-tail accounting for the fused loop.

    The loop marks the tail start (:meth:`tail_start`, right after the
    device call returns), wraps each named tail section in
    :meth:`span`, and closes the quantum at the top of the next
    iteration (:meth:`quantum_start`) — the SAME boundary
    ``LoopVitals.call_started`` accounts into ``host_sec_total``, so
    the journaled decomposition and the counter agree by construction.
    The flush write itself (one journal line) lands in the NEXT
    record as the ``flush`` span, positioned at its true (earlier)
    monotonic time, so no tail microsecond goes unattributed.

    Every timestamp is host ``time.monotonic()``; there is no device
    traffic and no new readback.
    """

    def __init__(self, journal=None, metrics=None,
                 worker: Optional[str] = None):
        self._journal = journal
        self._metrics = metrics
        self._worker = worker or default_worker()
        self._tail_mark: Optional[float] = None
        self._spans: List[Tuple[str, float, float]] = []
        self._call_spans: List[Tuple[str, float, float]] = []
        self._quantum = 0
        self._xprof = xprof_enabled()

    @contextmanager
    def span(self, phase: str):
        """Time one named section; in-call phases (:data:`CALL_PHASES`)
        are kept apart from the tail decomposition."""
        ann = phase_annotation(f"host/{phase}") if self._xprof else None
        if ann is not None:
            ann.__enter__()
        t0 = time.monotonic()
        try:
            yield
        finally:
            dur = time.monotonic() - t0
            if ann is not None:
                ann.__exit__(None, None, None)
            dest = (
                self._call_spans if phase in CALL_PHASES else self._spans
            )
            dest.append((phase, t0, dur))

    def step(self):
        """The per-quantum ``StepTraceAnnotation`` wrapper (no-op
        unless xprof is on)."""
        self._quantum += 1
        if not self._xprof:
            return nullcontext()
        return step_annotation(self._quantum)

    def collect(self, eng) -> None:
        """Fold in-call host spans the engine measured itself (the
        optional ``_wl_host_spans()`` hook: e.g. the tiered engine's
        cold-run windowing inside ``_wl_call``)."""
        hook = getattr(eng, "_wl_host_spans", None)
        if hook is None:
            return
        for phase, t0, dur in hook() or ():
            dest = (
                self._call_spans if phase in CALL_PHASES else self._spans
            )
            dest.append((str(phase), float(t0), float(dur)))

    def tail_start(self, now: float) -> None:
        self._tail_mark = now

    def quantum_start(self, now: float) -> None:
        """Close the previous quantum's tail ``[tail_start, now)`` —
        called at the top of each loop iteration with the same
        timestamp handed to ``vitals.call_started``."""
        if self._tail_mark is not None:
            self._flush(now)

    def finish(self, now: float) -> float:
        """Close the final tail at loop exit; returns its seconds so
        the loop can fold them into ``host_sec_total``
        (``LoopVitals.record_host``) — the last tail has no next call
        to account it otherwise."""
        if self._tail_mark is None:
            return 0.0
        return self._flush(now)

    def _flush(self, now: float) -> float:
        tail = max(0.0, now - self._tail_mark)
        spans: Dict[str, List[float]] = {}
        for phase, t0, dur in self._spans:
            rel = t0 - self._tail_mark
            cur = spans.get(phase)
            if cur is None:
                spans[phase] = [rel, dur]
            else:
                cur[0] = min(cur[0], rel)
                cur[1] += dur
        in_tail = sum(v[1] for k, v in spans.items() if v[0] >= 0.0)
        other = max(0.0, tail - in_tail)
        spans["other"] = [max(0.0, tail - other), other]
        call_spans: Dict[str, List[float]] = {}
        for phase, t0, dur in self._call_spans:
            rel = t0 - self._tail_mark
            cur = call_spans.get(phase)
            if cur is None:
                call_spans[phase] = [rel, dur]
            else:
                cur[0] = min(cur[0], rel)
                cur[1] += dur
        t_flush0 = time.monotonic()
        if self._metrics is not None:
            from .metrics import LATENCY_BUCKETS

            for phase, (_rel, dur) in spans.items():
                self._metrics.observe(
                    f"host_{phase}_sec", dur, boundaries=LATENCY_BUCKETS
                )
            for phase, (_rel, dur) in call_spans.items():
                self._metrics.observe(
                    f"host_{phase}_sec", dur, boundaries=LATENCY_BUCKETS
                )
        if self._journal is not None:
            self._journal.append(
                SPAN_EVENT,
                quantum=self._quantum,
                worker=self._worker,
                mono=round(self._tail_mark, 6),
                host_sec=round(tail, 6),
                spans={
                    k: [round(v[0], 6), round(v[1], 6)]
                    for k, v in spans.items()
                },
                **(
                    {"call_spans": {
                        k: [round(v[0], 6), round(v[1], 6)]
                        for k, v in call_spans.items()
                    }} if call_spans else {}
                ),
            )
        flush_dur = time.monotonic() - t_flush0
        self._spans = [("flush", t_flush0, flush_dur)]
        self._call_spans = []
        self._tail_mark = None
        return tail


def record_oneshot_span(journal, metrics, phase: str, sec: float,
                        **fields) -> None:
    """A run-scoped host span outside the wave loop (knob-cache
    writes): one ``host_span`` event with ``scope="run"`` — excluded
    from the per-quantum tail reconciliation — plus the same
    ``host_<phase>_sec`` histogram."""
    sec = max(0.0, float(sec))
    if metrics is not None:
        from .metrics import LATENCY_BUCKETS

        metrics.observe(f"host_{phase}_sec", sec,
                        boundaries=LATENCY_BUCKETS)
    if journal is not None:
        journal.append(
            SPAN_EVENT, scope="run", worker=default_worker(),
            host_sec=round(sec, 6),
            spans={phase: [0.0, round(sec, 6)]}, **fields,
        )


def host_share_of(metrics: Dict) -> Optional[float]:
    """``host_sec_total / (host_sec_total + device_call_sec_total)`` —
    the ROADMAP #2 regression gauge; None when the metrics cannot say."""
    try:
        h = float(metrics.get("host_sec_total"))
        d = float(metrics.get("device_call_sec_total"))
    except (TypeError, ValueError):
        return None
    if h < 0 or d <= 0:
        return None
    return h / (h + d)


def host_tail_sums(events: Iterable[Dict]) -> Dict[str, float]:
    """Per-phase summed seconds over a journal's per-quantum
    ``host_span`` events (run-scoped one-shots excluded) — the
    reconciliation side of the ``host_sec_total`` counter."""
    sums: Dict[str, float] = {}
    for e in events:
        if e.get("event") != SPAN_EVENT or e.get("scope") == "run":
            continue
        for phase, rel_dur in (e.get("spans") or {}).items():
            try:
                sums[phase] = sums.get(phase, 0.0) + float(rel_dur[1])
            except (TypeError, IndexError, ValueError):
                continue
    return sums


# --- the exporter -----------------------------------------------------------

_SUBMIT_EVENTS = frozenset({"fleet_submitted", "job_submitted"})
_STEP_EVENTS = frozenset({
    "fleet_claimed", "fleet_requeued", "fleet_lease", "gang_dispatch",
    "job_running", "fleet_preempted",
})
_FINISH_EVENTS = frozenset({
    "fleet_done", "fleet_failed", "fleet_cancelled",
    "job_done", "job_failed", "job_cancelled",
})
_FLOW_EVENTS = _SUBMIT_EVENTS | _STEP_EVENTS | _FINISH_EVENTS

_TID_DEVICE = 1
_TID_HOST = 2
_TID_JOBS = 3


def resolve_journal(path: str) -> str:
    """Accept a journal file, a run directory, or a fleet directory."""
    if os.path.isdir(path):
        for cand in (
            os.path.join(path, "journal.jsonl"),
            os.path.join(path, "fleet", "journal.jsonl"),
        ):
            if os.path.exists(cand):
                return cand
        raise FileNotFoundError(
            f"no journal.jsonl under directory {path!r}"
        )
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    return path


class _Clock:
    """Per-worker monotonic -> wall mapping from ``clock_sync`` epochs
    (the journal header event, runtime/journal.py): sound on stepping
    wall clocks because each process's offset is measured once against
    its OWN monotonic clock."""

    def __init__(self, syncs: Sequence[Dict]):
        self._by_worker: Dict[str, Tuple[float, float]] = {}
        for s in syncs:
            w = s.get("worker")
            if w and w not in self._by_worker:
                try:
                    self._by_worker[w] = (float(s["t"]), float(s["mono"]))
                except (KeyError, TypeError, ValueError):
                    continue
        self.primary: Optional[str] = (
            min(self._by_worker) if self._by_worker else None
        )

    def wall(self, worker: Optional[str], mono: Optional[float],
             fallback: float) -> float:
        if mono is not None:
            ref = self._by_worker.get(worker) or (
                self._by_worker.get(self.primary)
                if worker is None else None
            )
            if ref is not None:
                t0, m0 = ref
                return t0 + (float(mono) - m0)
        return fallback


def _pid_of(worker: str, fallback: int) -> int:
    head = str(worker).split("@", 1)[0]
    try:
        return int(head)
    except ValueError:
        return fallback


def build_trace(events: Sequence[Dict]) -> Dict:
    """Fold merged journal events into a Chrome trace-event object.

    Deterministic: tracks are keyed and sorted by worker stamp, flow
    ids are assigned in sorted-job-id order, and the final event list
    is fully sorted — exporting the same event set in any input order
    yields byte-identical JSON."""
    syncs = [e for e in events if e.get("event") == CLOCK_SYNC_EVENT]
    clock = _Clock(syncs)
    workers: Dict[str, int] = {}

    def track(worker: Optional[str]) -> Tuple[str, int]:
        w = worker or clock.primary or "run"
        if w not in workers:
            workers[w] = _pid_of(w, 100_000 + len(workers))
        return w, workers[w]

    # slices: (pid, tid, start_wall, dur_sec, name, args, children)
    slices: List[Dict] = []
    flows: List[Dict] = []
    has_spans = any(
        e.get("event") == SPAN_EVENT and e.get("scope") != "run"
        for e in events
    )
    job_points: Dict[str, List[Tuple[float, str, int, int]]] = {}

    try:
        from .trace import PHASE_ORDER
    except Exception:  # pragma: no cover - trace module is sibling
        PHASE_ORDER = ()

    for e in events:
        kind = e.get("event")
        t = float(e.get("t", 0.0))
        if kind == "wave":
            w, pid = track(e.get("worker"))
            call_sec = max(0.0, float(e.get("call_sec", 0.0)))
            start = clock.wall(w, e.get("mono"), t - call_sec)
            parent = {
                "pid": pid, "tid": _TID_DEVICE, "name": "wave",
                "start": start, "dur": call_sec,
                "args": {
                    k: e[k] for k in (
                        "waves", "depth", "unique", "flags", "occupancy",
                        "remaining",
                    ) if k in e
                },
                "children": [],
            }
            breakdown = e.get("wave_breakdown")
            if isinstance(breakdown, dict):
                order = [p for p in PHASE_ORDER if p in breakdown]
                order += sorted(k for k in breakdown if k not in order)
                at = start
                for ph in order:
                    try:
                        d = max(0.0, float(breakdown[ph]))
                    except (TypeError, ValueError):
                        continue
                    parent["children"].append({
                        "pid": pid, "tid": _TID_DEVICE, "name": ph,
                        "start": at, "dur": d, "args": {},
                    })
                    at += d
            slices.append(parent)
        elif kind == SPAN_EVENT:
            w, pid = track(e.get("worker"))
            host_sec = max(0.0, float(e.get("host_sec", 0.0)))
            start = clock.wall(w, e.get("mono"), t - host_sec)
            oneshot = e.get("scope") == "run"
            parent = {
                "pid": pid, "tid": _TID_HOST,
                "name": "knob_cache" if oneshot else "host",
                "start": start, "dur": host_sec,
                "args": {
                    k: e[k] for k in ("quantum", "job") if k in e
                },
                "children": [],
            }
            if not oneshot:
                for ph, rel_dur in sorted(
                    (e.get("spans") or {}).items()
                ):
                    try:
                        rel, dur = float(rel_dur[0]), float(rel_dur[1])
                    except (TypeError, IndexError, ValueError):
                        continue
                    if dur <= 0.0:
                        continue
                    if rel < 0.0:
                        # The previous record's flush write: a sibling
                        # slice at its true (earlier) position.
                        slices.append({
                            "pid": pid, "tid": _TID_HOST, "name": ph,
                            "start": start + rel, "dur": min(dur, -rel),
                            "args": {},
                        })
                    else:
                        parent["children"].append({
                            "pid": pid, "tid": _TID_HOST, "name": ph,
                            "start": start + rel, "dur": dur, "args": {},
                        })
                for ph, rel_dur in sorted(
                    (e.get("call_spans") or {}).items()
                ):
                    try:
                        rel, dur = float(rel_dur[0]), float(rel_dur[1])
                    except (TypeError, IndexError, ValueError):
                        continue
                    if dur <= 0.0 or rel >= 0.0:
                        continue
                    # In-call host work: before the tail, clamped so it
                    # cannot lap into the host slice.
                    slices.append({
                        "pid": pid, "tid": _TID_HOST, "name": ph,
                        "start": start + rel, "dur": min(dur, -rel),
                        "args": {},
                    })
            slices.append(parent)
        elif kind == "checkpoint" and not has_spans:
            w, pid = track(e.get("worker"))
            dur = max(0.0, float(e.get("write_sec", 0.0)))
            if dur > 0.0:
                slices.append({
                    "pid": pid, "tid": _TID_HOST, "name": "checkpoint",
                    "start": t - dur, "dur": dur, "args": {},
                })
        elif kind == "job_span":
            w, pid = track(e.get("worker"))
            dur = max(0.0, float(e.get("sec", 0.0)))
            slices.append({
                "pid": pid, "tid": _TID_JOBS,
                "name": str(e.get("span", "span")),
                "start": t - dur, "dur": dur,
                "args": {"job": e.get("job")},
            })
        if kind in _FLOW_EVENTS:
            w, pid = track(e.get("worker"))
            jids = e.get("jobs") if kind == "gang_dispatch" else None
            if jids is None:
                jids = [e.get("job")] if e.get("job") else []
            phase = (
                0 if kind in _SUBMIT_EVENTS
                else (2 if kind in _FINISH_EVENTS else 1)
            )
            for jid in jids:
                if jid is None:
                    continue
                job_points.setdefault(str(jid), []).append(
                    (t, kind, pid, phase)
                )

    # Job lifecycle anchors + flow arrows: s at the first point, t at
    # the middles, f at the last — every started flow resolves.
    flow_ids = {
        jid: i + 1 for i, jid in enumerate(sorted(job_points))
    }
    for jid, points in sorted(job_points.items()):
        points.sort()
        if len(points) < 2:
            continue
        for i, (t, kind, pid, _phase) in enumerate(points):
            slices.append({
                "pid": pid, "tid": _TID_JOBS, "name": kind,
                "start": t, "dur": 0.0, "args": {"job": jid},
            })
            ph = "s" if i == 0 else ("f" if i == len(points) - 1 else "t")
            flow = {
                "ph": ph, "id": flow_ids[jid], "pid": pid,
                "tid": _TID_JOBS, "name": "job", "cat": "job",
                "start": t,
            }
            if ph == "f":
                flow["bp"] = "e"
            flows.append(flow)

    if not slices and not flows:
        return {"traceEvents": [], "displayTimeUnit": "ms"}

    t0 = min(
        [s["start"] for s in slices]
        + [s["start"] for sl in slices for s in sl.get("children", ())]
        + [f["start"] for f in flows]
    )

    def us(x: float) -> int:
        return max(0, int(round((x - t0) * _US)))

    out: List[Dict] = []
    for w, pid in sorted(workers.items()):
        out.append({
            "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
            "args": {"name": w}, "ts": 0,
        })
        for tid, label in (
            (_TID_DEVICE, "device"), (_TID_HOST, "host"),
            (_TID_JOBS, "jobs"),
        ):
            out.append({
                "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": label}, "ts": 0,
            })
    for s in slices:
        ts, dur = us(s["start"]), max(0, int(round(s["dur"] * _US)))
        ev = {
            "ph": "X", "pid": s["pid"], "tid": s["tid"],
            "name": s["name"], "ts": ts, "dur": dur, "args": s["args"],
        }
        out.append(ev)
        end = ts + dur
        for c in s.get("children", ()):
            cts = min(max(us(c["start"]), ts), end)
            cdur = max(0, min(int(round(c["dur"] * _US)), end - cts))
            out.append({
                "ph": "X", "pid": c["pid"], "tid": c["tid"],
                "name": c["name"], "ts": cts, "dur": cdur,
                "args": c["args"],
            })
    for f in flows:
        ev = dict(f)
        ev["ts"] = us(ev.pop("start"))
        out.append(ev)

    _sanitize_nesting(out)
    out.sort(key=_sort_key)
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def _sort_key(ev: Dict) -> Tuple:
    return (
        0 if ev.get("ph") == "M" else 1,
        ev.get("ts", 0), ev.get("pid", 0), ev.get("tid", 0),
        -ev.get("dur", 0), str(ev.get("ph")), str(ev.get("name")),
        ev.get("id", 0),
    )


def _sanitize_nesting(events: List[Dict]) -> None:
    """Clamp microsecond rounding so every ``X`` slice either nests in
    or is disjoint from its track neighbours (the validator's rule)."""
    by_track: Dict[Tuple, List[Dict]] = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_track.setdefault(
                (ev.get("pid"), ev.get("tid")), []
            ).append(ev)
    for track in by_track.values():
        track.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack: List[Dict] = []
        for ev in track:
            while stack and ev["ts"] >= (
                stack[-1]["ts"] + stack[-1]["dur"]
            ):
                stack.pop()
            if stack:
                top_end = stack[-1]["ts"] + stack[-1]["dur"]
                if ev["ts"] + ev["dur"] > top_end:
                    ev["dur"] = max(0, top_end - ev["ts"])
            stack.append(ev)


def validate_trace(trace: Dict) -> List[str]:
    """Structural validation of a Chrome trace-event object; returns a
    list of problems (empty = valid).  Checks the invariants Perfetto
    and ``chrome://tracing`` rely on: every event carries ``ph``;
    ``X`` slices have nonnegative integer ``ts``/``dur`` and are
    well-nested per (pid, tid) track; ``B``/``E`` pairs balance per
    track; every flow ``s`` resolves to an ``f`` and every flow event
    lands on a slice."""
    problems: List[str] = []
    if not isinstance(trace, dict) or not isinstance(
        trace.get("traceEvents"), list
    ):
        return ["trace must be a dict with a traceEvents list"]
    try:
        json.dumps(trace)
    except (TypeError, ValueError) as exc:
        return [f"trace is not JSON-serializable: {exc}"]
    xs: Dict[Tuple, List[Dict]] = {}
    bes: Dict[Tuple, List[Dict]] = {}
    flow_phases: Dict = {}
    flow_events: List[Dict] = []
    for i, ev in enumerate(trace["traceEvents"]):
        if not isinstance(ev, dict) or "ph" not in ev:
            problems.append(f"event {i}: missing ph")
            continue
        ph = ev["ph"]
        if ph == "M":
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            problems.append(f"event {i} ({ph}): missing ts")
            continue
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "X":
            if not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0:
                problems.append(
                    f"event {i}: X slice needs dur >= 0, got "
                    f"{ev.get('dur')!r}"
                )
                continue
            xs.setdefault(key, []).append(ev)
        elif ph in ("B", "E"):
            bes.setdefault(key, []).append(ev)
        elif ph in ("s", "t", "f"):
            if "id" not in ev:
                problems.append(f"event {i}: flow {ph} without id")
                continue
            flow_phases.setdefault(ev["id"], set()).add(ph)
            flow_events.append(ev)
    for key, track in xs.items():
        track.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[Dict] = []
        for ev in track:
            while stack and ev["ts"] >= stack[-1]["ts"] + stack[-1]["dur"]:
                stack.pop()
            if stack:
                top_end = stack[-1]["ts"] + stack[-1]["dur"]
                if ev["ts"] + ev["dur"] > top_end:
                    problems.append(
                        f"track {key}: slice {ev.get('name')!r} at "
                        f"ts={ev['ts']} overlaps {stack[-1].get('name')!r} "
                        "without nesting"
                    )
            stack.append(ev)
    for key, track in bes.items():
        track.sort(key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
        depth: List[str] = []
        for ev in sorted(track, key=lambda e: e["ts"]):
            if ev["ph"] == "B":
                depth.append(str(ev.get("name")))
            elif not depth:
                problems.append(
                    f"track {key}: E without matching B at ts={ev['ts']}"
                )
            else:
                depth.pop()
        if depth:
            problems.append(
                f"track {key}: {len(depth)} unclosed B event(s)"
            )
    for fid, phases in sorted(flow_phases.items(), key=str):
        if "s" in phases and "f" not in phases:
            problems.append(f"flow id {fid!r}: started but never finishes")
        if "f" in phases and "s" not in phases:
            problems.append(f"flow id {fid!r}: finishes but never starts")
    for ev in flow_events:
        key = (ev.get("pid"), ev.get("tid"))
        ts = ev["ts"]
        if not any(
            s["ts"] <= ts <= s["ts"] + s["dur"] for s in xs.get(key, ())
        ):
            problems.append(
                f"flow {ev['ph']} id={ev.get('id')!r} at ts={ts} binds "
                f"to no slice on track {key}"
            )
    return problems


def export_timeline(paths, out: Optional[str] = None) -> Dict:
    """Export one or more journals (files, run dirs, or fleet dirs)
    into a single aligned Chrome trace-event object; write it to
    ``out`` when given.  Multi-journal merges are deterministic:
    input order never changes the output."""
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    events: List[Dict] = []
    for p in paths:
        evs, _skipped = read_journal_stats(
            resolve_journal(str(p)), include_sync=True
        )
        events.extend(evs)
    trace = build_trace(events)
    if out:
        parent = os.path.dirname(out)
        if parent:
            os.makedirs(parent, exist_ok=True)
        with open(out, "w", encoding="utf-8") as fh:
            json.dump(trace, fh, sort_keys=True)
            fh.write("\n")
    return trace


def timeline_main(args: List[str]) -> int:
    """The ``timeline`` CLI verb: ``timeline export <journal|dir>...
    [--out FILE]`` — export, validate, and report one summary line."""
    args = list(args)
    if args and args[0] == "export":
        args = args[1:]
    out = None
    paths: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--out":
            if i + 1 >= len(args):
                print("timeline: --out needs a path")
                return 2
            out = args[i + 1]
            i += 2
        elif a.startswith("--"):
            print(f"timeline: unknown flag {a!r}")
            return 2
        else:
            paths.append(a)
            i += 1
    if not paths:
        print(
            "usage: timeline export <journal.jsonl|run-dir|fleet-dir>... "
            "[--out FILE]"
        )
        return 2
    try:
        resolved = [resolve_journal(p) for p in paths]
    except FileNotFoundError as exc:
        print(f"timeline: {exc}")
        return 2
    if out is None:
        out = resolved[0] + ".trace.json"
    trace = export_timeline(paths, out=out)
    problems = validate_trace(trace)
    n_slices = sum(
        1 for e in trace["traceEvents"] if e.get("ph") == "X"
    )
    n_flows = sum(
        1 for e in trace["traceEvents"] if e.get("ph") in ("s", "t", "f")
    )
    n_tracks = len({
        e.get("pid") for e in trace["traceEvents"] if e.get("ph") != "M"
    })
    print(
        f"timeline: journals={len(resolved)} slices={n_slices} "
        f"flows={n_flows} workers={n_tracks} "
        f"valid={'yes' if not problems else 'NO'} out={out}"
    )
    for p in problems[:10]:
        print(f"timeline: problem: {p}")
    return 0 if not problems else 1
