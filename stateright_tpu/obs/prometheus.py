"""Prometheus text exposition (and a minimal validating parser).

``render_prometheus`` turns a ``Checker.metrics()`` / service
``metrics()`` dict into the classic text exposition format
(version 0.0.4) so the Explorer and the checking service plug into
standard scrapers — ``GET /.metrics?format=prometheus`` on both HTTP
surfaces (explorer/server.py, serve/server.py).  Mapping rules, applied
to each top-level key:

- numeric (or bool) value -> one ``gauge`` sample, unless the name is a
  known counter (the :data:`COUNTER_NAMES` set, or any ``*_total``
  name) -> ``counter``;
- string value -> a label on the single ``<prefix>_info`` gauge (value
  1), the idiomatic place for build/engine identity;
- histogram-shaped dict (the ``histograms`` key of ``metrics()``;
  shape from ``obs.metrics.Histogram.snapshot``) -> a ``histogram``
  family with cumulative ``_bucket{le=...}`` series plus ``_sum`` and
  ``_count`` (the estimated ``p50/p95/p99`` readbacks are dropped —
  scrapers derive quantiles from the buckets);
- flat all-numeric dict (e.g. the service's ``jobs`` state counts) ->
  one gauge family with a ``key`` label per entry;
- anything deeper (``trace_summary``, ``accounting``) is skipped: those
  stay on the JSON surface, which remains the default.

``parse_prometheus`` is the matching minimal parser — enough to
*validate* an exposition (CI's serve smoke and tests/test_report.py use
it; no external client library): it checks ``# TYPE`` declarations,
parses every sample line, and verifies histogram families carry
consistent cumulative ``_bucket``/``_sum``/``_count`` series.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Optional, Tuple

PREFIX = "stateright"
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# Monotone-over-a-run names that don't carry the _total suffix (most
# were named before the exposition existed; renames would break the
# documented JSON surface).
COUNTER_NAMES = frozenset({
    "waves", "device_calls", "grows", "overflow_retries", "spills",
    "cold_hits_total", "bucket_retries", "state_count",
    "unique_state_count", "program_cache_hits", "program_cache_misses",
    "knob_cache_hits", "knob_cache_misses", "jobs_submitted",
    "jobs_completed", "jobs_failed", "jobs_cancelled", "portfolio_wins",
    "violations_found", "unique_states_total",
})

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)$"
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _sanitize(name: str) -> str:
    out = re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))
    if not _NAME_OK.match(out):
        out = "_" + out
    return out


def _escape_label(value: str) -> str:
    return (
        str(value).replace("\\", "\\\\").replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt(value) -> str:
    v = float(value)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _is_histogram_snapshot(value) -> bool:
    return (
        isinstance(value, dict)
        and {"boundaries", "counts", "sum", "count"} <= set(value)
    )


def _render_histogram(lines: List[str], name: str, snap: dict) -> None:
    lines.append(f"# HELP {name} {name.rsplit('_', 1)[0]} distribution")
    lines.append(f"# TYPE {name} histogram")
    cum = 0
    for bound, c in zip(snap["boundaries"], snap["counts"]):
        cum += int(c)
        lines.append(f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {int(snap["count"])}')
    lines.append(f"{name}_sum {_fmt(snap['sum'])}")
    lines.append(f"{name}_count {int(snap['count'])}")


def render_prometheus(metrics: dict, prefix: str = PREFIX) -> str:
    """Render a metrics dict (see module docstring for the mapping) as
    Prometheus exposition text.  Deterministic: keys render in sorted
    order, so tests can pin the output."""
    lines: List[str] = []
    info: List[Tuple[str, str]] = []
    for key in sorted(metrics):
        value = metrics[key]
        name = f"{prefix}_{_sanitize(key)}"
        if key == "histograms" and isinstance(value, dict):
            for hname in sorted(value):
                if _is_histogram_snapshot(value[hname]):
                    _render_histogram(
                        lines, f"{prefix}_{_sanitize(hname)}", value[hname]
                    )
            continue
        if isinstance(value, bool):
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {int(value)}")
        elif isinstance(value, (int, float)):
            kind = (
                "counter"
                if key in COUNTER_NAMES or key.endswith("_total")
                else "gauge"
            )
            lines.append(f"# TYPE {name} {kind}")
            lines.append(f"{name} {_fmt(value)}")
        elif isinstance(value, str):
            info.append((_sanitize(key), value))
        elif _is_histogram_snapshot(value):
            _render_histogram(lines, name, value)
        elif isinstance(value, dict) and value and all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in value.values()
        ):
            lines.append(f"# TYPE {name} gauge")
            for k in sorted(value):
                lines.append(
                    f'{name}{{key="{_escape_label(k)}"}} {_fmt(value[k])}'
                )
        # deeper structures (trace_summary, accounting, ...) stay JSON-only
    if info:
        labels = ",".join(f'{k}="{_escape_label(v)}"' for k, v in info)
        lines.append(f"# TYPE {prefix}_info gauge")
        lines.append(f"{prefix}_info{{{labels}}} 1")
    return "\n".join(lines) + "\n"


def wants_prometheus(query: dict, accept: Optional[str]) -> bool:
    """Content negotiation for ``GET /.metrics``: the explicit
    ``?format=prometheus`` query wins; otherwise the Accept header's
    media ranges are scanned IN PREFERENCE ORDER and the first
    recognized one decides — a scraper's
    ``application/openmetrics-text, text/plain;…`` selects the text
    exposition, while a JSON client's common default
    ``application/json, text/plain, */*`` keeps JSON even though
    text/plain appears as a fallback.  JSON stays the default for
    everything else."""
    fmt = (query.get("format") or "").lower()
    if fmt:
        return fmt in ("prometheus", "openmetrics", "text")
    for part in (accept or "").lower().split(","):
        mt = part.split(";", 1)[0].strip()
        if mt in ("application/openmetrics-text", "text/plain"):
            return True
        if mt in ("application/json", "*/*"):
            return False
    return False


# --- minimal validating parser (CI smoke / tests; no new deps) ---------------


class ExpositionError(ValueError):
    pass


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Parse exposition text into ``{family: {"type": t, "samples":
    [(name, labels, value), ...]}}``, validating as it goes: unknown
    ``# TYPE``s, malformed sample lines, non-float values, and
    inconsistent histogram families (non-cumulative buckets, missing
    ``_sum``/``_count``, +Inf bucket != count) all raise
    :class:`ExpositionError`."""
    families: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                t = parts[3] if len(parts) > 3 else ""
                if t not in ("counter", "gauge", "histogram", "summary",
                             "untyped"):
                    raise ExpositionError(f"unknown TYPE {t!r}: {line}")
                types[parts[2]] = t
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ExpositionError(f"malformed sample line: {line!r}")
        name, labelstr, valstr = m.groups()
        labels = dict(_LABEL.findall(labelstr)) if labelstr else {}
        try:
            value = float(valstr.replace("+Inf", "inf"))
        except ValueError:
            raise ExpositionError(
                f"non-numeric sample value in {line!r}"
            ) from None
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base is not None and types.get(base) == "histogram":
                family = base
                break
        fam = families.setdefault(
            family, {"type": types.get(family, "untyped"), "samples": []}
        )
        fam["samples"].append((name, labels, value))
    for family, fam in families.items():
        if fam["type"] != "histogram":
            # Labeled gauge/counter families (the per-shard series:
            # ``stateright_shard_unique{key="3"}``) must be internally
            # consistent: every sample in a family carries the SAME
            # label-name set, and no two samples repeat the same label
            # set (a duplicate series is a scrape-breaking exposition).
            label_names = None
            seen = set()
            for name, labels, _v in fam["samples"]:
                names = frozenset(labels)
                if label_names is None:
                    label_names = names
                elif names != label_names:
                    raise ExpositionError(
                        f"family {family} mixes label sets "
                        f"{sorted(label_names)} and {sorted(names)}"
                    )
                sig = (name, tuple(sorted(labels.items())))
                if sig in seen:
                    raise ExpositionError(
                        f"family {family} repeats series {sig}"
                    )
                seen.add(sig)
            continue
        buckets = [
            (labels.get("le"), v)
            for n, labels, v in fam["samples"] if n.endswith("_bucket")
        ]
        sums = [v for n, _, v in fam["samples"] if n.endswith("_sum")]
        counts = [v for n, _, v in fam["samples"] if n.endswith("_count")]
        if not buckets or len(sums) != 1 or len(counts) != 1:
            raise ExpositionError(
                f"histogram {family} missing _bucket/_sum/_count series"
            )
        values = [v for _, v in buckets]
        if values != sorted(values):
            raise ExpositionError(
                f"histogram {family} buckets are not cumulative"
            )
        if buckets[-1][0] != "+Inf" or buckets[-1][1] != counts[0]:
            raise ExpositionError(
                f"histogram {family} +Inf bucket must equal _count"
            )
    return families
