"""Live journal watcher: the ``watch`` CLI verb.

``watch <journal.jsonl>`` tails a run (or service) journal — including
rotating ones, whose segments :func:`~stateright_tpu.runtime.journal.
read_journal_stats` merges — and renders a refreshing ONE-LINE progress
view: wall clock, depth, unique states, a uniq/s EMA computed over the
trailing wave events, hot-table load factor, measured valid density,
the current dedup-sort and step rungs plus the dedup path
(``dedup=sortless|sort``, from the ``geometry`` events and rung-climb/
fallback ``grow`` notes), the bottleneck phase, and warning badges
(recompile storms, rung-ladder thrash, claim-election fallback thrash,
torn lines, faults).  It reads the journal file only — never the engine — so it
watches supervised children, serve daemons, and remote runs over any
shared filesystem alike, mid-run or post-mortem.

``--once`` prints a single snapshot line and exits (the non-interactive
mode CI greps); otherwise the line refreshes every ``--interval``
seconds (default 2) until interrupted — or, for a run journal that has
reached ``engine_done``/``supervisor_done``, until the final line is
printed.  On a TTY the line redraws in place; a pipe gets one line per
refresh.
"""

from __future__ import annotations

import os
import re
import sys
import time
from typing import List, Optional

# uniq/s smoothing over the trailing wave events, mirroring the
# engines' live EMA (wave_loop.LoopVitals, alpha 0.3) so the watched
# number and the /.metrics number read alike.
EMA_ALPHA = 0.3
_EMA_TAIL = 32  # trailing wave events folded into the EMA

# Sort-rung ladder-thrash badge: this many flag-4 rung-climb retries
# inside the trailing window means the dedup-sort geometry ladder is
# thrashing (climb → downshift → climb), the condition that silently
# burns a run's budget on recompiles (docs/OBSERVABILITY.md "The
# dedup-sort rung ladder").
SORT_THRASH_WINDOW_SEC = 120.0
SORT_THRASH_RETRIES = 3


def summarize_events(events: List[dict], skipped: int = 0) -> dict:
    """Reduce a journal event list to the one-line snapshot fields."""
    from ..parallel.wave_common import (
        COMPILE_STORM_THRESHOLD, COMPILE_STORM_WINDOW_SEC,
    )

    out: dict = {"events": len(events), "warnings": []}
    if skipped:
        out["warnings"].append(f"torn-lines={skipped}")
    if not events:
        return out
    times = [e["t"] for e in events if isinstance(e.get("t"), (int, float))]
    if times:
        out["t"] = round(max(times) - min(times), 1)
        out["last_event_age"] = round(time.time() - max(times), 1)

    waves = [e for e in events if e.get("event") == "wave"]
    if waves:
        last = waves[-1]
        for k in ("unique", "depth", "waves", "remaining"):
            if k in last:
                out[k] = last[k]
        if isinstance(last.get("occupancy"), (int, float)):
            out["load_factor"] = last["occupancy"]
        dens = [
            w["density"] for w in waves
            if isinstance(w.get("density"), (int, float))
        ]
        if dens:
            out["density"] = dens[-1]
        # uniq/s EMA over the trailing segments.
        pts = [
            (w["t"], w["unique"]) for w in waves[-_EMA_TAIL:]
            if isinstance(w.get("t"), (int, float))
            and isinstance(w.get("unique"), int)
        ]
        ema: Optional[float] = None
        for (t0, u0), (t1, u1) in zip(pts, pts[1:]):
            if t1 > t0:
                rate = max(0, u1 - u0) / (t1 - t0)
                ema = rate if ema is None else ema + EMA_ALPHA * (rate - ema)
        if ema is not None:
            out["uniq_per_sec"] = round(ema, 1)
        # Bottleneck: the dominant device phase on traced journals, the
        # device/host split otherwise (obs/report.py's rule, inlined so
        # a watch tick stays O(waves), not a full report).
        from .trace import HOST_PHASES

        phases: dict = {}
        for w in waves:
            if isinstance(w.get("wave_breakdown"), dict):
                for name, sec in w["wave_breakdown"].items():
                    phases[name] = phases.get(name, 0.0) + float(sec)
        if phases:
            device = {
                k: v for k, v in phases.items() if k not in HOST_PHASES
            } or phases
            out["bottleneck"] = max(device, key=device.get)
        else:
            device = sum(float(w.get("call_sec", 0.0)) for w in waves)
            wall = (
                waves[-1]["t"] - waves[0]["t"]
                if len(waves) > 1
                and all("t" in w for w in (waves[0], waves[-1]))
                else device
            )
            out["bottleneck"] = (
                "device" if device >= max(0.0, wall - device) else "host"
            )
        # Host share: per-quantum host tail / (tail + device call), EMA
        # over the trailing quanta — the ROADMAP #2 regression gauge.
        # The ``host_span`` events (obs/timeline.py) pair each quantum's
        # measured tail with the preceding wave's call_sec; traced
        # journals without them fall back to the wave_breakdown's
        # host-classed phases.
        from .timeline import SPAN_EVENT

        ratios: List[float] = []
        last_call: Optional[float] = None
        for e in events:
            ev = e.get("event")
            if ev == "wave":
                c = e.get("call_sec")
                last_call = float(c) if isinstance(c, (int, float)) else None
            elif (ev == SPAN_EVENT and e.get("scope") != "run"
                    and last_call):
                h = e.get("host_sec")
                if isinstance(h, (int, float)) and h >= 0:
                    ratios.append(h / (h + last_call))
        if not ratios and phases:
            host = sum(v for k, v in phases.items() if k in HOST_PHASES)
            total = sum(phases.values())
            if total > 0:
                ratios.append(host / total)
        hs_ema: Optional[float] = None
        for r in ratios[-_EMA_TAIL:]:
            hs_ema = (
                r if hs_ema is None else hs_ema + EMA_ALPHA * (r - hs_ema)
            )
        if hs_ema is not None:
            out["host_share"] = round(hs_ema, 4)
            if hs_ema > 0.5:
                out["warnings"].append(f"host-share={round(hs_ema, 2)}")

    # Actor/chaos journals (runtime/chaos.py, actor/obs.py): the
    # periodic ``actor_stats`` stream gives a msgs/s EMA + retransmit
    # counters; injected ``chaos_*`` faults, ``orl_give_up``, an active
    # partition window, and a rejected audit raise ⚠ badges.
    stats = [e for e in events if e.get("event") == "actor_stats"]
    if stats:
        last = stats[-1]
        for k in ("datagrams", "invoked", "returned", "retransmits"):
            if k in last:
                out[k] = last[k]
        pts = [
            (e["t"], e["datagrams"]) for e in stats[-_EMA_TAIL:]
            if isinstance(e.get("t"), (int, float))
            and isinstance(e.get("datagrams"), int)
        ]
        ema = None
        for (t0, d0), (t1, d1) in zip(pts, pts[1:]):
            if t1 > t0:
                rate = max(0, d1 - d0) / (t1 - t0)
                ema = rate if ema is None else ema + EMA_ALPHA * (rate - ema)
        if ema is not None:
            out["msgs_per_sec"] = round(ema, 1)
        if last.get("partition_active"):
            out["partition_active"] = True
            out["warnings"].append("partition-active")
    spans = sum(1 for e in events if e.get("event") == "actor_span")
    if spans:
        out["spans"] = spans
    chaos_faults = sum(
        1 for e in events
        if str(e.get("event", "")).startswith("chaos_")
        and e.get("event") not in ("chaos_start", "chaos_summary")
    )
    if chaos_faults:
        out["chaos_faults"] = chaos_faults
    give_ups = sum(1 for e in events if e.get("event") == "orl_give_up")
    if give_ups:
        out["orl_give_ups"] = give_ups
        out["warnings"].append(f"orl-give-ups={give_ups}")
    audits = [e for e in events if e.get("event") == "audit"]
    if audits:
        out["audit_consistent"] = bool(audits[-1].get("consistent"))
        out["done"] = True  # the audit verdict is a chaos run's last word
        if not out["audit_consistent"]:
            out["warnings"].append("audit-inconsistent")

    # Chaos-ensemble journals (ensemble/engine.py,
    # docs/CHAOS_ENSEMBLES.md): members swept, failing seeds, shrink
    # progress, and whether a repro landed.  In an ensemble journal an
    # INCONSISTENT replay audit is the *goal* (the host confirming a
    # device-found failing seed), so the audit warning is withdrawn and
    # the repro badge speaks instead.
    starts = [e for e in events if e.get("event") == "ensemble_start"]
    if starts:
        out["ensemble_members"] = starts[-1].get("members")
        sweeps = [e for e in events if e.get("event") == "ensemble_sweep"]
        if sweeps:
            out["ensemble_failing"] = sweeps[-1].get("failing")
            if sweeps[-1].get("schedules_per_sec") is not None:
                out["schedules_per_sec"] = sweeps[-1]["schedules_per_sec"]
        shrinks = [e for e in events if e.get("event") == "ensemble_shrink"]
        if shrinks:
            out["ensemble_shrinks"] = len(shrinks)
            out["ensemble_shrinks_accepted"] = sum(
                1 for e in shrinks if e.get("accepted")
            )
        if any(e.get("event") == "ensemble_repro" for e in events):
            out["ensemble_repro"] = True
            out["done"] = True
        out["warnings"] = [
            w for w in out["warnings"] if w != "audit-inconsistent"
        ]

    # Service journals: job counts by their latest lifecycle event,
    # plus which worker ran the latest event (the pid@host stamp on
    # every job_* row, serve/jobs.py).
    job_state: dict = {}
    job_workers = set()
    for e in events:
        ev = str(e.get("event", ""))
        if ev in ("job_submitted", "job_running", "job_done", "job_failed",
                  "job_cancelled") and e.get("job"):
            job_state[e["job"]] = ev[len("job_"):]
            if e.get("worker"):
                job_workers.add(e["worker"])
    if job_state:
        counts: dict = {}
        for s in job_state.values():
            s = "queued" if s == "submitted" else s
            counts[s] = counts.get(s, 0) + 1
        out["jobs"] = counts
        if job_workers:
            out["job_workers"] = len(job_workers)

    # Fleet journals (fleet/store.py): fold with the store's own
    # reader so watch and the service /.metrics agree by construction.
    if any(str(e.get("event", "")).startswith(("fleet_", "gang_"))
           for e in events):
        from ..fleet.store import FleetStore

        view = FleetStore.fold_events(events, skipped)
        out["fleet"] = {
            k: v for k, v in view.counts().items() if v
        }
        out["fleet_workers"] = sum(
            1 for w in view.workers.values() if not w.get("stopped")
        )
        c = view.counters
        if c.get("gang_dispatches"):
            out["gang_occupancy"] = round(
                c.get("gang_jobs_batched", 0) / c["gang_dispatches"], 2
            )
        requeues = (c.get("fleet_lease_requeues", 0)
                    + c.get("fleet_orphan_requeues", 0))
        if requeues:
            out["fleet_requeues"] = requeues
            out["warnings"].append(f"lease-requeues={requeues}")
        if c.get("fleet_preemptions"):
            out["fleet_preemptions"] = c["fleet_preemptions"]
        active = any(
            j["state"] in ("queued", "running")
            for j in view.jobs.values()
        )
        if view.jobs and not active and "service_stop" not in {
            e.get("event") for e in events
        }:
            out["fleet_drained"] = True

    # Recompile storms: the journaled storm flag, or enough compile
    # events inside the trailing window to cross the threshold now.
    compiles = [e for e in events if e.get("event") == "compile"]
    if any(e.get("storm") for e in compiles):
        out["recompile_storm"] = True
    elif compiles and times:
        tail = [
            e for e in compiles
            if e["t"] >= max(times) - COMPILE_STORM_WINDOW_SEC
        ]
        if len(tail) >= COMPILE_STORM_THRESHOLD:
            out["recompile_storm"] = True
    if out.get("recompile_storm"):
        out["warnings"].append("recompile-storm")
    out["compiles"] = len(compiles)

    faults = sum(
        1 for e in events if e.get("event") in ("crash", "hang")
    )
    if faults:
        out["warnings"].append(f"faults={faults}")
    grows = sum(1 for e in events if e.get("event") == "grow")
    if grows:
        out["grows"] = grows

    # Current rungs and dedup path: the latest ``geometry`` event's
    # sort_lanes/step_lanes/sortless (engines re-journal geometry on
    # every tuner downshift, rung reset, and sortless fallback),
    # advanced by any LATER rung-climb grow events (their ``grown``
    # notes carry "sort_lanes=N" / "step_lanes=N" / "sortless=0") — so
    # the watched rungs track both directions of each ladder.  Flag-4
    # rung retries inside the trailing window raise the ladder-thrash
    # badge; repeated sortless→sort fallbacks inside the same window
    # (a serve journal flip-flopping per job) raise the claim-election
    # fallback-thrash badge.
    rung = None
    step_rung = None
    sortless = None
    rung_retry_times: List[float] = []
    fallback_times: List[float] = []
    for e in events:
        ev = e.get("event")
        if ev == "geometry":
            if e.get("sort_lanes") is not None:
                rung = e.get("sort_lanes")
            if e.get("step_lanes") is not None:
                step_rung = e.get("step_lanes")
            if e.get("sortless") is not None:
                sortless = bool(e.get("sortless"))
        elif ev == "grow":
            grown = str(e.get("grown", ""))
            m = re.search(r"(?<!_)sort_lanes=(\d+)", grown)
            if m:
                rung = int(m.group(1))
                if int(e.get("flags", 0) or 0) & 4 and isinstance(
                    e.get("t"), (int, float)
                ):
                    rung_retry_times.append(e["t"])
            m = re.search(r"step_lanes=(\d+)", grown)
            if m:
                step_rung = int(m.group(1))
            if "sortless=0" in grown:
                sortless = False
                if isinstance(e.get("t"), (int, float)):
                    fallback_times.append(e["t"])
    if rung is not None:
        out["sort_rung"] = rung
    if step_rung is not None:
        out["step_rung"] = step_rung
    if sortless is not None:
        out["dedup"] = "sortless" if sortless else "sort"
    if times and rung_retry_times:
        tail_retries = [
            t for t in rung_retry_times
            if t >= max(times) - SORT_THRASH_WINDOW_SEC
        ]
        out["sort_rung_retries"] = len(rung_retry_times)
        if len(tail_retries) >= SORT_THRASH_RETRIES:
            out["rung_thrash"] = True
            out["warnings"].append("rung-thrash")
    if times and fallback_times:
        tail_fb = [
            t for t in fallback_times
            if t >= max(times) - SORT_THRASH_WINDOW_SEC
        ]
        out["sortless_fallbacks"] = len(fallback_times)
        if len(tail_fb) >= SORT_THRASH_RETRIES:
            out["fallback_thrash"] = True
            out["warnings"].append("dedup-fallback-thrash")
    # Incremental re-checking (incr/, docs/INCREMENTAL.md): the latest
    # classification's mode is the one-word answer to "did this
    # re-check reuse anything", plus the cumulative verdict-cache hits.
    incr_modes = [
        e.get("mode") for e in events
        if e.get("event") == "incr_classified" and e.get("mode")
    ]
    if incr_modes:
        out["recheck"] = incr_modes[-1]
    hits = sum(1 for e in events if e.get("event") == "incr_verdict_hit")
    if hits:
        out["verdict_hits"] = hits

    kinds = {e.get("event") for e in events}
    if "engine_done" in kinds or "supervisor_done" in kinds:
        out["done"] = True
    if "service_stop" in kinds:
        out["done"] = True
    return out


def _fmt(v, digits: int = 4) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.{digits}g}"
    return str(v)


def render_line(s: dict) -> str:
    """The one-line progress view.  Field names are part of the
    greppable surface (docs/OBSERVABILITY.md "watch"): ``density=``,
    ``bottleneck=``, and ``host_share=`` always appear on run journals
    (— when unknown)."""
    parts = []
    if "t" in s:
        parts.append(f"t+{s['t']}s")
    if "jobs" in s:
        parts.append(
            "jobs " + " ".join(
                f"{k}={v}" for k, v in sorted(s["jobs"].items())
            )
        )
        if "job_workers" in s:
            parts.append(f"workers={s['job_workers']}")
    if "fleet" in s:
        parts.append(
            "fleet " + " ".join(
                f"{k}={v}" for k, v in sorted(s["fleet"].items())
            )
        )
        parts.append(f"fleet_workers={s.get('fleet_workers', 0)}")
        if "gang_occupancy" in s:
            parts.append(f"gang_occ={_fmt(s['gang_occupancy'])}")
        if "fleet_preemptions" in s:
            parts.append(f"preempted={s['fleet_preemptions']}")
        if s.get("fleet_drained"):
            parts.append("drained")
    if "unique" in s or "depth" in s:
        parts.append(f"depth={_fmt(s.get('depth'))}")
        parts.append(f"unique={_fmt(s.get('unique'))}")
        parts.append(f"uniq/s={_fmt(s.get('uniq_per_sec'))}")
        parts.append(f"load_factor={_fmt(s.get('load_factor'))}")
        parts.append(f"density={_fmt(s.get('density'))}")
        if "sort_rung" in s:
            parts.append(f"sort_rung={_fmt(s.get('sort_rung'))}")
        if "step_rung" in s:
            parts.append(f"step_rung={_fmt(s.get('step_rung'))}")
        if "dedup" in s:
            parts.append(f"dedup={s['dedup']}")
        parts.append(f"bottleneck={_fmt(s.get('bottleneck'))}")
        parts.append(f"host_share={_fmt(s.get('host_share'))}")
        if "waves" in s:
            parts.append(f"waves={s['waves']}")
        if s.get("grows"):
            parts.append(f"grows={s['grows']}")
    if "datagrams" in s:
        # Actor/chaos journal: the greppable actor fields
        # (docs/OBSERVABILITY.md "Actor-runtime observability").
        parts.append(f"msgs/s={_fmt(s.get('msgs_per_sec'))}")
        parts.append(f"datagrams={_fmt(s.get('datagrams'))}")
        parts.append(
            f"ops={_fmt(s.get('returned'))}/{_fmt(s.get('invoked'))}"
        )
        parts.append(f"retransmits={_fmt(s.get('retransmits'))}")
    if "chaos_faults" in s:
        parts.append(f"faults={s['chaos_faults']}")
    if s.get("spans"):
        parts.append(f"spans={s['spans']}")
    if "audit_consistent" in s and "ensemble_members" not in s:
        parts.append(
            "audit=ok" if s["audit_consistent"] else "audit=INCONSISTENT"
        )
    if "ensemble_members" in s:
        parts.append(f"members={_fmt(s['ensemble_members'])}")
        parts.append(f"failing={_fmt(s.get('ensemble_failing'))}")
        parts.append(f"sched/s={_fmt(s.get('schedules_per_sec'))}")
        if "ensemble_shrinks" in s:
            parts.append(
                f"shrinks={s.get('ensemble_shrinks_accepted', 0)}"
                f"/{s['ensemble_shrinks']}"
            )
        if s.get("ensemble_repro"):
            parts.append("repro=journaled")
    if "recheck" in s:
        parts.append(f"recheck={s['recheck']}")
    if s.get("verdict_hits"):
        parts.append(f"verdict_hits={s['verdict_hits']}")
    if s.get("compiles"):
        parts.append(f"compiles={s['compiles']}")
    if s.get("done"):
        parts.append("done")
    if not parts:
        parts.append(f"events={s.get('events', 0)} (no waves yet)")
    line = " ".join(parts)
    for w in s.get("warnings", ()):
        line += f" ⚠ {w}"
    return line


def watch_main(args: List[str], out=None) -> int:
    """``watch <journal.jsonl> [--interval SEC] [--once]`` (cli.py)."""
    out = out or sys.stdout
    once = False
    interval = 2.0
    targets: List[str] = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--once":
            once = True
        elif a == "--interval" or a.startswith("--interval="):
            if a == "--interval":
                i += 1
                val = args[i] if i < len(args) else None
            else:
                val = a.split("=", 1)[1]
            try:
                interval = float(val)
            except (TypeError, ValueError):
                print("--interval requires seconds", file=sys.stderr)
                return 2
            if interval <= 0:
                print("--interval must be positive", file=sys.stderr)
                return 2
        else:
            targets.append(a)
        i += 1
    if len(targets) != 1:
        print("watch takes exactly one journal path", file=sys.stderr)
        return 2
    path = targets[0]
    if not os.path.exists(path) and once:
        print(f"no such journal: {path}", file=sys.stderr)
        return 2

    from ..runtime.journal import read_journal_stats

    tty = hasattr(out, "isatty") and out.isatty()
    try:
        while True:
            events, skipped = (
                read_journal_stats(path) if os.path.exists(path)
                else ([], 0)
            )
            s = summarize_events(events, skipped)
            line = render_line(s)
            if once:
                print(line, file=out)
                return 0
            if tty:
                print("\r\x1b[2K" + line, end="", file=out, flush=True)
            else:
                print(line, file=out, flush=True)
            if s.get("done"):
                if tty:
                    print(file=out)
                return 0
            time.sleep(interval)
    except KeyboardInterrupt:
        if tty:
            print(file=out)
        return 0
