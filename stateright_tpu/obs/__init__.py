"""Observability for the TPU engines: metrics, trace spans, roofline.

Three small pieces, composed by the wavefront engines
(parallel/wavefront.py, parallel/sharded.py) and surfaced through the
runtime journal, the Explorer's ``GET /.metrics`` endpoint, the CLI's
``check-tpu --trace``, and ``bench.py``:

- :mod:`.metrics` — a thread-safe name->value registry every checker
  carries; counters and gauges the host loop updates from the scalars it
  already reads back (no extra device syncs with ``trace=False``).
- :mod:`.trace` — per-wave phase-timed trace spans: with ``trace=True``
  the engines run the wave loop in separately-dispatched phase programs
  (step kernel / canon+fingerprint / dedup-sort+probe / exchange /
  append / host readback) and record seconds + modeled bytes per phase.
- :mod:`.roofline` — the per-device-peak table and the bytes-touched
  model that reduce a wave's phase records into ``hbm_util_frac``
  (fraction of the device's peak HBM bandwidth the wave achieved).

Schema and methodology: docs/OBSERVABILITY.md.
"""

from .metrics import MetricsRegistry
from .roofline import (
    DEVICE_PEAKS,
    hbm_util_frac,
    peaks_for_device,
    probe_bytes,
    sort_bytes,
)
from .trace import WaveTracer

__all__ = [
    "DEVICE_PEAKS",
    "MetricsRegistry",
    "WaveTracer",
    "hbm_util_frac",
    "peaks_for_device",
    "probe_bytes",
    "sort_bytes",
]
