"""Observability for the TPU engines: metrics, trace spans, roofline.

Three small pieces, composed by the wavefront engines
(parallel/wavefront.py, parallel/sharded.py) and surfaced through the
runtime journal, the Explorer's ``GET /.metrics`` endpoint, the CLI's
``check-tpu --trace``, and ``bench.py``:

- :mod:`.metrics` — a thread-safe name->value registry every checker
  carries; counters, gauges, and fixed-boundary histograms (with
  p50/p95/p99 readback) the host loop updates from the scalars it
  already reads back (no extra device syncs with ``trace=False``).
- :mod:`.prometheus` — the standard text exposition of any metrics
  dict (``GET /.metrics?format=prometheus`` on the Explorer and the
  checking service) plus a minimal validating parser for CI.
- :mod:`.report` — journal-derived run/service reports (phase
  breakdown, bottleneck_phase, throughput curve, restart timeline, job
  spans) and the cross-round ``BENCH_r*.json`` trajectory with
  regression flagging; backs the ``report`` CLI verb.
- :mod:`.trace` — per-wave phase-timed trace spans: with ``trace=True``
  the engines run the wave loop in separately-dispatched phase programs
  (step kernel / canon+fingerprint / dedup-sort+probe / exchange /
  append / host readback) and record seconds + modeled bytes per phase.
- :mod:`.roofline` — the per-device-peak table and the bytes-touched
  model that reduce a wave's phase records into ``hbm_util_frac``
  (fraction of the device's peak HBM bandwidth the wave achieved).
- :mod:`.timeline` — the unified timeline: host-tail span decomposition
  of the fused loop's per-quantum host work (``host_span`` journal
  events + per-phase histograms), the Chrome trace-event exporter that
  folds run/serve/fleet journals — multi-worker fleets included — onto
  one clock-aligned Perfetto view (``timeline export``), and the JAX
  profiler hooks (``check-tpu --xprof-dir``).

Schema and methodology: docs/OBSERVABILITY.md.
"""

from .metrics import Histogram, MetricsRegistry, merge_histogram_snapshots
from .prometheus import parse_prometheus, render_prometheus
from .report import analyze_journal, bench_trajectory, render_markdown
from .timeline import (
    SpanRecorder,
    build_trace,
    export_timeline,
    host_share_of,
    host_tail_sums,
    validate_trace,
)
from .roofline import (
    DEVICE_PEAKS,
    hbm_util_frac,
    peaks_for_device,
    probe_bytes,
    sort_bytes,
)
from .trace import WaveTracer

__all__ = [
    "DEVICE_PEAKS",
    "Histogram",
    "MetricsRegistry",
    "SpanRecorder",
    "WaveTracer",
    "analyze_journal",
    "bench_trajectory",
    "build_trace",
    "export_timeline",
    "hbm_util_frac",
    "host_share_of",
    "host_tail_sums",
    "merge_histogram_snapshots",
    "parse_prometheus",
    "peaks_for_device",
    "probe_bytes",
    "render_markdown",
    "render_prometheus",
    "sort_bytes",
    "validate_trace",
]
