"""Fleet worker: one process, one backend, zero coordination.

A worker is a loop over the shared fleet directory (fleet/store.py):
sweep for dead siblings' jobs (lease-expiry requeue), resolve finished
portfolio groups, then claim work the placement policy
(fleet/placement.py) says this backend should take.  Compatible small
jobs are gang-batched into one device dispatch (fleet/gang.py);
everything else runs solo through EXACTLY the in-process scheduler's
builder/spawn/knob-cache path (the module-level helpers in
serve/scheduler.py), so a job produces the same result whether a serve
thread or a fleet worker ran it.

Unlike the in-process scheduler, the worker drives its solo checkers
directly: the poll loop is also where lease heartbeats fire, where
cross-process cancel flags are honored, and where SLO preemption
happens — a long-running job whose backend a strictly-higher-priority
job is queued for gets a cooperative ``request_stop``, its state saved
(``save_snapshot``), and a requeue carrying the snapshot path; the
next claimant spawns with ``resume_from=`` and continues mid-run
instead of restarting (runtime/supervisor.py proved this identity
under kill -9; preemption reuses the same machinery voluntarily).

``kill -9`` of a worker at ANY point loses no accepted job: every
state change it made was an fsync'd journal event, and whatever it was
holding comes back via the sibling sweep.  tests/test_fleet.py and the
CI fleet smoke exercise exactly that.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from ..serve.jobs import JobCancelled, JobSpec, worker_id
from ..serve.portfolio import checker_summary
from ..serve.scheduler import (
    _SIM_ENGINES, bound_simulation, knob_engine_tag, final_geometry,
    make_builder, spawn_engine,
)
from ..serve.workloads import workload_label
from .gang import gang_eligibility, run_gang
from .placement import describe_worker, placement_order
from .store import FleetStore


class FleetWorker:
    def __init__(
        self,
        fleet_dir: str,
        knob_cache_dir: Optional[str] = None,
        lease_sec: float = 15.0,
        poll_interval: float = 0.05,
        gang_max: int = 8,
        gang_min: int = 2,
        gang_frontier: int = 256,
        accept_big: bool = False,
        preempt_after: Optional[float] = None,
        max_jobs: Optional[int] = None,
    ):
        self.store = FleetStore(fleet_dir, lease_sec=lease_sec)
        self.knob_cache_dir = knob_cache_dir
        self.poll = float(poll_interval)
        self.gang_max = max(1, int(gang_max))
        self.gang_min = max(2, int(gang_min))
        self.gang_frontier = int(gang_frontier)
        self.preempt_after = preempt_after
        self.max_jobs = max_jobs
        self.desc = describe_worker(accept_big=accept_big)
        self.jobs_done = 0
        self.gang_dispatches = 0
        self.preemptions = 0
        self._started = time.time()
        self._stop = False
        # Per-worker histogram accumulation (wave latency, host spans
        # from finished checkers; job-run spans observed here): shipped
        # as snapshots inside ``fleet_worker_vitals`` so the fleet
        # ``/.metrics`` can merge them bucket-wise (fleet/service.py).
        from ..obs.metrics import MetricsRegistry

        self._span_metrics = MetricsRegistry()
        self._hists: dict = {}

    # -- lifecycle ------------------------------------------------------------

    def run(self, once: bool = False) -> int:
        """The worker loop.  ``once=True`` drains the queue this worker
        can serve and returns (tests and CI); otherwise runs until
        ``max_jobs`` or SIGTERM."""
        self.store.register_worker(self.desc)
        idle_passes = 0
        try:
            while not self._stop:
                did = self._pass()
                if did:
                    idle_passes = 0
                    self._vitals()
                else:
                    idle_passes += 1
                if self.max_jobs is not None and \
                        self.jobs_done >= self.max_jobs:
                    break
                if once and not did and idle_passes >= 3:
                    # Three empty passes, not one: requeue sweeps and
                    # portfolio resolution may unlock work between
                    # passes right after a sibling dies.
                    break
                if not did:
                    time.sleep(self.poll)
        finally:
            self.store.worker_stop(
                jobs_done=self.jobs_done,
                gang_dispatches=self.gang_dispatches,
            )
        return 0

    def _vitals(self) -> None:
        from ..obs.metrics import merge_histogram_snapshots

        vitals = {
            "jobs_done": self.jobs_done,
            "gang_dispatches": self.gang_dispatches,
            "preemptions": self.preemptions,
            "uptime_sec": round(time.time() - self._started, 1),
            "platform": self.desc["platform"],
        }
        hists = merge_histogram_snapshots(
            self._hists, self._span_metrics.snapshot_histograms()
        )
        if hists:
            vitals["histograms"] = hists
        self.store.worker_vitals(vitals)

    def _fold_checker_hists(self, checker) -> None:
        """Accumulate a finished checker's histograms (wave latency,
        ``host_*_sec`` spans) into this worker's published vitals —
        bucket-wise, so the fleet-level merge stays exact."""
        from ..obs.metrics import merge_histogram_snapshots

        try:
            hists = (checker.metrics() or {}).get("histograms") or {}
        except Exception:
            return
        if hists:
            self._hists = merge_histogram_snapshots(self._hists, hists)

    # -- one scheduling pass --------------------------------------------------

    def _pass(self) -> bool:
        """One pass: sweep, resolve, then claim-and-run.  Returns True
        when any job was run (solo or gang)."""
        self.store.requeue_expired()
        view = self.store.fold()
        self.store.resolve_portfolios(view)
        mine = placement_order(
            view.queued(), self.desc, self.knob_cache_dir
        )
        if not mine:
            return False
        gang = self._plan_gang(mine)
        if len(gang) >= self.gang_min:
            claimed = [j for j in gang if self.store.claim(j)]
            claimed = [
                j for j in claimed if not self._drop_if_cancelled(j)
            ]
            if len(claimed) >= 2:
                self._run_gang(claimed)
                return True
            if claimed:
                self._run_solo(claimed[0])
                return True
            return False
        for job in mine:
            if not self.store.claim(job):
                continue
            if self._drop_if_cancelled(job):
                return True
            self._run_solo(job)
            return True
        return False

    def _drop_if_cancelled(self, job: dict) -> bool:
        """A cancel can land between the fold and a claim win; the
        winner honors it instead of running a cancelled job."""
        if self.store.cancel_requested(job["id"]):
            self.store.mark_cancelled(job, reason="cancelled before start")
            return True
        return False

    def _plan_gang(self, mine: List[dict]) -> List[dict]:
        """The largest same-family group among the claimable queue, up
        to ``gang_max``.  Ineligibility is per-spec and journaled only
        at dispatch time (``gang_eject`` with the reason) to keep the
        planning pass quiet."""
        families: dict = {}
        for job in mine:
            if job.get("solo") or job.get("resume"):
                continue
            try:
                spec = JobSpec.from_dict(job["spec"])
            except ValueError:
                continue
            compat, _reason = gang_eligibility(spec)
            if compat is None:
                continue
            families.setdefault(compat, []).append(job)
        best: List[dict] = []
        for group in families.values():
            if len(group) > len(best):
                best = group
        return best[: self.gang_max]

    # -- gang dispatch --------------------------------------------------------

    def _run_gang(self, claimed: List[dict]) -> None:
        from ..obs.metrics import LATENCY_BUCKETS
        from ..serve.workloads import build_model

        members = []
        for job in claimed:
            spec = JobSpec.from_dict(job["spec"])
            model, _cli, _n = build_model(
                spec.workload, spec.n, spec.network
            )
            cm = model.compiled()
            members.append({
                "tag": job, "model": model, "cm": cm,
                "consts": cm.gang_constants(),
            })
        gang_id = f"gang-{claimed[0]['id']}"
        self.store.journal.append(
            "gang_dispatch", gang=gang_id, worker=worker_id(),
            jobs=[j["id"] for j in claimed],
            key=str(members[0]["cm"].gang_key()),
        )
        self.gang_dispatches += 1
        t_gang = time.monotonic()
        beat = {"t": t_gang}

        def on_wave(_wave, alive):
            now = time.monotonic()
            if now - beat["t"] >= self.store.lease_sec / 3.0:
                beat["t"] = now
                for job in alive:
                    self.store.lease(job["id"], job["attempt"])

        try:
            results, waves = run_gang(
                members, journal=self.store.journal,
                max_frontier=self.gang_frontier, on_wave=on_wave,
            )
        except Exception as exc:
            for job in claimed:
                self.store.fail(job, f"gang dispatch failed: {exc}")
            self.jobs_done += len(claimed)
            return
        for job, checker, eject_reason in results:
            if checker is None:
                # Overgrew the gang geometry: journal why and requeue
                # to run solo (and never gang again).
                self.store.journal.append(
                    "gang_eject", gang=gang_id, job=job["id"],
                    worker=worker_id(), reason=eject_reason,
                )
                self.store.requeue(
                    job, f"gang_eject: {eject_reason}", solo=True
                )
                continue
            summary = checker_summary(checker)
            self._fold_checker_hists(checker)
            # Gang members share one device program, so each finished
            # job is charged the gang's wall time — the same
            # ``job_run_sec`` family the solo path observes, keeping
            # fleet /.metrics histograms populated on gang-only runs.
            self._span_metrics.observe(
                "job_run_sec", time.monotonic() - t_gang,
                boundaries=LATENCY_BUCKETS,
            )
            summary["completed"] = True
            summary["engine"] = "tpu"
            summary["gang"] = {
                "id": gang_id, "size": len(claimed), "waves": waves,
            }
            summary["worker"] = worker_id()
            self.store.finish(job, summary, gang=gang_id)
            self.jobs_done += 1

    # -- solo jobs ------------------------------------------------------------

    def _run_solo(self, job: dict, _retry: bool = False) -> None:
        """One claimed job end-to-end on this process's backend — the
        in-process scheduler's engine-kwargs layering (workload defaults
        < cached knobs < explicit overrides) via the shared helpers, plus
        the fleet-only concerns: heartbeats, cross-process cancel, resume
        snapshots, and SLO preemption."""
        from ..runtime.knob_cache import (
            drop_knobs, knob_key, load_knobs, store_knobs,
        )

        t_job = time.monotonic()
        try:
            spec = JobSpec.from_dict(job["spec"])
        except ValueError as exc:
            self.store.fail(job, f"invalid spec: {exc}")
            self.jobs_done += 1
            return
        cache_key = None
        cache_hit = False
        device_engine = spec.engine in (
            "tpu", "tiered", "sharded", "tiered-sharded",
        )
        try:
            model, cli, builder, n = make_builder(
                spec, spec.engine, spec.symmetry
            )
            if spec.engine in _SIM_ENGINES:
                bound_simulation(builder, spec)
            engine_kwargs = (
                dict(cli.tpu_kwargs)
                if spec.engine in ("tpu", "tiered") else {}
            )
            if (device_engine and spec.use_knob_cache
                    and self.knob_cache_dir is not None):
                label = workload_label(
                    spec.workload, n, spec.network, spec.symmetry
                )
                if spec.engine in ("tiered", "tiered-sharded"):
                    label += ":mb={}".format(
                        spec.engine_kwargs.get("memory_budget_mb")
                    )
                cache_key = knob_key(
                    label, engine=knob_engine_tag(spec.engine)
                )
                cached = None if _retry else load_knobs(
                    self.knob_cache_dir, cache_key
                )
                if cached is not None:
                    engine_kwargs.update(cached)
                    cache_hit = True
            engine_kwargs.update(spec.engine_kwargs)
            if job.get("resume") and spec.engine == "tpu":
                # A preempted (or supervised-restart) attempt: continue
                # from the saved snapshot instead of re-exploring.
                engine_kwargs["resume_from"] = job["resume"]

            checker = spawn_engine(
                builder, spec, spec.engine, engine_kwargs, spec.seed
            )
            preempted = self._poll(job, checker)
            if preempted:
                return
        except JobCancelled as c:
            partial = dict(c.partial)
            partial["completed"] = False
            self.store.mark_cancelled(
                job, unique=partial.get("unique_state_count")
            )
            self.jobs_done += 1
            return
        except Exception as exc:
            if cache_hit and cache_key is not None and not _retry:
                # Stale cached geometry: drop and rerun once fresh —
                # the knob-cache staleness contract.
                drop_knobs(self.knob_cache_dir, cache_key)
                self.store.journal.append(
                    "knobs_dropped", job=job["id"], key=cache_key,
                    worker=worker_id(),
                )
                return self._run_solo(job, _retry=True)
            self.store.fail(job, f"{type(exc).__name__}: {exc}")
            self.jobs_done += 1
            return

        summary = checker_summary(checker)
        self._fold_checker_hists(checker)
        from ..obs.metrics import LATENCY_BUCKETS

        self._span_metrics.observe(
            "job_run_sec", time.monotonic() - t_job,
            boundaries=LATENCY_BUCKETS,
        )
        summary["completed"] = True
        summary["engine"] = spec.engine
        summary["n"] = n
        summary["knob_cache_hit"] = cache_hit
        summary["worker"] = worker_id()
        hand_tuned = set(spec.engine_kwargs) - {"memory_budget_mb"}
        if (cache_key is not None and not cache_hit and device_engine
                and not hand_tuned and not job.get("resume")):
            knobs = final_geometry(checker)
            if knobs:
                from ..obs.timeline import record_oneshot_span

                t_kc = time.monotonic()
                store_knobs(
                    self.knob_cache_dir, cache_key, knobs,
                    unique=summary["unique_state_count"],
                    depth=summary["max_depth"],
                    source=f"fleet:{job['id']}",
                )
                record_oneshot_span(
                    self.store.journal, self._span_metrics, "knob_cache",
                    time.monotonic() - t_kc, job=job["id"],
                )
        self.store.finish(job, summary)
        self.jobs_done += 1

    def _poll(self, job: dict, checker) -> bool:
        """Drive one solo checker: heartbeat the lease, forward cancel
        flags, and preempt when SLO policy says to.  Returns True when
        the job was preempted (requeued with a resume snapshot — no
        terminal event belongs here)."""
        last_beat = time.monotonic()
        started = time.monotonic()
        cancelled = False
        while not checker.is_done():
            now = time.monotonic()
            if now - last_beat >= self.store.lease_sec / 3.0:
                last_beat = now
                self.store.lease(job["id"], job["attempt"])
                if self.store.cancel_requested(job["id"]):
                    cancelled = True
                    checker.request_stop()
                elif self._should_preempt(job, now - started):
                    if self._preempt(job, checker):
                        return True
            time.sleep(self.poll)
        checker.join()
        if cancelled or self.store.cancel_requested(job["id"]):
            raise JobCancelled(partial=checker_summary(checker))
        return False

    def _should_preempt(self, job: dict, running_sec: float) -> bool:
        """SLO preemption policy: only after the grace window, only for
        snapshot-capable engines, and only when a STRICTLY higher
        priority job this worker could serve is waiting."""
        if self.preempt_after is None or running_sec < self.preempt_after:
            return False
        if (job.get("spec") or {}).get("engine", "tpu") != "tpu":
            return False
        view = self.store.fold()
        return any(
            q["priority"] > job["priority"]
            for q in view.queued()
        )

    def _preempt(self, job: dict, checker) -> bool:
        """Cooperatively stop, snapshot, and requeue-with-resume.  A
        checker without snapshot support just keeps running (False)."""
        save = getattr(checker, "save_snapshot", None)
        if save is None:
            return False
        checker.request_stop()
        checker.join()
        snap = self.store.snapshot_path(job["id"], job["attempt"])
        try:
            save(snap)
        except Exception as exc:
            # No snapshot -> no resume; finish the job from the partial
            # run rather than discarding the work.
            self.store.journal.append(
                "fleet_preempt_failed", job=job["id"],
                worker=worker_id(), error=str(exc)[:200],
            )
            summary = checker_summary(checker)
            summary["completed"] = checker.is_done()
            self.store.finish(job, summary)
            self.jobs_done += 1
            return True
        self.preemptions += 1
        self.store.preempt(job, snap, "higher-priority job queued")
        return True


def worker_main(argv: Optional[List[str]] = None) -> int:
    """CLI entry for one fleet worker process (``fleet-worker`` verb,
    cli.py; also ``python -m stateright_tpu.fleet worker``)."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="fleet-worker",
        description="serve jobs from a shared fleet directory",
    )
    ap.add_argument("--fleet-dir", required=True,
                    help="shared durable job store directory")
    ap.add_argument("--knob-cache", default=None,
                    help="persisted engine-knob cache directory")
    ap.add_argument("--lease-sec", type=float, default=15.0,
                    help="claim lease; siblings requeue after expiry")
    ap.add_argument("--poll", type=float, default=0.05)
    ap.add_argument("--gang-max", type=int, default=8,
                    help="max compatible jobs batched into one dispatch")
    ap.add_argument("--gang-frontier", type=int, default=256,
                    help="per-member frontier budget inside a gang; "
                         "overgrowing members are ejected to run solo")
    ap.add_argument("--accept-big", action="store_true",
                    help="claim big jobs even off-TPU (single-backend "
                         "fleets)")
    ap.add_argument("--preempt-after", type=float, default=None,
                    help="seconds before a running job may be preempted "
                         "for a higher-priority one")
    ap.add_argument("--max-jobs", type=int, default=None)
    ap.add_argument("--once", action="store_true",
                    help="drain the claimable queue and exit")
    args = ap.parse_args(argv)
    worker = FleetWorker(
        args.fleet_dir,
        knob_cache_dir=args.knob_cache,
        lease_sec=args.lease_sec,
        poll_interval=args.poll,
        gang_max=args.gang_max,
        gang_frontier=args.gang_frontier,
        accept_big=args.accept_big,
        preempt_after=args.preempt_after,
        max_jobs=args.max_jobs,
    )
    return worker.run(once=args.once)
