"""Heterogeneous placement: which worker should run which job.

A fleet mixes backends — CPU containers, the odd GPU box, TPU meshes —
and the placement rule is the obvious economic one: small jobs are
cheap anywhere, so they go to commodity workers; a TPU mesh is the
scarce resource, reserved for jobs that actually need device scale.
Workers self-describe (:func:`describe_worker` — the same
platform/device_kind fields the knob cache keys on,
runtime/knob_cache.knob_key), jobs are sized (:func:`is_big` — the
declared engine plus the knob-cache history's recorded unique-state
counts), and :func:`placement_order` turns one worker's view of the
queue into the ordered claim list: TPU workers take big jobs first,
non-TPU workers never take them at all (``--accept-big`` overrides for
single-backend fleets).

There is no central placer: every worker applies the same pure
functions to the same folded store view, and the per-attempt claim
locks (fleet/store.py) resolve the races.  docs/SERVING.md documents
the policy.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

# A job is "big" when its expected unique-state count crosses this, or
# when it explicitly asks for a multi-chip engine.  2^20 unique states
# is where the single-chip engines start growing tables past commodity
# RAM and a mesh's HBM begins to pay for itself.
BIG_UNIQUE_THRESHOLD = 1 << 20
# An explicit capacity request at/above this is a self-declared big job
# even with no history.
BIG_CAPACITY_THRESHOLD = 1 << 22

_MESH_ENGINES = ("sharded", "tiered-sharded")


def describe_worker(accept_big: bool = False) -> dict:
    """This process's backend self-description, journaled as the
    ``fleet_worker`` registration event.  The platform/device_kind
    fields are exactly the knob cache's device-key fields, so a
    journal reader can correlate a worker's claims with the knob
    entries its runs produced."""
    import jax

    d = jax.devices()[0]
    mem_mb = None
    try:
        stats = d.memory_stats()
        if stats and stats.get("bytes_limit"):
            mem_mb = int(stats["bytes_limit"] // (1024 * 1024))
    except Exception:
        pass
    return {
        "platform": d.platform,
        "device_kind": getattr(d, "device_kind", d.platform),
        "memory_mb": mem_mb,
        "engines": ["tpu", "tiered", "bfs", "dfs", "simulation",
                    "tpu_simulation"]
        + (["sharded", "tiered-sharded"] if len(jax.devices()) > 1 else []),
        "accept_big": bool(accept_big),
    }


def estimate_unique(spec: dict,
                    knob_cache_dir: Optional[str]) -> Optional[int]:
    """Expected unique-state count for a job, from the knob-cache
    history: every served run persists its final geometry with the
    run's ``unique`` count as metadata (serve/scheduler.py
    ``store_knobs(..., unique=...)``), so the cache doubles as a
    size-history keyed by workload label.  Matched by label prefix
    across devices/engines (the count is device-independent); the MAX
    over matches is returned — requeues must not flap a job between
    size classes because a partial run recorded a smaller count.
    None when this workload configuration has never been seen."""
    if not knob_cache_dir:
        return None
    try:
        with open(os.path.join(knob_cache_dir, "knobs.json"), "r",
                  encoding="utf-8") as fh:
            data = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(data, dict):
        return None
    from ..serve.workloads import cli_spec_for, workload_label

    workload = spec.get("workload")
    n = spec.get("n")
    if n is None:
        try:
            n = cli_spec_for(workload).default_n
        except Exception:
            return None
    prefix = workload_label(
        workload, int(n), spec.get("network"), bool(spec.get("symmetry"))
    ) + "|"
    best = None
    for key, entry in data.items():
        if not str(key).startswith(prefix):
            continue
        try:
            unique = int(entry.get("unique"))
        except (AttributeError, TypeError, ValueError):
            continue
        best = unique if best is None else max(best, unique)
    return best


def is_big(spec: dict, knob_cache_dir: Optional[str]) -> bool:
    """Size one job.  Declared intent first (a mesh engine or a huge
    explicit capacity IS a big job), then the knob-cache history; an
    unknown workload defaults to small — the first run sizes it for
    every run after."""
    if spec.get("engine") in _MESH_ENGINES:
        return True
    kwargs = spec.get("engine_kwargs") or {}
    try:
        if int(kwargs.get("capacity", 0)) >= BIG_CAPACITY_THRESHOLD:
            return True
    except (TypeError, ValueError):
        pass
    est = estimate_unique(spec, knob_cache_dir)
    return est is not None and est >= BIG_UNIQUE_THRESHOLD


def worker_takes(job: dict, desc: dict,
                 knob_cache_dir: Optional[str]) -> bool:
    """May a worker with self-description ``desc`` claim ``job``?  The
    reservation rule: big jobs only on TPU-platform workers (or an
    explicit ``accept_big``); engines the backend can't spawn are
    skipped (a single-device worker claiming a sharded job would just
    fail it)."""
    spec = job.get("spec") or {}
    engine = spec.get("engine", "tpu")
    if engine in _MESH_ENGINES and engine not in desc.get("engines", ()):
        return False
    if is_big(spec, knob_cache_dir):
        return desc.get("platform") == "tpu" or bool(
            desc.get("accept_big")
        )
    return True


def placement_order(queued: List[dict], desc: dict,
                    knob_cache_dir: Optional[str]) -> List[dict]:
    """Order one worker's claim attempts over the queue (already
    priority-sorted by ``FleetView.queued``): filter to what this
    worker may take, then — on TPU workers only — big jobs first, so
    the mesh drains the work only it can do before competing with CPU
    siblings for crumbs."""
    mine = [
        j for j in queued if worker_takes(j, desc, knob_cache_dir)
    ]
    if desc.get("platform") != "tpu":
        return mine
    big = [j for j in mine if is_big(j.get("spec") or {}, knob_cache_dir)]
    small = [j for j in mine if j not in big]
    return big + small
