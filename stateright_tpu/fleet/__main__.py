"""Fleet operator CLI: ``python -m stateright_tpu.fleet VERB`` (also
reachable as the ``fleet-worker`` and ``fleet`` verbs of the main CLI,
stateright_tpu/cli.py).

- ``worker``  — run one fleet worker against ``--fleet-dir``
- ``submit``  — append one job to the fleet store; ``--wait`` blocks
  for the verdict and exits with the supervisor's VIOLATION_RC on a
  property violation (scriptable exactly like ``check-tpu``)
- ``status``  — one fold of the store: workers, counters, job table
- ``cancel``  — request cancellation of one job
- ``quota``   — set/clear a tenant's admission quota
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from typing import List, Optional


def _submit_main(argv: List[str]) -> int:
    from ..runtime.supervisor import VIOLATION_RC
    from ..serve.jobs import JobSpec
    from .store import DONE, FAILED, FleetStore, TERMINAL

    ap = argparse.ArgumentParser(
        prog="fleet submit", description="queue one job on the fleet"
    )
    ap.add_argument("--fleet-dir", required=True)
    ap.add_argument("workload", help="a SERVABLE workload name")
    ap.add_argument("--n", type=int, default=None)
    ap.add_argument("--network", default=None)
    ap.add_argument("--engine", default="tpu")
    ap.add_argument("--engine-kwargs", default=None,
                    help="JSON object of engine keyword overrides")
    ap.add_argument("--symmetry", action="store_true")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--tenant", default="default")
    ap.add_argument("--priority", type=int, default=0)
    ap.add_argument("--portfolio", type=int, default=None,
                    help="diversified portfolio of this size across "
                         "the fleet's workers")
    ap.add_argument("--wait", type=float, default=None,
                    help="block up to SECONDS for the verdict; exit "
                         f"{VIOLATION_RC} on violation")
    args = ap.parse_args(argv)

    spec_dict = {
        "workload": args.workload, "engine": args.engine,
        "seed": args.seed, "symmetry": args.symmetry,
    }
    if args.n is not None:
        spec_dict["n"] = args.n
    if args.network is not None:
        spec_dict["network"] = args.network
    if args.engine_kwargs:
        spec_dict["engine_kwargs"] = json.loads(args.engine_kwargs)
    if args.portfolio is not None:
        spec_dict["portfolio"] = {"size": args.portfolio,
                                  "seed": args.seed}
    store = FleetStore(args.fleet_dir)
    job_id = store.submit(
        JobSpec.from_dict(spec_dict), tenant=args.tenant,
        priority=args.priority,
    )
    print(job_id)
    if args.wait is None:
        return 0
    deadline = time.monotonic() + args.wait
    while time.monotonic() < deadline:
        rec = store.fold().jobs.get(job_id)
        if rec is not None and rec["state"] in TERMINAL:
            result = store.read_result(job_id) or {}
            json.dump(
                {"id": job_id, "state": rec["state"],
                 "violation": rec["violation"], "error": rec["error"],
                 "unique_state_count": result.get("unique_state_count")},
                sys.stdout, indent=2,
            )
            print()
            if rec["state"] == DONE:
                return VIOLATION_RC if rec["violation"] else 0
            return 1 if rec["state"] == FAILED else 0
        time.sleep(0.2)
    print(f"timeout: job {job_id} not terminal after {args.wait}s",
          file=sys.stderr)
    return 1


def _status_main(argv: List[str]) -> int:
    from .store import FleetStore

    ap = argparse.ArgumentParser(
        prog="fleet status", description="one fold of the fleet store"
    )
    ap.add_argument("--fleet-dir", required=True)
    ap.add_argument("--json", action="store_true",
                    help="machine-readable dump of the full fold")
    args = ap.parse_args(argv)
    view = FleetStore(args.fleet_dir).fold()
    if args.json:
        json.dump(
            {"jobs": view.jobs, "workers": view.workers,
             "counters": view.counters, "torn": view.torn},
            sys.stdout, indent=2, default=str,
        )
        print()
        return 0
    print(f"fleet {args.fleet_dir}")
    counts = view.counts()
    print("  jobs:    " + "  ".join(
        f"{k}={v}" for k, v in sorted(counts.items())
    ))
    print("  counters:" + "".join(
        f" {k}={v}" for k, v in sorted(view.counters.items()) if v
    ))
    for wid, w in sorted(view.workers.items()):
        desc = w.get("desc") or {}
        state = "stopped" if w.get("stopped") else "alive"
        print(f"  worker {wid}: {desc.get('platform')}"
              f"/{desc.get('device_kind')} {state}")
    for jid, j in sorted(view.jobs.items()):
        wl = (j["spec"] or {}).get("workload", "?")
        extra = ""
        if j["worker"]:
            extra += f" worker={j['worker']}"
        if j["attempt"]:
            extra += f" attempt={j['attempt']}"
        if j["gang"]:
            extra += f" gang={j['gang']}"
        if j["violation"]:
            extra += f" VIOLATION={j['violation']!r}"
        if j["error"]:
            extra += f" error={j['error']!r}"
        print(f"  {jid} {j['state']:<9} {wl}{extra}")
    return 0


def _cancel_main(argv: List[str]) -> int:
    from .store import FleetStore

    ap = argparse.ArgumentParser(prog="fleet cancel")
    ap.add_argument("--fleet-dir", required=True)
    ap.add_argument("job_id")
    args = ap.parse_args(argv)
    ok = FleetStore(args.fleet_dir).cancel(args.job_id)
    print("cancelled" if ok else "not cancellable (unknown or terminal)")
    return 0 if ok else 1


def _quota_main(argv: List[str]) -> int:
    from .store import FleetStore

    ap = argparse.ArgumentParser(
        prog="fleet quota",
        description="per-tenant admission quota (max active jobs)",
    )
    ap.add_argument("--fleet-dir", required=True)
    ap.add_argument("tenant")
    ap.add_argument("limit", nargs="?", default=None,
                    help="max active jobs; omit or 'none' to clear")
    args = ap.parse_args(argv)
    store = FleetStore(args.fleet_dir)
    limit = (None if args.limit in (None, "none")
             else int(args.limit))
    store.set_quota(args.tenant, limit)
    print(json.dumps(store.quotas(), sort_keys=True))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    verbs = {
        "submit": _submit_main, "status": _status_main,
        "cancel": _cancel_main, "quota": _quota_main,
    }
    if argv and argv[0] == "worker":
        from .worker import worker_main

        return worker_main(argv[1:])
    if argv and argv[0] in verbs:
        return verbs[argv[0]](argv[1:])
    print("usage: python -m stateright_tpu.fleet "
          "{worker|submit|status|cancel|quota} ...", file=sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
