"""Fleet serving: durable multi-worker scheduling over a shared
directory (docs/SERVING.md "Fleet mode").

The in-process service (serve/) runs N scheduler threads against the
one mesh its process owns; the fleet runs N PROCESSES — each owning
its own backend (a CPU container, a GPU box, a TPU mesh) — against one
durable on-disk job store.  Four pieces:

- fleet/store.py — the store: an fsync'd journal as the single source
  of truth plus O_EXCL lock files for claim races.  kill -9 any worker;
  no accepted job is lost.
- fleet/gang.py — gang batching: K compatible small jobs become ONE
  device dispatch over a leading jobs axis, with per-job verdicts
  bit-identical to K solo runs.
- fleet/placement.py — heterogeneous placement: small jobs to
  commodity workers, TPU meshes reserved for jobs that need them.
- fleet/worker.py / fleet/service.py — the worker loop and the
  fleet-backed HTTP service (same endpoints as serve/server.py).

``python -m stateright_tpu.fleet worker|submit|status|cancel|quota``
or the ``fleet-worker`` / ``fleet`` CLI verbs drive it.
"""

from .gang import GangMemberChecker, gang_eligibility, run_gang
from .placement import (
    describe_worker, estimate_unique, is_big, placement_order,
    worker_takes,
)
from .service import FleetJobView, FleetService
from .store import (
    CANCELLED, COUNTERS, DONE, FAILED, FleetStore, FleetView, QUEUED,
    QuotaExceeded, RUNNING, TERMINAL,
)
from .worker import FleetWorker, worker_main

__all__ = [
    "CANCELLED", "COUNTERS", "DONE", "FAILED", "FleetJobView",
    "FleetService", "FleetStore", "FleetView", "FleetWorker",
    "GangMemberChecker", "QUEUED", "QuotaExceeded", "RUNNING",
    "TERMINAL", "describe_worker", "estimate_unique",
    "gang_eligibility", "is_big", "placement_order", "run_gang",
    "worker_main", "worker_takes",
]
