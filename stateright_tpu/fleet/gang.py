"""Gang batching: K compatible small jobs in ONE device dispatch.

The fleet's small-job traffic is dominated by parameter sweeps — the
same workload module at different constants (grid walks at different
bounds, counters at different caps).  Run solo, each job pays a full
program trace/compile (its constants are baked into the traced step)
and a device round-trip per wave for a frontier that fills a sliver of
a chip.  The gang runner instead stacks K such jobs on a leading *jobs*
axis and drives ONE jitted wave program over ``[K, F, W]`` frontiers,
with each job's constants riding a traced ``[K, C]`` input — so every
member of a gang family shares one compiled program AND one device
dispatch per wave.

Compatibility is the model's own declaration (``CompiledModel.gang_*``
hooks, parallel/compiled.py): a non-None ``gang_key()`` names the
family, and the contract is that equal keys trace IDENTICAL programs
with the per-instance constants supplied as data.  On top of that the
job spec must be semantically batchable — see :func:`gang_eligibility`;
anything else (and any member that overgrows the gang's fixed geometry
mid-run) is ejected and requeued to run solo, journaled as
``gang_eject``.

Parity contract (the gate in docs/SERVING.md): each member's
``discovered_fingerprints()``, per-property verdicts, and violation
verdict are bit-equal to K serial ``spawn_tpu`` runs.  The wave
semantics reproduce ``wave_common.wave_eval`` for the gang-eligible
subset (no EVENTUALLY properties): property conditions evaluated at
expansion time, the awaiting-discoveries gate, ALWAYS/SOMETIMES
latching with first-lane witnesses, and boundary pruning — over the
same 64-bit device fingerprints (``ops.device_fp``) the solo engines
dedup and report with.  Gang families are required to carry a
never-discovered ALWAYS anchor property (their declared convention, see
docs/SERVING.md), which keeps every state awaited in both engines and
makes the parity independent of chunking and discovery timing.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.checker import Checker
from ..core.model import Expectation
from ..core.path import Path

# Engine kwargs that only shape solo geometry; the gang manages its own
# geometry, so these are ignorable — anything else changes semantics
# (journal, trace, resume_from, ...) and disqualifies the job.
_GEOMETRY_KWARGS = {
    "capacity", "log_capacity", "max_frontier", "chunk_size",
    "dedup_factor", "sort_lanes", "sortless", "bucket_slack",
}

# Compiled gang wave programs, keyed by (gang_key, K, F) — the shared
# bounded-FIFO idiom (wave_common.cached_program), so gang compiles
# count into the same program_cache_hits/misses evidence and journal
# the same ``compile`` events as every other engine.
_GANG_PROGRAMS: dict = {}
_GANG_CACHE_MAX = 8


def gang_eligibility(spec) -> Tuple[Optional[tuple], str]:
    """Decide whether one JobSpec may join a gang.

    Returns ``(compat, reason)``: ``compat`` is a hashable family key
    (equal keys may share a dispatch) or None with ``reason`` naming the
    first disqualifier — journaled on ``gang_eject`` so an operator can
    see WHY a job ran solo.

    The semantic requirements mirror what the gang wave implements:
    single-chip exhaustive search (engine ``tpu``), run-to-completion
    stopping (``finish_when`` absent or ``all``, no depth/count/time
    targets), no symmetry (the gang fingerprints raw rows), no
    portfolio/store wrapping, and a model whose compiled form declares
    a gang family with no EVENTUALLY properties (the eventually-bit
    pipeline needs trace-end bookkeeping the gang does not carry).
    """
    from ..serve.workloads import build_model

    if spec.engine != "tpu":
        return None, f"engine {spec.engine!r}"
    if spec.portfolio is not None:
        return None, "portfolio job"
    if spec.store:
        return None, "verification-store job"
    if spec.symmetry:
        return None, "symmetry"
    if spec.finish_when not in (None, "all"):
        return None, f"finish_when {spec.finish_when!r}"
    if spec.target_max_depth is not None:
        return None, "target_max_depth"
    if spec.target_state_count is not None:
        return None, "target_state_count"
    if spec.timeout is not None:
        return None, "timeout"
    extra = set(spec.engine_kwargs) - _GEOMETRY_KWARGS
    if extra:
        return None, f"engine_kwargs {sorted(extra)}"
    try:
        model, cli, n = build_model(spec.workload, spec.n, spec.network)
    except Exception as exc:
        return None, f"build failed: {exc}"
    if cli.target_max_depth is not None or \
            cli.tpu_target_max_depth is not None:
        return None, "workload depth target"
    compiled = getattr(model, "compiled", None)
    if compiled is None:
        return None, "no compiled form"
    cm = model.compiled()
    key = cm.gang_key()
    if key is None:
        return None, "model not gang-capable"
    props = model.properties()
    if any(p.expectation is Expectation.EVENTUALLY for p in props):
        return None, "eventually property"
    consts = np.asarray(cm.gang_constants(), np.uint32)
    compat = (key, spec.finish_when or "all", int(consts.shape[0]),
              tuple(p.name for p in props),
              tuple(p.expectation.name for p in props))
    return compat, ""


class GangMemberChecker(Checker):
    """The finished-checker view of one gang member: the same surface
    ``checker_summary`` (serve/portfolio.py) and the discovery pins read
    on a solo checker — counts, discoveries as re-executed
    :class:`Path` objects, and the sorted 64-bit discovery-set
    fingerprint."""

    def __init__(self, model, state_count: int, unique: int, depth: int,
                 discoveries: Dict[str, Path], fingerprints: np.ndarray,
                 waves: int, gang_size: int):
        super().__init__(model)
        self._state_count = int(state_count)
        self._unique = int(unique)
        self._depth = int(depth)
        self._discoveries = dict(discoveries)
        self._fps = fingerprints
        self._waves = int(waves)
        self._gang_size = int(gang_size)

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return self._unique

    def max_depth(self) -> int:
        return self._depth

    def discoveries(self) -> Dict[str, Path]:
        return dict(self._discoveries)

    def is_done(self) -> bool:
        return True

    def join(self) -> "Checker":
        return self

    def discovered_fingerprints(self) -> np.ndarray:
        """Sorted uint64 device fingerprints of every committed state —
        the cross-engine discovery-set pin, bit-equal to the solo
        engine's ``discovered_fingerprints()`` by the parity gate."""
        return self._fps.copy()

    def metrics(self) -> dict:
        m = super().metrics()
        m["engine"] = "gang-member"
        m["gang_waves"] = self._waves
        m["gang_size"] = self._gang_size
        return m


class _Member:
    """Host-side traversal state for one gang lane."""

    def __init__(self, tag, model, cm, consts, n_props):
        self.tag = tag  # caller's handle (the fleet job dict)
        self.model = model
        self.cm = cm
        self.consts = consts
        self.frontier_rows: List[np.ndarray] = []
        self.frontier_fps: List[int] = []
        self.seen: set = set()
        self.parent: Dict[int, Optional[int]] = {}
        self.rowof: Dict[int, np.ndarray] = {}
        self.witness: List[Optional[int]] = [None] * n_props
        self.state_count = 0
        self.depth = 0
        self.done = False
        self.eject_reason: Optional[str] = None

    @property
    def alive(self) -> bool:
        return not self.done and self.eject_reason is None

    def path_to(self, fp: int) -> Path:
        """Re-execute the parent chain behind ``fp`` — the same
        host-replay witness recovery the solo engine's ``_slot_path``
        does (Path.from_fingerprints re-runs the model along the
        chain's HOST fingerprints)."""
        chain: List[int] = []
        cur: Optional[int] = fp
        while cur is not None:
            chain.append(cur)
            cur = self.parent[cur]
        chain.reverse()
        return Path.from_fingerprints(
            self.model,
            [self.model.fingerprint(self.cm.decode(self.rowof[c]))
             for c in chain],
        )


def _build_wave(cm, expectations, K, F, A, W, P, C, has_boundary):
    """Trace the gang wave: one jitted call advancing ALL K members one
    BFS level.  ``cm`` is any member's compiled model — by the gang_key
    contract its ``gang_*`` hooks read every instance-specific constant
    from the traced ``consts`` lane, so the program is family-global."""
    import jax
    import jax.numpy as jnp

    from ..ops.device_fp import device_fp64

    fpw = cm.fp_words or W

    def one(states, active, consts, undisc):
        conds = jax.vmap(
            lambda s: cm.gang_property_conds(s, consts)
        )(states)  # [F, P]
        # The awaiting-discoveries gate, exactly wave_eval's: a state
        # expands only while some property still awaits what this state
        # offers (ALWAYS awaits satisfying states, SOMETIMES awaits
        # non-satisfying ones).
        awaiting = jnp.zeros((F,), jnp.bool_)
        hits = []
        for p, exp in enumerate(expectations):
            if exp == "ALWAYS":
                awaiting = awaiting | (undisc[p] & conds[:, p])
                hits.append(active & ~conds[:, p])
            else:  # SOMETIMES (EVENTUALLY is gang-ineligible)
                awaiting = awaiting | (undisc[p] & ~conds[:, p])
                hits.append(active & conds[:, p])
        hitm = jnp.stack(hits) if hits else jnp.zeros((0, F), jnp.bool_)
        nexts, valid = jax.vmap(
            lambda s: cm.gang_step(s, consts)
        )(states)  # [F, A, W], [F, A]
        valid = valid & active[:, None] & awaiting[:, None]
        if has_boundary:
            inb = jax.vmap(
                lambda row: jax.vmap(
                    lambda s: cm.gang_boundary(s, consts)
                )(row)
            )(nexts)
            valid = valid & inb
        generated = jnp.sum(valid, dtype=jnp.uint32)
        return hitm, nexts, valid, generated

    @jax.jit
    def wave(frontier, active, consts, undisc):
        hitm, nexts, valid, generated = jax.vmap(one)(
            frontier, active, consts, undisc
        )
        flat = nexts.reshape((K * F * A, W))
        # hi/lo stay separate uint32 on device (no x64); the host folds
        # them into uint64 — same split as fingerprints_of_rows.
        hi, lo = device_fp64(flat[:, :fpw])
        return (hitm, nexts, valid, generated,
                hi.reshape((K, F * A)), lo.reshape((K, F * A)))

    return wave


def run_gang(members_in: List[dict], journal=None,
             max_frontier: int = 256, max_states: int = 1 << 20,
             on_wave=None):
    """Run one gang to completion.

    ``members_in``: dicts with ``tag`` (opaque handle), ``model``,
    ``cm``, ``consts`` — all sharing one compat key from
    :func:`gang_eligibility`.  Returns ``(results, waves)`` where
    ``results`` is a list of ``(tag, checker_or_None, eject_reason)``
    in input order: a :class:`GangMemberChecker` for completed members,
    ``None`` + reason for members ejected mid-run (frontier or state
    budget overgrown — the caller requeues those to run solo).
    ``on_wave(wave_index, alive_tags)`` fires once per device wave —
    the fleet worker's hook for lease heartbeats mid-gang.
    """
    from ..parallel.wave_common import cached_program
    from ..parallel.wave_loop import fingerprints_of_rows

    first = members_in[0]
    cm = first["cm"]
    model = first["model"]
    props = model.properties()
    expectations = [p.expectation.name for p in props]
    P = len(props)
    W = cm.state_width
    A = cm.max_actions
    C = int(np.asarray(first["consts"]).shape[0])
    F = int(max_frontier)
    has_boundary = cm.gang_boundary(
        np.zeros((W,), np.uint32), np.asarray(first["consts"], np.uint32)
    ) is not None
    # Pad K to a power of two: gangs of 3 and 4 share one program, and
    # the cache holds O(log) shapes per family instead of one per size.
    K = 1
    while K < len(members_in):
        K *= 2

    members = [
        _Member(m["tag"], m["model"], m["cm"],
                np.asarray(m["consts"], np.uint32), P)
        for m in members_in
    ]

    # Seed frontiers with each member's unique initial states, in init
    # order — the same first-occurrence commit order the solo row log
    # uses, over the same device fingerprints.
    for mem in members:
        rows = [np.asarray(mem.cm.encode(s), np.uint32)
                for s in mem.model.init_states()]
        fps = fingerprints_of_rows(
            mem.cm, np.stack(rows, axis=0), sort=False
        )
        for row, fp in zip(rows, fps):
            fp = int(fp)
            if fp in mem.seen:
                continue
            mem.seen.add(fp)
            mem.parent[fp] = None
            mem.rowof[fp] = row
            mem.frontier_rows.append(row)
            mem.frontier_fps.append(fp)
        mem.state_count = len(mem.frontier_rows)
        # Solo max_depth counts path LENGTH in states, not edges: the
        # init level alone is depth 1.
        mem.depth = 1 if mem.frontier_rows else 0
        if len(mem.frontier_rows) > F:
            mem.eject_reason = "init frontier exceeds gang geometry"

    gang_key = cm.gang_key()
    wave_fn = cached_program(
        _GANG_PROGRAMS, _GANG_CACHE_MAX,
        (gang_key, tuple(expectations), K, F, has_boundary),
        lambda: _build_wave(cm, expectations, K, F, A, W, P, C,
                            has_boundary),
        label=f"gang:{gang_key[0]}", journal=journal,
        provenance={"gang_key": str(gang_key), "K": K, "F": F},
    )

    consts_arr = np.zeros((K, C), np.uint32)
    for j, mem in enumerate(members):
        consts_arr[j] = mem.consts

    waves = 0
    while any(mem.alive and mem.frontier_rows for mem in members):
        frontier = np.zeros((K, F, W), np.uint32)
        active = np.zeros((K, F), bool)
        undisc = np.zeros((K, P), bool)
        for j, mem in enumerate(members):
            if not (mem.alive and mem.frontier_rows):
                continue
            f = len(mem.frontier_rows)
            frontier[j, :f] = np.stack(mem.frontier_rows, axis=0)
            active[j, :f] = True
            undisc[j] = [w is None for w in mem.witness]
        hitm, nexts, valid, generated, hi, lo = wave_fn(
            frontier, active, consts_arr, undisc
        )
        hitm = np.asarray(hitm)
        nexts = np.asarray(nexts)
        valid = np.asarray(valid)
        generated = np.asarray(generated)
        fps = (np.asarray(hi).astype(np.uint64) << np.uint64(32)) | \
            np.asarray(lo).astype(np.uint64)
        waves += 1
        if on_wave is not None:
            on_wave(waves, [m.tag for m in members if m.alive])

        for j, mem in enumerate(members):
            if not (mem.alive and mem.frontier_rows):
                continue
            f = len(mem.frontier_rows)
            # Latch witnesses first-lane-wins, against wave-start
            # discoveries — wave_eval's ordering.
            for p in range(P):
                if mem.witness[p] is not None:
                    continue
                lanes = np.nonzero(hitm[j, p, :f])[0]
                if lanes.size:
                    mem.witness[p] = mem.frontier_fps[int(lanes[0])]
            mem.state_count += int(generated[j])
            # Commit successors in candidate lane order (state-major,
            # action-minor) — the solo row log's first-occurrence
            # append order — deduped on the same 64-bit device fps.
            nxt_rows: List[np.ndarray] = []
            nxt_fps: List[int] = []
            for i in range(f):
                parent_fp = mem.frontier_fps[i]
                for a in range(A):
                    if not valid[j, i, a]:
                        continue
                    fp = int(fps[j, i * A + a])
                    if fp in mem.seen:
                        continue
                    mem.seen.add(fp)
                    mem.parent[fp] = parent_fp
                    mem.rowof[fp] = nexts[j, i, a].copy()
                    nxt_rows.append(mem.rowof[fp])
                    nxt_fps.append(fp)
            # Finish check AFTER the commit, like the solo wave loop:
            # the matching wave's successors are in the log but never
            # expanded (finish_when "all" — the only gang policy).
            if P and all(w is not None for w in mem.witness):
                mem.done = True
                mem.frontier_rows, mem.frontier_fps = [], []
                continue
            if len(nxt_rows) > F:
                mem.eject_reason = (
                    f"frontier overgrew gang geometry "
                    f"({len(nxt_rows)} > {F})"
                )
                continue
            if len(mem.seen) > max_states:
                mem.eject_reason = (
                    f"state budget overgrown ({len(mem.seen)} > "
                    f"{max_states})"
                )
                continue
            mem.frontier_rows, mem.frontier_fps = nxt_rows, nxt_fps
            if nxt_rows:
                mem.depth += 1
            else:
                mem.done = True

    results = []
    for mem in members:
        if mem.eject_reason is not None:
            results.append((mem.tag, None, mem.eject_reason))
            continue
        discoveries = {
            props[p].name: mem.path_to(mem.witness[p])
            for p in range(P)
            if mem.witness[p] is not None
        }
        fps_sorted = np.sort(
            np.fromiter(mem.seen, dtype=np.uint64, count=len(mem.seen))
        )
        checker = GangMemberChecker(
            mem.model, mem.state_count, len(mem.seen), mem.depth,
            discoveries, fps_sorted, waves, len(members),
        )
        results.append((mem.tag, checker, None))
    return results, waves
