"""Fleet-backed serving: the HTTP surface of serve/server.py over a
shared durable store instead of an in-process scheduler.

``serve --fleet-dir DIR`` swaps :class:`FleetService` in for
``CheckService`` — the endpoints, request/response shapes, and error
codes stay identical (serve/server.py's Handler is reused verbatim),
but the server process runs no checks itself: ``POST /jobs`` appends to
the fleet journal, and separately-launched ``fleet-worker`` processes
(fleet/worker.py) claim and run them.  The server can therefore restart
freely — every job it ever accepted is in the store — and many servers
can front the same fleet directory.

What necessarily differs from in-process mode:

- ``/jobs/{id}/explore`` returns 409: completed checkers live (and die)
  in worker processes, so there is no retained checker to attach the
  Explorer to.  Re-run the workload locally to explore it.
- ``/.metrics`` aggregates the FLEET view: job counts folded from the
  journal, the ``fleet_*``/``gang_*`` counters (COUNTERS in
  fleet/store.py), per-worker vitals from their last heartbeat, and
  gang occupancy (mean jobs per device dispatch — the batching win).
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..serve.jobs import JobSpec
from ..serve.workloads import workload_names
from .store import FleetStore, QUEUED, RUNNING, TERMINAL


class FleetJobView:
    """Read-only job handle shaped like serve/jobs.Job for the HTTP
    handler: ``id``/``state``/``snapshot()``/``wait()``.  State is
    re-folded from the journal on each access — the store is the truth,
    this object is a cursor."""

    def __init__(self, service: "FleetService", job_id: str):
        self._service = service
        self.id = job_id

    def _record(self) -> Optional[dict]:
        return self._service.fleet.fold().jobs.get(self.id)

    @property
    def state(self) -> str:
        rec = self._record()
        return rec["state"] if rec else "unknown"

    @property
    def explorer_address(self):
        return None

    def snapshot(self) -> dict:
        rec = self._record()
        if rec is None:
            return {"id": self.id, "state": "unknown"}
        out = {
            "id": self.id,
            "state": rec["state"],
            "spec": rec["spec"],
            "tenant": rec["tenant"],
            "priority": rec["priority"],
            "attempt": rec["attempt"],
            "worker": rec["worker"],
            "error": rec["error"],
            "result": None,
        }
        if rec["group"]:
            out["group"] = rec["group"]
        if rec.get("gang"):
            out["gang"] = rec["gang"]
        if rec["state"] in TERMINAL:
            out["result"] = self._service.fleet.read_result(self.id)
        return out

    def wait(self, timeout: float = 0.0) -> bool:
        """Block until terminal (the ``?wait=`` result endpoint); the
        poll is against the journal fold, so progress made by any
        worker process is visible."""
        deadline = time.monotonic() + timeout
        while True:
            rec = self._record()
            if rec is None or rec["state"] in TERMINAL:
                return rec is not None
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.1)


class FleetService:
    """Drop-in for serve/server.CheckService over a fleet directory.
    Also its own ``store`` shim: the Handler reads
    ``service.store.list()`` and that is the only JobStore surface it
    uses."""

    def __init__(self, fleet_dir: str, lease_sec: float = 15.0):
        self.fleet = FleetStore(fleet_dir, lease_sec=lease_sec)
        self.fleet_dir = fleet_dir
        self.store = self  # Handler reads service.store.list()
        self.started_at = time.time()
        self.http_server = None
        self.address = None
        self.journal = self.fleet.journal
        self.journal.append("service_start", fleet_dir=fleet_dir)

    # -- store shim -----------------------------------------------------------

    def list(self) -> List[FleetJobView]:
        view = self.fleet.fold()
        return [FleetJobView(self, jid) for jid in sorted(view.jobs)]

    def counts(self) -> dict:
        return self.fleet.fold().counts()

    # -- CheckService surface -------------------------------------------------

    def submit(self, spec, tenant: str = "default",
               priority: int = 0) -> FleetJobView:
        if isinstance(spec, dict):
            spec = dict(spec)
            tenant = str(spec.pop("tenant", tenant))
            priority = int(spec.pop("priority", priority))
            spec = JobSpec.from_dict(spec)
        job_id = self.fleet.submit(
            spec, tenant=tenant, priority=priority
        )
        return FleetJobView(self, job_id)

    def get(self, job_id: str) -> Optional[FleetJobView]:
        if self.fleet.fold().jobs.get(job_id) is None:
            return None
        return FleetJobView(self, job_id)

    def cancel(self, job_id: str) -> bool:
        return self.fleet.cancel(job_id)

    def explore(self, job, port: int = 0):
        raise ValueError(
            f"job {job.id} ran on a fleet worker; fleet mode retains no "
            "checkers to explore — run the workload in-process "
            "(serve without --fleet-dir, or the check-tpu CLI) to "
            "attach the Explorer"
        )

    def metrics(self) -> dict:
        view = self.fleet.fold()
        out = {
            "service": "stateright-tpu-serve",
            "mode": "fleet",
            "uptime_sec": round(time.time() - self.started_at, 1),
            "fleet_dir": self.fleet_dir,
            "jobs": view.counts(),
            "journal_torn_lines": view.torn,
        }
        out.update(view.counters)
        # Gang occupancy: mean jobs per device dispatch.  1.0 means the
        # batcher never found compatible work; the CPU-gauge bench
        # phase (bench.py phase_fleet) drives this toward gang_max.
        dispatches = view.counters.get("gang_dispatches", 0)
        if dispatches:
            out["gang_occupancy"] = round(
                view.counters.get("gang_jobs_batched", 0) / dispatches, 3
            )
        active = [
            j for j in view.jobs.values()
            if j["state"] in (QUEUED, RUNNING)
        ]
        out["jobs_active"] = len(active)
        out["workers"] = {
            wid: {
                "platform": (w.get("desc") or {}).get("platform"),
                "device_kind": (w.get("desc") or {}).get("device_kind"),
                "accept_big": (w.get("desc") or {}).get("accept_big"),
                "alive": not w.get("stopped"),
                "last_seen": w.get("last_seen"),
                "vitals": w.get("vitals") or {},
            }
            for wid, w in view.workers.items()
        }
        out["workers_alive"] = sum(
            1 for w in view.workers.values() if not w.get("stopped")
        )
        # Fleet-wide histogram merge: per-worker vitals ship histogram
        # SNAPSHOTS (wave latency, host spans, job spans) through the
        # journal; bucket-wise addition folds them into one fleet view.
        # Commutative, so the merged view cannot depend on worker
        # enumeration order (pinned in tests/test_timeline.py).
        from ..obs.metrics import merge_histogram_snapshots

        merged = merge_histogram_snapshots(*(
            (w.get("vitals") or {}).get("histograms") or {}
            for w in view.workers.values()
        ))
        if merged:
            out["histograms"] = merged
        return out

    def status(self) -> dict:
        view = self.fleet.fold()
        return {
            "service": "stateright-tpu-serve",
            "mode": "fleet",
            "uptime_sec": round(time.time() - self.started_at, 1),
            "fleet_dir": self.fleet_dir,
            "workers": sum(
                1 for w in view.workers.values() if not w.get("stopped")
            ),
            "jobs": view.counts(),
            "workloads": workload_names(),
        }

    def shutdown(self) -> None:
        if self.http_server is not None:
            self.http_server.shutdown()
        self.journal.append("service_stop")
        self.journal.close()
