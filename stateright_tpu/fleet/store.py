"""Durable on-disk job store shared by a fleet of worker processes.

The store is a directory (``--fleet-dir``)::

    fleet-dir/
      journal.jsonl     # fsync'd event log — the SINGLE source of truth
      locks/            # O_EXCL claim/requeue/resolve lock files
      results/          # one atomic JSON file per finished job
      flags/            # cancel request markers
      snapshots/        # preemption snapshots (fleet/worker.py)
      quotas.json       # optional per-tenant admission limits

There is no database and no daemon: every fact about every job is an
appended ``fleet_*`` event (runtime/journal.py with ``fsync=True``, so
an event that was acknowledged survives ``kill -9`` an instruction
later), and the current state is a pure fold over the event stream
(:meth:`FleetStore.fold`).  Any process with the directory can compute
the same view — that is what lets N independent workers cooperate with
no coordinator and lets a sibling requeue a dead worker's job.

Mutual exclusion uses the one primitive shared filesystems give us
atomically: ``open(..., O_CREAT | O_EXCL)``.  Claims are per-attempt
(``locks/<job>.claim.<attempt>``), so a requeued job's next attempt is
a fresh race that the dead worker's stale lock cannot block; requeues
race on ``locks/<job>.requeue.<attempt>`` so exactly one sibling moves
the job back to queued.  Both outcomes of every race are journaled
(``fleet_claimed`` / ``fleet_claim_lost``), so the journal alone
reconstructs who won and who stood down.

Crash-safety argument (the durability gate in docs/SERVING.md):

* killed before the claim lock       -> job still queued, anyone claims;
* killed between lock and journal    -> the orphan-claim rule below
  detects the aged lock with no ``fleet_claimed`` event and requeues to
  the next attempt;
* killed while running               -> the lease (``fleet_lease``
  heartbeats) expires and any sibling requeues;
* killed after the result file but before ``fleet_done`` -> the job
  reruns; the run is deterministic, so the rewritten result is
  identical bit-for-bit.

In every window, an accepted (journaled) job is eventually completed by
somebody, and nothing a client was told is lost.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from ..runtime.journal import Journal, read_journal_stats
from ..serve.jobs import JobSpec, worker_id

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
CANCELLED = "cancelled"
TERMINAL = (DONE, FAILED, CANCELLED)

# Fold-derived fleet counters surfaced by /.metrics (fleet/service.py).
COUNTERS = (
    "fleet_submitted", "fleet_claims", "fleet_claims_lost",
    "fleet_lease_requeues", "fleet_orphan_requeues", "fleet_preemptions",
    "gang_dispatches", "gang_jobs_batched", "gang_ejects",
)


class QuotaExceeded(ValueError):
    """Tenant admission refused: active jobs at the configured limit."""


def _atomic_write_json(path: str, payload: dict) -> None:
    """tmp + fsync + rename: readers see the old file or the complete
    new one, never a torn JSON (same discipline as the knob cache)."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, sort_keys=True, default=str)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _try_lock(path: str) -> bool:
    """One O_EXCL creation attempt — THE atomic race primitive.  The
    file content (worker id) is advisory breadcrumbs for debugging; the
    creation itself is the decision."""
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
    except FileExistsError:
        return False
    try:
        os.write(fd, worker_id().encode())
    finally:
        os.close(fd)
    return True


class FleetView:
    """One fold of the journal: jobs, workers, and event counters."""

    def __init__(self, jobs: Dict[str, dict], workers: Dict[str, dict],
                 counters: Dict[str, int], torn: int):
        self.jobs = jobs
        self.workers = workers
        self.counters = counters
        self.torn = torn

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in (QUEUED, RUNNING) + TERMINAL}
        for job in self.jobs.values():
            out[job["state"]] += 1
        return out

    def queued(self) -> List[dict]:
        """Claimable jobs, priority-major / submit-order-minor — the
        same ordering the in-process scheduler's heap gives.  Portfolio
        parents are NOT claimable (their members are)."""
        out = [
            j for j in self.jobs.values()
            if j["state"] == QUEUED and not j.get("portfolio_parent")
        ]
        out.sort(key=lambda j: (-j["priority"], j["submitted_at"], j["id"]))
        return out

    def active_for_tenant(self, tenant: str) -> int:
        return sum(
            1 for j in self.jobs.values()
            if j["tenant"] == tenant and j["state"] in (QUEUED, RUNNING)
            and not j.get("portfolio_parent")
        )


class FleetStore:
    """One process's handle on a fleet directory.  Stateless between
    calls apart from the journal fd: every decision re-derives from the
    directory, so any number of FleetStore instances (in any number of
    processes) stay consistent."""

    def __init__(self, root: str, lease_sec: float = 15.0,
                 max_attempts: int = 5):
        self.root = str(root)
        self.lease_sec = float(lease_sec)
        self.max_attempts = int(max_attempts)
        for sub in ("locks", "results", "flags", "snapshots"):
            os.makedirs(os.path.join(self.root, sub), exist_ok=True)
        self.journal_path = os.path.join(self.root, "journal.jsonl")
        # Unrotated on purpose: the journal is the store's entire
        # history, and requeue correctness folds over all of it.
        self.journal = Journal(self.journal_path, fsync=True)

    # -- paths ----------------------------------------------------------------

    def _lock(self, name: str) -> str:
        return os.path.join(self.root, "locks", name)

    def result_path(self, job_id: str) -> str:
        return os.path.join(self.root, "results", f"{job_id}.json")

    def _cancel_flag(self, job_id: str) -> str:
        return os.path.join(self.root, "flags", f"{job_id}.cancel")

    def snapshot_path(self, job_id: str, attempt: int) -> str:
        return os.path.join(
            self.root, "snapshots", f"{job_id}.{attempt}.npz"
        )

    # -- admission ------------------------------------------------------------

    def quotas(self) -> Dict[str, int]:
        path = os.path.join(self.root, "quotas.json")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                raw = json.load(fh)
            return {str(k): int(v) for k, v in raw.items()}
        except (FileNotFoundError, ValueError):
            return {}

    def set_quota(self, tenant: str, limit: Optional[int]) -> None:
        q = self.quotas()
        if limit is None:
            q.pop(tenant, None)
        else:
            q[str(tenant)] = int(limit)
        _atomic_write_json(os.path.join(self.root, "quotas.json"), q)

    def _next_id(self) -> str:
        # Ids must be unique ACROSS processes with no shared counter: a
        # per-store sequence file under an O_EXCL lock would serialize
        # submits; time+pid+seq is collision-free without coordination
        # and sorts roughly by submission.
        self._seq = getattr(self, "_seq", 0) + 1
        return f"fj-{int(time.time() * 1000):013d}-{os.getpid()}-{self._seq}"

    def submit(self, spec: JobSpec, tenant: str = "default",
               priority: Optional[int] = None) -> str:
        """Admit one job: quota check, then the durable ``fleet_submitted``
        event (spec inlined — the journal alone must reconstruct the
        job).  Portfolio specs are expanded HERE into per-member jobs
        (``group=<parent>``), which is what makes fleet portfolios
        diversify across workers instead of across threads of one."""
        if spec.store:
            raise ValueError(
                "store: true jobs need the serving process's verification "
                "store; submit them to a serve instance, not the fleet"
            )
        quota = self.quotas().get(tenant)
        if quota is not None:
            if self.fold().active_for_tenant(tenant) >= quota:
                raise QuotaExceeded(
                    f"tenant {tenant!r} at admission quota ({quota} active)"
                )
        job_id = self._next_id()
        prio = spec.priority if priority is None else int(priority)
        if spec.portfolio is None:
            self.journal.append(
                "fleet_submitted", job=job_id, tenant=tenant,
                priority=prio, spec=spec.to_dict(), worker=worker_id(),
            )
            return job_id
        # Portfolio expansion: the parent is a bookkeeping record (never
        # claimable); each diversified member becomes an ordinary fleet
        # job any worker can claim.
        from ..serve.portfolio import diversify
        from ..serve.workloads import build_model

        pf = spec.portfolio
        base = spec.to_dict()
        base.pop("portfolio")
        base_kwargs = dict(spec.engine_kwargs)
        try:
            _, cli, _ = build_model(spec.workload, spec.n, spec.network)
            if spec.engine == "tpu":
                merged = dict(cli.tpu_kwargs)
                merged.update(base_kwargs)
                base_kwargs = merged
        except Exception:
            pass
        members = diversify(
            size=int(pf["size"]), seed=int(pf.get("seed", 0)),
            base_engine=spec.engine, base_kwargs=base_kwargs,
            symmetry_capable=False,
            include_simulation=bool(pf.get("simulation", True)),
        )
        member_ids = []
        for m in members:
            mid = f"{job_id}.m{m.index}"
            mspec = dict(
                base, engine=m.engine, engine_kwargs=m.engine_kwargs,
                symmetry=m.symmetry, seed=m.seed or spec.seed,
                finish_when=spec.finish_when or "any_failures",
            )
            if m.kind == "simulation" and spec.target_state_count is None:
                mspec["target_state_count"] = m.target_state_count
            JobSpec.from_dict(mspec)  # loud validation before admission
            member_ids.append(mid)
            self.journal.append(
                "fleet_submitted", job=mid, tenant=tenant, priority=prio,
                spec=mspec, group=job_id, member=m.index,
                worker=worker_id(),
            )
        self.journal.append(
            "fleet_submitted", job=job_id, tenant=tenant, priority=prio,
            spec=spec.to_dict(), portfolio_parent=True,
            worker=worker_id(),
        )
        self.journal.append(
            "fleet_portfolio", job=job_id, members=member_ids,
            worker=worker_id(),
        )
        return job_id

    # -- fold -----------------------------------------------------------------

    def fold(self) -> FleetView:
        """Replay the journal into the current fleet state.  The fold is
        the ONLY reader of fleet semantics — workers, the service view,
        report/watch, and the tests all agree by construction."""
        events, torn = read_journal_stats(self.journal_path)
        return self.fold_events(events, torn)

    @staticmethod
    def fold_events(events, torn: int = 0) -> FleetView:
        """The fold itself, over a pre-read event list — report/watch
        (obs/) reuse it on journals they already parsed."""
        jobs: Dict[str, dict] = {}
        workers: Dict[str, dict] = {}
        counters = {k: 0 for k in COUNTERS}
        for ev in events:
            e = ev.get("event", "")
            jid = ev.get("job")
            rec = jobs.get(jid) if jid else None
            if e == "gang_dispatch":
                # Carries a ``jobs`` list, not a ``job`` id — count it
                # before the per-job guard below skips it.
                counters["gang_dispatches"] += 1
                counters["gang_jobs_batched"] += len(ev.get("jobs", ()))
                continue
            if e == "fleet_submitted":
                counters["fleet_submitted"] += 1
                jobs[jid] = {
                    "id": jid,
                    "spec": ev.get("spec") or {},
                    "tenant": ev.get("tenant", "default"),
                    "priority": int(ev.get("priority", 0)),
                    "group": ev.get("group"),
                    "member": ev.get("member"),
                    "portfolio_parent": bool(ev.get("portfolio_parent")),
                    "state": QUEUED,
                    "attempt": 0,
                    "worker": None,
                    "lease_t": None,
                    "resume": None,
                    "solo": False,
                    "submitted_at": float(ev.get("t", 0.0)),
                    "finished_at": None,
                    "unique": None,
                    "violation": None,
                    "error": None,
                    "gang": None,
                }
            elif rec is None:
                continue  # event for a job whose submit we never saw
            elif e == "fleet_claimed":
                counters["fleet_claims"] += 1
                if (rec["state"] == QUEUED
                        and int(ev.get("attempt", -1)) == rec["attempt"]):
                    rec["state"] = RUNNING
                    rec["worker"] = ev.get("worker")
                    rec["lease_t"] = float(ev.get("t", 0.0))
            elif e == "fleet_claim_lost":
                counters["fleet_claims_lost"] += 1
            elif e == "fleet_lease":
                if (rec["state"] == RUNNING
                        and int(ev.get("attempt", -1)) == rec["attempt"]):
                    rec["lease_t"] = float(ev.get("t", 0.0))
            elif e == "fleet_requeued":
                reason = ev.get("reason", "")
                if reason == "orphan_claim":
                    counters["fleet_orphan_requeues"] += 1
                else:
                    counters["fleet_lease_requeues"] += 1
                if rec["state"] not in TERMINAL:
                    rec["state"] = QUEUED
                    rec["attempt"] = int(ev.get("attempt", rec["attempt"]))
                    rec["worker"] = None
                    rec["lease_t"] = None
                    rec["resume"] = ev.get("resume")
                    rec["solo"] = rec["solo"] or bool(ev.get("solo"))
            elif e == "fleet_preempted":
                counters["fleet_preemptions"] += 1
            elif e == "fleet_done":
                # A verdict is a verdict even from a lease-lost attempt
                # that finished late: runs are deterministic, so the
                # first terminal event wins and later ones are no-ops.
                if rec["state"] not in TERMINAL:
                    rec["state"] = DONE
                    rec["worker"] = ev.get("worker", rec["worker"])
                    rec["finished_at"] = float(ev.get("t", 0.0))
                    rec["unique"] = ev.get("unique")
                    rec["violation"] = ev.get("violation")
                    rec["gang"] = ev.get("gang")
            elif e == "fleet_failed":
                # Unlike fleet_done, a stale attempt's failure does NOT
                # terminate a retried job — only the current attempt
                # (or an attempt-less admission failure) may fail it.
                att = ev.get("attempt")
                if rec["state"] not in TERMINAL and (
                        att is None or int(att) == rec["attempt"]):
                    rec["state"] = FAILED
                    rec["finished_at"] = float(ev.get("t", 0.0))
                    rec["error"] = ev.get("error")
            elif e == "fleet_cancelled":
                if rec["state"] not in TERMINAL:
                    rec["state"] = CANCELLED
                    rec["finished_at"] = float(ev.get("t", 0.0))
            elif e == "gang_eject":
                counters["gang_ejects"] += 1
        # Worker registry events carry no job id; second pass is
        # cheaper than special-casing the None-jid branch above.
        for ev in events:
            e = ev.get("event", "")
            wid = ev.get("worker")
            if not wid:
                continue
            if e == "fleet_worker":
                workers[wid] = {
                    "worker": wid,
                    "desc": {
                        k: ev.get(k)
                        for k in ("platform", "device_kind", "memory_mb",
                                  "engines", "accept_big")
                    },
                    "started_at": float(ev.get("t", 0.0)),
                    "last_seen": float(ev.get("t", 0.0)),
                    "vitals": None,
                    "stopped": False,
                }
            elif e == "fleet_worker_stop" and wid in workers:
                workers[wid]["stopped"] = True
                workers[wid]["last_seen"] = float(ev.get("t", 0.0))
            elif e == "fleet_worker_vitals" and wid in workers:
                workers[wid]["vitals"] = ev.get("vitals")
                workers[wid]["last_seen"] = float(ev.get("t", 0.0))
            elif e in ("fleet_claimed", "fleet_lease") and wid in workers:
                workers[wid]["last_seen"] = max(
                    workers[wid]["last_seen"], float(ev.get("t", 0.0))
                )
        return FleetView(jobs, workers, counters, torn)

    # -- claims / leases ------------------------------------------------------

    def claim(self, job: dict, worker: Optional[str] = None) -> bool:
        """Race for one queued job at its current attempt.  Both
        outcomes are journaled: the loser's ``fleet_claim_lost`` is the
        auditable proof the race happened and was resolved."""
        wid = worker or worker_id()
        attempt = job["attempt"]
        if _try_lock(self._lock(f"{job['id']}.claim.{attempt}")):
            self.journal.append(
                "fleet_claimed", job=job["id"], attempt=attempt,
                worker=wid, tenant=job["tenant"],
            )
            return True
        self.journal.append(
            "fleet_claim_lost", job=job["id"], attempt=attempt, worker=wid,
        )
        return False

    def lease(self, job_id: str, attempt: int,
              worker: Optional[str] = None) -> None:
        """Heartbeat: extends the lease so siblings don't requeue a job
        that is merely slow.  Workers beat well inside ``lease_sec``."""
        self.journal.append(
            "fleet_lease", job=job_id, attempt=attempt,
            worker=worker or worker_id(),
        )

    def lease_expired(self, job: dict, now: Optional[float] = None) -> bool:
        if job["state"] != RUNNING or job["lease_t"] is None:
            return False
        return (now or time.time()) - job["lease_t"] > self.lease_sec

    def _orphan_claim(self, job: dict,
                      now: Optional[float] = None) -> bool:
        """A worker killed BETWEEN winning the claim lock and journaling
        ``fleet_claimed`` leaves the job queued but unclaimable (the
        lock for its attempt exists, so every future claim loses).  The
        lock file's age is the tiebreaker: older than a lease with no
        matching claim event means the winner is dead."""
        if job["state"] != QUEUED:
            return False
        path = self._lock(f"{job['id']}.claim.{job['attempt']}")
        try:
            age = (now or time.time()) - os.stat(path).st_mtime
        except FileNotFoundError:
            return False
        return age > self.lease_sec

    def requeue(self, job: dict, reason: str,
                resume: Optional[str] = None, solo: bool = False) -> bool:
        """Move a stuck job back to queued at ``attempt+1`` (exactly one
        sibling wins the per-attempt requeue lock).  At the attempt cap
        the job fails instead — a job that kills every worker that
        touches it must not poison the fleet forever.  ``solo=True``
        marks the job gang-ineligible from here on (a gang-ejected
        member must not be re-batched into the geometry it overgrew)."""
        attempt = job["attempt"]
        if not _try_lock(self._lock(f"{job['id']}.requeue.{attempt}")):
            return False
        if attempt + 1 >= self.max_attempts:
            self.journal.append(
                "fleet_failed", job=job["id"], attempt=attempt,
                worker=worker_id(),
                error=f"gave up after {attempt + 1} attempts ({reason})",
            )
            return True
        self.journal.append(
            "fleet_requeued", job=job["id"], attempt=attempt + 1,
            reason=reason, resume=resume, worker=worker_id(),
            solo=bool(solo or job.get("solo")),
        )
        return True

    def requeue_expired(self) -> int:
        """Sweep for jobs whose owner died: expired leases and orphaned
        claims.  Any worker runs this on every loop pass; the requeue
        lock keeps concurrent sweeps from double-requeueing."""
        view = self.fold()
        now = time.time()
        requeued = 0
        for job in view.jobs.values():
            if self.lease_expired(job, now):
                if self.requeue(job, "lease_expired"):
                    requeued += 1
            elif self._orphan_claim(job, now):
                if self.requeue(job, "orphan_claim"):
                    requeued += 1
        return requeued

    # -- completion -----------------------------------------------------------

    def finish(self, job: dict, result: dict,
               gang: Optional[str] = None) -> None:
        """Result file FIRST (atomic), then the terminal event: a crash
        between the two reruns the job, never serves a dangling DONE."""
        _atomic_write_json(self.result_path(job["id"]), result)
        self.journal.append(
            "fleet_done", job=job["id"], attempt=job["attempt"],
            worker=worker_id(),
            unique=result.get("unique_state_count"),
            violation=result.get("violation"), gang=gang,
        )

    def fail(self, job: dict, error: str) -> None:
        self.journal.append(
            "fleet_failed", job=job["id"], attempt=job["attempt"],
            worker=worker_id(), error=str(error)[:500],
        )

    def preempt(self, job: dict, resume: Optional[str],
                reason: str) -> None:
        """Journal the preemption, then requeue WITH the snapshot path:
        the next claimant resumes mid-run instead of restarting."""
        self.journal.append(
            "fleet_preempted", job=job["id"], attempt=job["attempt"],
            worker=worker_id(), reason=reason, resume=resume,
        )
        self.journal.append(
            "fleet_requeued", job=job["id"], attempt=job["attempt"] + 1,
            reason=f"preempted:{reason}", resume=resume,
            worker=worker_id(),
        )

    def cancel(self, job_id: str) -> bool:
        """Request cancellation.  The flag file is the cross-process
        signal a running worker polls; a still-queued job is terminally
        cancelled right here (claim attempts race the fold, but a
        worker that wins the claim then sees the flag and stands
        down)."""
        view = self.fold()
        job = view.jobs.get(job_id)
        if job is None or job["state"] in TERMINAL:
            return False
        try:
            with open(self._cancel_flag(job_id), "w") as fh:
                fh.write(worker_id())
        except OSError:
            pass
        if job["state"] == QUEUED:
            self.journal.append(
                "fleet_cancelled", job=job_id, worker=worker_id(),
                reason="while queued",
            )
        return True

    def cancel_requested(self, job_id: str) -> bool:
        return os.path.exists(self._cancel_flag(job_id))

    def mark_cancelled(self, job: dict, **fields) -> None:
        self.journal.append(
            "fleet_cancelled", job=job["id"], attempt=job["attempt"],
            worker=worker_id(), **fields
        )

    def read_result(self, job_id: str) -> Optional[dict]:
        try:
            with open(self.result_path(job_id), "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (FileNotFoundError, ValueError):
            return None

    # -- worker registry ------------------------------------------------------

    def register_worker(self, desc: dict) -> None:
        self.journal.append("fleet_worker", worker=worker_id(), **desc)

    def worker_stop(self, **fields) -> None:
        self.journal.append(
            "fleet_worker_stop", worker=worker_id(), **fields
        )

    def worker_vitals(self, vitals: dict) -> None:
        self.journal.append(
            "fleet_worker_vitals", worker=worker_id(), vitals=vitals,
        )

    # -- portfolio groups -----------------------------------------------------

    def resolve_portfolios(self, view: Optional[FleetView] = None) -> int:
        """Swarm resolution across workers: the first member whose
        verdict names a violation wins its group — remaining members
        are cancelled (their partial work stands in the journal) and
        the parent's result is written from the winner.  With no
        violation the parent resolves once every member is terminal,
        anchored on the first completed member.  The per-parent resolve
        lock makes exactly one sweeping worker the resolver."""
        view = view or self.fold()
        resolved = 0
        groups: Dict[str, List[dict]] = {}
        for job in view.jobs.values():
            if job["group"]:
                groups.setdefault(job["group"], []).append(job)
        for parent_id, members in groups.items():
            parent = view.jobs.get(parent_id)
            if parent is None or parent["state"] in TERMINAL:
                continue
            members.sort(key=lambda j: j["member"] or 0)
            winner = next(
                (m for m in members
                 if m["state"] == DONE and m["violation"]), None
            )
            all_terminal = all(m["state"] in TERMINAL for m in members)
            if winner is None and not all_terminal:
                continue
            if not _try_lock(self._lock(f"{parent_id}.resolve")):
                resolved += 1  # someone else is resolving it
                continue
            if winner is not None:
                for m in members:
                    if m["state"] not in TERMINAL:
                        self.cancel(m["id"])
            anchor = winner or next(
                (m for m in members if m["state"] == DONE), None
            )
            if anchor is None:
                self.fail(parent, "every portfolio member failed")
                resolved += 1
                continue
            result = dict(self.read_result(anchor["id"]) or {})
            result["portfolio"] = {
                "size": len(members),
                "winner": (winner or {}).get("member"),
                "members": [
                    {"job": m["id"], "member": m["member"],
                     "state": m["state"], "violation": m["violation"],
                     "worker": m["worker"]}
                    for m in members
                ],
            }
            self.journal.append(
                "fleet_portfolio_winner", job=parent_id,
                member=(winner or {}).get("member"),
                member_job=(winner or anchor)["id"], worker=worker_id(),
            )
            self.finish(parent, result)
            resolved += 1
        return resolved
