"""Per-example command-line interface, shared by every model module.

The reference ships each example as a mini-binary with ``check`` /
``check-sym`` / ``check-simulation`` / ``explore`` / ``spawn`` subcommands
and a NETWORK positional parsed through the network name registry
(reference: examples/paxos.rs:355-513, src/actor/network.rs:318-331) — the
"embedded TLC" UX: run a model from a shell, point a browser at
``explore``.  Here every model module under ``stateright_tpu.models`` is
runnable the same way::

    python -m stateright_tpu.models.paxos check 2
    python -m stateright_tpu.models.paxos check-tpu 3
    python -m stateright_tpu.models.twophase check-sym 5
    python -m stateright_tpu.models.paxos explore 2 localhost:3017
    python -m stateright_tpu.models.paxos spawn

This package adds two subcommands the reference does not have: ``check-dfs``
(the reference folds it into per-example flags) and ``check-tpu`` (the TPU
wavefront engine, for models with a compiled form).
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Optional

from .actor.network import Network


def _usage(name: str, spec: "CliSpec") -> str:
    lines = [f"usage for {name}:"]
    n_meta = spec.n_meta
    net = " [NETWORK]" if spec.default_network else ""
    lines.append(f"  check [{n_meta}]{net}")
    lines.append(f"  check-dfs [{n_meta}]{net}")
    if spec.symmetry:
        tpu_flag = " [--tpu]" if spec.tpu else ""
        lines.append(f"  check-sym [{n_meta}]{net}{tpu_flag}")
    lines.append(f"  check-simulation [{n_meta}] [SEED]{net}")
    if spec.tpu:
        lines.append(f"  check-tpu [{n_meta}]{net}"
                     " [--supervise] [--checkpoint-dir DIR] [--resume]"
                     " [--trace] [--sharded[=SHARDS]] [--bucket-slack PCT]"
                     " [--sort-lanes N] [--sortless|--no-sortless]"
                     " [--step-lanes N]"
                     " [--tiered] [--memory-budget-mb MB]"
                     " [--store-dir DIR] [--incremental]"
                     " [--xprof-dir DIR]")
        lines.append(f"  reshard [{n_meta}] IN.npz OUT.npz --shards M{net}")
    lines.append(f"  explore [{n_meta}] [ADDRESS]{net}")
    lines.append(
        "  serve [ADDRESS] [--journal PATH] [--journal-max-mb MB]"
        " [--knob-cache DIR] [--workers N] [--store-dir DIR]"
        " [--fleet-dir DIR]"
    )
    lines.append(
        "  fleet-worker --fleet-dir DIR [--knob-cache DIR]"
        " [--lease-sec S] [--gang-max K] [--accept-big]"
        " [--preempt-after S] [--once]"
    )
    lines.append(
        "  fleet {submit|status|cancel|quota} --fleet-dir DIR ..."
    )
    lines.append(
        f"  submit [{n_meta}]{net} [--address ADDR] [--engine ENGINE]"
        " [--portfolio K] [--portfolio-seed S] [--priority P]"
        " [--no-wait]"
    )
    lines.append("  status [JOB_ID] [--address ADDR]")
    lines.append(
        "  report <journal.jsonl | BENCH-glob | dir> [--json]"
        " [--out FILE] [--threshold FRAC] [--timeline-out FILE]"
    )
    lines.append("  watch <journal.jsonl> [--interval SEC] [--once]")
    lines.append(
        "  timeline export <journal.jsonl | run-dir | fleet-dir>..."
        " [--out FILE]"
    )
    if spec.spawn is not None:
        lines.append(
            "  spawn [--chaos SPEC_JSON] [--seed N] [--audit]"
            " [--journal PATH] [--duration SEC] [--metrics-port PORT]"
            " [--trace]"
        )
    if spec.ensemble:
        lines.append(
            "  check-ensemble [--members K] [--seed N]"
            " [--chaos SPEC_JSON] [--steps T] [--fault HOOK]"
            " [--journal PATH] [--no-shrink] [--no-replay]"
        )
    if spec.default_network:
        lines.append(f"NETWORK: one of {' | '.join(Network.names())}")
    return "\n".join(lines)


class CliSpec:
    def __init__(
        self,
        name: str,
        build: Callable[..., Any],  # build(n) or build(n, network) -> Model
        default_n: int,
        n_meta: str = "N",
        default_network: Optional[str] = None,
        symmetry: bool = False,
        tpu: bool = False,
        tpu_kwargs: Optional[dict] = None,
        spawn: Optional[Callable[[], Any]] = None,
        default_address: str = "localhost:3017",
        target_max_depth: Optional[int] = None,
        tpu_target_max_depth: Optional[int] = None,
        ensemble: bool = False,
    ):
        self.name = name
        self.build = build
        self.default_n = default_n
        self.n_meta = n_meta
        self.default_network = default_network
        self.symmetry = symmetry
        self.tpu = tpu
        self.tpu_kwargs = tpu_kwargs or {}
        self.spawn = spawn
        self.default_address = default_address
        self.ensemble = ensemble
        self.target_max_depth = target_max_depth
        # Device-run depth override: raft's reference default (12) needs
        # ~4x10^7 stored states — beyond one chip's HBM at its state
        # width — so its check-tpu bounds depth where a single chip can
        # hold the store (models/raft_compiled.py documents the math).
        self.tpu_target_max_depth = tpu_target_max_depth


def _parse_n(args, default):
    if args and args[0].isdigit():
        return int(args.pop(0))
    return default


def _extract_runtime_flags(args):
    """Pull the supervised-run flags out of the positional stream (they
    may appear anywhere after the subcommand).  Returns
    ``(positional_args, supervise, checkpoint_dir, resume, trace,
    sharded, bucket_slack, sort_lanes, sortless, step_lanes, tiered,
    memory_budget_mb, store_dir, incremental)`` —
    ``sharded`` is None (single-chip), 0 (mesh over every visible
    device), or a mesh width; ``bucket_slack`` is the sharded engine's
    exchange-bucket rung in percent; ``sort_lanes`` the dedup-sort
    geometry rung, ``sortless``/``--no-sortless`` the dedup-path
    selection (claim-plane election vs the sorted fallback), and
    ``step_lanes`` the frontier-sized chunk rung (any device engine;
    docs/OBSERVABILITY.md "Sortless dedup and the rung ladders");
    ``tiered``/``memory_budget_mb`` select
    the out-of-core engine under an HBM budget (docs/TIERED.md; the
    budget flag alone implies ``--tiered``); ``store_dir`` /
    ``incremental`` route the check through the persistent verification
    store (docs/INCREMENTAL.md: ``--store-dir`` alone records the run,
    ``--incremental`` additionally reuses stored entries);
    ``xprof_dir`` wraps the run in a JAX profiler trace
    (``jax.profiler.start_trace``) with per-quantum step annotations
    whose names match the journal's host-span phases
    (docs/OBSERVABILITY.md "Timeline export and profiling") — or raises
    ``ValueError`` on a malformed flag."""
    supervise = False
    resume = False
    trace = False
    ckpt_dir = None
    sharded = None
    bucket_slack = None
    sort_lanes = None
    sortless = None
    step_lanes = None
    tiered = False
    memory_budget_mb = None
    store_dir = None
    incremental = False
    xprof_dir = None
    out = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--supervise":
            supervise = True
        elif a == "--incremental":
            incremental = True
        elif a == "--store-dir" or a.startswith("--store-dir="):
            if a == "--store-dir":
                i += 1
                if i >= len(args):
                    raise ValueError("--store-dir requires a directory")
                store_dir = args[i]
            else:
                store_dir = a.split("=", 1)[1]
            if not store_dir:
                raise ValueError(
                    "--store-dir requires a non-empty directory"
                )
        elif a == "--resume":
            resume = True
        elif a == "--trace":
            trace = True
        elif a == "--tiered":
            tiered = True
        elif a == "--memory-budget-mb" or a.startswith("--memory-budget-mb="):
            if a == "--memory-budget-mb":
                i += 1
                if i >= len(args):
                    raise ValueError(
                        "--memory-budget-mb requires a size in MB"
                    )
                val = args[i]
            else:
                val = a.split("=", 1)[1]
            try:
                memory_budget_mb = float(val)
            except ValueError:
                raise ValueError(
                    "--memory-budget-mb requires a number of MB "
                    "(fractions allowed)"
                ) from None
            import math

            if not math.isfinite(memory_budget_mb) or memory_budget_mb <= 0:
                # float() parses "nan"/"inf" happily; they must die here
                # as a usage error, not as a traceback deep in spawn.
                raise ValueError(
                    "--memory-budget-mb must be positive and finite"
                )
            tiered = True
        elif a == "--sharded":
            sharded = 0  # all visible devices
        elif a.startswith("--sharded="):
            try:
                sharded = int(a.split("=", 1)[1])
            except ValueError:
                raise ValueError("--sharded=SHARDS requires an integer")
            if sharded < 1:
                raise ValueError("--sharded=SHARDS requires SHARDS >= 1")
        elif a == "--bucket-slack" or a.startswith("--bucket-slack="):
            if a == "--bucket-slack":
                i += 1
                if i >= len(args):
                    raise ValueError(
                        "--bucket-slack requires a percentage"
                    )
                val = args[i]
            else:
                val = a.split("=", 1)[1]
            try:
                bucket_slack = int(val)
            except ValueError:
                raise ValueError(
                    "--bucket-slack requires an integer percentage"
                )
            if bucket_slack < 1:
                raise ValueError("--bucket-slack must be >= 1")
        elif a == "--sort-lanes" or a.startswith("--sort-lanes="):
            if a == "--sort-lanes":
                i += 1
                if i >= len(args):
                    raise ValueError("--sort-lanes requires a lane count")
                val = args[i]
            else:
                val = a.split("=", 1)[1]
            try:
                sort_lanes = int(val)
            except ValueError:
                raise ValueError(
                    "--sort-lanes requires an integer lane count"
                ) from None
            if sort_lanes < 1:
                raise ValueError("--sort-lanes must be >= 1")
        elif a == "--sortless":
            sortless = True
        elif a == "--no-sortless":
            sortless = False
        elif a == "--step-lanes" or a.startswith("--step-lanes="):
            if a == "--step-lanes":
                i += 1
                if i >= len(args):
                    raise ValueError("--step-lanes requires a lane count")
                val = args[i]
            else:
                val = a.split("=", 1)[1]
            try:
                step_lanes = int(val)
            except ValueError:
                raise ValueError(
                    "--step-lanes requires an integer lane count"
                ) from None
            if step_lanes < 1:
                raise ValueError("--step-lanes must be >= 1")
        elif a == "--xprof-dir" or a.startswith("--xprof-dir="):
            if a == "--xprof-dir":
                i += 1
                if i >= len(args):
                    raise ValueError("--xprof-dir requires a directory")
                xprof_dir = args[i]
            else:
                xprof_dir = a.split("=", 1)[1]
            if not xprof_dir:
                raise ValueError(
                    "--xprof-dir requires a non-empty directory"
                )
        elif a == "--checkpoint-dir":
            i += 1
            if i >= len(args):
                raise ValueError("--checkpoint-dir requires a directory")
            ckpt_dir = args[i]
        elif a.startswith("--checkpoint-dir="):
            ckpt_dir = a.split("=", 1)[1]
            if not ckpt_dir:
                # An empty value (e.g. --checkpoint-dir=$DIR with DIR
                # unset) would resolve to the CWD, where a non-resume
                # supervised run DELETES run-artifact-named files.
                raise ValueError(
                    "--checkpoint-dir requires a non-empty directory"
                )
        else:
            out.append(a)
        i += 1
    return (
        out, supervise, ckpt_dir, resume, trace, sharded, bucket_slack,
        sort_lanes, sortless, step_lanes, tiered, memory_budget_mb,
        store_dir, incremental, xprof_dir,
    )


def _parse_chaos_flags(args, trace: bool = False):
    """Parse the ``spawn`` subcommand's chaos/observability flags.
    Returns ``(leftover_args, ChaosOptions | None)``; raises
    ``ValueError`` on a malformed flag or chaos spec.  ``--chaos @FILE``
    reads the spec JSON from a file.  ``trace`` arrives pre-parsed (the
    shared runtime-flag parser consumed ``--trace``); it alone — like
    ``--metrics-port`` — is enough to build options around an empty
    (fault-free) chaos spec, so a spawned system can be traced or
    scraped without injecting any faults."""
    from .runtime.chaos import ChaosSpec

    spec_json = None
    seed = 0
    audit = False
    journal = None
    duration = 10.0
    metrics_port = None
    seen_any = bool(trace)
    out = []
    i = 0

    def value_of(flag):
        nonlocal i
        i += 1
        if i >= len(args):
            raise ValueError(f"{flag} requires a value")
        return args[i]

    while i < len(args):
        a = args[i]
        if a == "--chaos":
            spec_json, seen_any = value_of(a), True
        elif a == "--seed":
            v = value_of(a)
            try:
                seed = int(v)
            except ValueError:
                raise ValueError("--seed requires an integer") from None
            seen_any = True
        elif a == "--audit":
            audit, seen_any = True, True
        elif a == "--journal":
            journal, seen_any = value_of(a), True
        elif a == "--metrics-port" or a.startswith("--metrics-port="):
            v = a.split("=", 1)[1] if "=" in a else value_of(a)
            try:
                metrics_port = int(v)
            except ValueError:
                raise ValueError(
                    "--metrics-port requires a port number (0 = ephemeral)"
                ) from None
            if metrics_port < 0 or metrics_port > 65535:
                raise ValueError("--metrics-port must be in [0, 65535]")
            seen_any = True
        elif a == "--duration":
            v = value_of(a)
            try:
                duration = float(v)
            except ValueError:
                raise ValueError("--duration requires seconds") from None
            if duration <= 0:
                raise ValueError("--duration must be positive")
            seen_any = True
        else:
            out.append(a)
        i += 1
    if not seen_any:
        return out, None
    if spec_json is None:
        spec_json = "{}"  # --audit/--seed alone: fault-free chaos harness
    if spec_json.startswith("@"):
        try:
            with open(spec_json[1:], "r", encoding="utf-8") as f:
                spec_json = f.read()
        except OSError as e:
            raise ValueError(f"--chaos {spec_json}: {e}") from None
    chaos = ChaosOptions(
        spec=ChaosSpec.from_json(spec_json),
        seed=seed,
        audit=audit,
        journal=journal,
        duration=duration,
        metrics_port=metrics_port,
        trace=trace,
    )
    return out, chaos


def _run_check_ensemble(spec: "CliSpec", args) -> int:
    """The ``check-ensemble`` verb: one device dispatch sweeping K
    independent fault schedules (ensemble/engine.py), shrinking and
    host-replaying any failing seed.  Exits ``VIOLATION_RC`` when a
    failing schedule was found (host-confirmed when replay is on), so
    CI gates on it like on ``check-tpu``."""
    import json as _json

    from .runtime.supervisor import VIOLATION_RC

    members, seed, steps = 1024, 0, 64
    chaos_json, fault, journal = None, None, None
    shrink, replay = True, True
    i = 0

    def value_of(flag):
        nonlocal i
        i += 1
        if i >= len(args):
            raise ValueError(f"{flag} requires a value")
        return args[i]

    def int_of(flag, minimum=0):
        v = value_of(flag)
        try:
            n = int(v)
        except ValueError:
            raise ValueError(f"{flag} requires an integer") from None
        if n < minimum:
            raise ValueError(f"{flag} must be >= {minimum}")
        return n

    try:
        while i < len(args):
            a = args[i]
            if a == "--members":
                members = int_of(a, minimum=1)
            elif a == "--seed":
                seed = int_of(a)
            elif a == "--steps":
                steps = int_of(a, minimum=1)
            elif a == "--chaos":
                chaos_json = value_of(a)
            elif a == "--fault":
                fault = value_of(a)
            elif a == "--journal":
                journal = value_of(a)
            elif a == "--no-shrink":
                shrink = False
            elif a == "--no-replay":
                replay = False
            else:
                raise ValueError(f"unknown check-ensemble flag: {a}")
            i += 1
        if chaos_json is not None and chaos_json.startswith("@"):
            try:
                with open(chaos_json[1:], "r", encoding="utf-8") as f:
                    chaos_json = f.read()
            except OSError as e:
                raise ValueError(f"--chaos {chaos_json}: {e}") from None
        from .ensemble import run_ensemble

        result = run_ensemble(
            members=members,
            seed=seed,
            chaos=chaos_json,
            steps=steps,
            fault=fault,
            journal=journal,
            shrink=shrink,
            replay=replay,
        )
    except ValueError as e:
        print(e, file=sys.stderr)
        return 2
    print(_json.dumps(result.to_dict(), sort_keys=True, default=str))
    found = result.confirmed if replay else result.failing
    if found:
        print(
            f"failing schedule discovered: member "
            f"{result.repro['member']}, seed {result.repro['seed']}",
            file=sys.stderr,
        )
        return VIOLATION_RC
    return 0


class ChaosOptions:
    """Parsed ``spawn --chaos`` flags, handed to a chaos-capable spawn
    target (one whose callable accepts a ``chaos`` keyword).
    ``metrics_port`` serves the runtime's live ``/.metrics`` and
    ``trace`` turns on the causal trace envelope (docs/OBSERVABILITY.md
    "Actor-runtime observability")."""

    def __init__(self, spec, seed, audit, journal, duration,
                 metrics_port=None, trace=False):
        self.spec = spec
        self.seed = seed
        self.audit = audit
        self.journal = journal
        self.duration = duration
        self.metrics_port = metrics_port
        self.trace = trace


def _parse_network(args, spec):
    """Consume the NETWORK positional (front of the remaining args).  An
    unknown name is an error, like the reference's FromStr parse
    (src/actor/network.rs:318-331) — never a silent default."""
    if spec.default_network is None:
        return None
    if args:
        return Network.from_name(args.pop(0))
    return Network.from_name(spec.default_network)


def _reject_leftovers(args, spec):
    if args:
        print(f"unexpected argument(s): {' '.join(args)}", file=sys.stderr)
        print(_usage(spec.name, spec), file=sys.stderr)
        raise SystemExit(2)


def _build(spec, n, network):
    if spec.default_network is None:
        return spec.build(n)
    return spec.build(n, network)


def _checkpointed_tpu_kwargs(ckpt_dir: str, resume: bool) -> dict:
    """Engine kwargs pointing the journal/checkpoint hooks into a run
    directory (the supervised child's layout, also usable stand-alone):
    journal.jsonl telemetry, checkpoint.npz snapshots, a relax.json
    geometry override left by the supervisor's backoff, and resume from
    the latest checkpoint when asked."""
    from .runtime.supervisor import (
        CHECKPOINT_FILE, JOURNAL_FILE, RELAX_FILE, load_json_or_default,
    )

    run_dir = os.path.abspath(ckpt_dir)
    os.makedirs(run_dir, exist_ok=True)
    # A torn relax.json degrades to no overrides, never a crash.
    kwargs: dict = dict(
        load_json_or_default(os.path.join(run_dir, RELAX_FILE), {})
    )
    ckpt = os.path.join(run_dir, CHECKPOINT_FILE)
    kwargs["journal"] = os.path.join(run_dir, JOURNAL_FILE)
    kwargs["checkpoint_path"] = ckpt
    if resume and os.path.exists(ckpt):
        kwargs["resume_from"] = ckpt
    return kwargs


def _run_supervised(spec: "CliSpec", n, network, ckpt_dir: str,
                    resume: bool, tiered: bool = False,
                    memory_budget_mb=None, sharded=None) -> int:
    """Parent mode for ``check-tpu --supervise``: re-invoke this model
    module's own CLI as the supervised child (with ``--checkpoint-dir``/
    ``--resume``), watch its journal for death and hangs, and restart it
    from the latest checkpoint until the check completes.  Tiered and
    mesh flags are forwarded verbatim so the restarted child resumes
    the same out-of-core run on the same mesh width (its checkpoint
    embeds the cold tiers and the shard count)."""
    from .runtime.supervisor import (
        RunSupervisor, SupervisorConfig, SupervisorError,
    )

    run_dir = os.path.abspath(ckpt_dir)
    module = _module_name(spec)
    if module is None:
        print(
            "--supervise requires running the model module via "
            "`python -m stateright_tpu.models.<name>` (the supervisor "
            "re-invokes that module as the child)",
            file=sys.stderr,
        )
        return 2
    child = [sys.executable, "-m", module, "check-tpu", str(n)]
    if network is not None:
        child.append(network.kind)
    if tiered:
        child.append("--tiered")
    if memory_budget_mb is not None:
        child.append(f"--memory-budget-mb={memory_budget_mb}")
    if sharded is not None:
        child.append("--sharded" if sharded == 0 else f"--sharded={sharded}")
    child += ["--checkpoint-dir", run_dir, "--resume"]
    if tiered and sharded is not None:
        engine = "tiered-sharded"
    elif tiered:
        engine = "tiered"
    else:
        engine = "tpu"
    # Seed the geometry backoff with the child's ACTUAL engine knobs:
    # the policy only relaxes knobs it can see, so without these the
    # frontier/waves steps could never fire in CLI mode.  The sharded
    # engines speak chunk_size, so the single-chip names translate the
    # same way the check-tpu dispatch translates them.
    backoff_kwargs = dict(spec.tpu_kwargs)
    if sharded is not None:
        if "max_frontier" in backoff_kwargs:
            backoff_kwargs["chunk_size"] = backoff_kwargs.pop("max_frontier")
        for single_chip_only in ("log_capacity", "waves_per_call",
                                 "auto_tune"):
            backoff_kwargs.pop(single_chip_only, None)
    sup = RunSupervisor(
        SupervisorConfig(
            run_dir=run_dir,
            resume=resume,
            inherit_output=True,
            call_deadline_sec=600.0,
            engine=engine,
        ),
        child_argv=child,
        engine_kwargs=backoff_kwargs,
    )
    try:
        result = sup.run()
    except SupervisorError as e:
        print(e, file=sys.stderr)
        return 1
    if not result.get("completed", True):
        print(
            "supervised run hit its wall deadline; partial progress is "
            f"checkpointed in {run_dir}",
            file=sys.stderr,
        )
        return 1
    # Propagate the child's verdict: a supervised check that completed
    # WITH a violation still gates (VIOLATION_RC), it just isn't a
    # crash the supervisor retries.
    return sup.last_child_rc or 0


def _run_reshard(spec: "CliSpec", args) -> int:
    """The ``reshard`` verb: re-key a sharded or tiered-sharded
    checkpoint onto a new mesh width (docs/TIERED.md "Elastic
    resharding").  Re-routes every logged state row to its owner under
    the new width and writes a tiered-sharded snapshot that resumes on
    an M-shard mesh — host-side work plus single-device fingerprint
    evaluation; the target mesh need not be attached."""
    import json as _json

    if not spec.tpu:
        print(f"{spec.name} has no compiled TPU form", file=sys.stderr)
        return 2
    shards = None
    rest = []
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--shards" or a.startswith("--shards="):
            if a == "--shards":
                i += 1
                if i >= len(args):
                    print("--shards requires a value", file=sys.stderr)
                    return 2
                raw = args[i]
            else:
                raw = a.split("=", 1)[1]
            try:
                shards = int(raw)
            except ValueError:
                print(f"--shards requires an integer, got {raw!r}",
                      file=sys.stderr)
                return 2
        else:
            rest.append(a)
        i += 1
    if shards is None or shards < 1:
        print(
            "reshard requires --shards M (the new mesh width, >= 1): "
            f"reshard [{spec.n_meta}] IN.npz OUT.npz --shards M",
            file=sys.stderr,
        )
        return 2
    n = _parse_n(rest, spec.default_n)
    if len(rest) < 2:
        print(
            "reshard requires the snapshot paths: "
            f"reshard [{spec.n_meta}] IN.npz OUT.npz --shards M",
            file=sys.stderr,
        )
        return 2
    in_path, out_path = rest.pop(0), rest.pop(0)
    try:
        network = _parse_network(rest, spec)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 2
    _reject_leftovers(rest, spec)
    model = _build(spec, n, network)
    from .tiered.reshard import reshard_snapshot

    try:
        summary = reshard_snapshot(model, in_path, out_path, shards)
    except (ValueError, KeyError, OSError) as e:
        print(e, file=sys.stderr)
        return 1
    # One parseable line so shell pipelines (and the CI reshard smoke)
    # can gate on the conversion without reading the snapshot back.
    print("reshard: " + _json.dumps(summary, sort_keys=True, default=int))
    return 0


# --- checking-service client verbs (docs/SERVING.md) -------------------------

def _module_name(spec: "CliSpec") -> Optional[str]:
    """The model module's runnable dotted name — the build callable's
    __module__, EXCEPT when this process was started as `python -m
    <module>`: then the lambda lives in __main__ and the real name is on
    __main__.__spec__ (set by runpy)."""
    module = spec.build.__module__
    if module == "__main__":
        main_spec = getattr(sys.modules.get("__main__"), "__spec__", None)
        if main_spec is not None and main_spec.name:
            module = main_spec.name
    return None if module == "__main__" else module


def _workload_name(spec: "CliSpec") -> Optional[str]:
    """The service workload name this model module is registered under
    (serve/workloads.py): the module's last dotted component."""
    module = _module_name(spec)
    if module is None or not module.startswith("stateright_tpu.models."):
        return None
    return module.rsplit(".", 1)[1]


def _http_json(method: str, url: str, body=None, timeout: float = 30.0):
    """One JSON request against the checking service; raises ValueError
    with the server's error message on a 4xx/5xx."""
    import json as _json
    import urllib.error
    import urllib.request

    data = None if body is None else _json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return _json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            detail = _json.loads(e.read()).get("error", "")
        except Exception:
            detail = ""
        raise ValueError(
            f"{method} {url}: HTTP {e.code}"
            + (f": {detail}" if detail else "")
        ) from None
    except urllib.error.URLError as e:
        raise ValueError(
            f"cannot reach the checking service at {url}: {e.reason} "
            "(start one with the `serve` subcommand or "
            "`python -m stateright_tpu.serve`)"
        ) from None


class SubmitOptions:
    def __init__(self):
        # One source of truth for the service's default address: the
        # daemon entry point the client verbs talk to.
        from .serve.__main__ import DEFAULT_ADDRESS

        self.address = DEFAULT_ADDRESS
        self.engine: Optional[str] = None
        self.portfolio = 0
        self.portfolio_seed = 0
        self.priority = 0
        self.no_wait = False


def _parse_submit_flags(args):
    """Flags for ``submit``/``status``; returns (positionals, options)
    or raises ValueError."""
    opts = SubmitOptions()
    out = []
    i = 0

    def value_of(flag, cast=str):
        nonlocal i
        i += 1
        if i >= len(args):
            raise ValueError(f"{flag} requires a value")
        try:
            return cast(args[i])
        except ValueError:
            raise ValueError(f"{flag} requires a {cast.__name__}") from None

    while i < len(args):
        a = args[i]
        if a == "--address":
            opts.address = value_of(a)
        elif a == "--engine":
            opts.engine = value_of(a)
        elif a == "--portfolio":
            opts.portfolio = value_of(a, int)
        elif a == "--portfolio-seed":
            opts.portfolio_seed = value_of(a, int)
        elif a == "--priority":
            opts.priority = value_of(a, int)
        elif a == "--no-wait":
            opts.no_wait = True
        else:
            out.append(a)
        i += 1
    return out, opts


def _run_submit(spec: "CliSpec", args) -> int:
    """Client half of the checking service: POST this model as a job,
    poll to a terminal state, exit on the verdict — 0 clean,
    VIOLATION_RC on a discovered violation, 1 on failure/cancellation
    (so CI can gate on a served check exactly like on check-tpu)."""
    import json as _json

    from .runtime.supervisor import VIOLATION_RC

    try:
        args, opts = _parse_submit_flags(args)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 2
    n = _parse_n(args, spec.default_n)
    network = None
    if spec.default_network is not None and args and args[0] in Network.names():
        network = args.pop(0)
    _reject_leftovers(args, spec)
    workload = _workload_name(spec)
    if workload is None:
        print(
            "submit requires running the model module via "
            "`python -m stateright_tpu.models.<name>` (the job names "
            "that workload to the service)",
            file=sys.stderr,
        )
        return 2
    body = {
        "workload": workload,
        "n": n,
        "engine": opts.engine or ("tpu" if spec.tpu else "bfs"),
        "priority": opts.priority,
    }
    if network is not None:
        body["network"] = network
    if opts.portfolio:
        body["portfolio"] = {
            "size": opts.portfolio, "seed": opts.portfolio_seed,
        }
    base = f"http://{opts.address}"
    try:
        resp = _http_json("POST", base + "/jobs", body)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1
    job_id = resp["id"]
    print(f"submitted {job_id} ({workload} n={n}) to {base}")
    if opts.no_wait:
        return 0
    while True:
        try:
            snap = _http_json(
                "GET", f"{base}/jobs/{job_id}/result?wait=10",
                timeout=30.0,
            )
        except ValueError as e:
            print(e, file=sys.stderr)
            return 1
        if snap["state"] not in ("queued", "running"):
            break
    print(_json.dumps(snap, sort_keys=True))
    if snap["state"] != "done":
        print(f"job {job_id} {snap['state']}: {snap.get('error') or ''}",
              file=sys.stderr)
        return 1
    if (snap.get("result") or {}).get("violation"):
        print(
            f"violation discovered: {snap['result']['violation']}",
            file=sys.stderr,
        )
        return VIOLATION_RC
    return 0


def _run_status(spec: "CliSpec", args) -> int:
    import json as _json

    try:
        args, opts = _parse_submit_flags(args)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 2
    job_id = args.pop(0) if args else None
    _reject_leftovers(args, spec)
    base = f"http://{opts.address}"
    url = f"{base}/jobs/{job_id}" if job_id else f"{base}/jobs"
    try:
        print(_json.dumps(_http_json("GET", url), sort_keys=True))
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1
    return 0


def example_main(spec: CliSpec, argv=None) -> int:
    from .core.report import WriteReporter

    args = list(sys.argv[1:] if argv is None else argv)
    if not args or args[0] in ("-h", "--help", "help"):
        print(_usage(spec.name, spec))
        return 0
    sub = args.pop(0)
    try:
        (
            args, supervise, ckpt_dir, resume, trace, sharded, bucket_slack,
            sort_lanes, sortless, step_lanes, tiered, memory_budget_mb,
            store_dir, incremental, xprof_dir,
        ) = _extract_runtime_flags(args)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 2
    if incremental and (store_dir is None or sub != "check-tpu"):
        print(
            "--incremental requires check-tpu with --store-dir DIR (the "
            "persistent verification store it reuses; "
            "docs/INCREMENTAL.md)",
            file=sys.stderr,
        )
        return 2
    if store_dir is not None and sub not in ("check-tpu", "serve"):
        print(
            "--store-dir requires the check-tpu subcommand (or `serve`, "
            "where it enables the service's verification store; "
            "docs/INCREMENTAL.md)",
            file=sys.stderr,
        )
        return 2
    if store_dir is not None and (
        sharded is not None or tiered or trace or supervise or resume
        or ckpt_dir is not None
    ):
        print(
            "--store-dir does not combine with --sharded/--tiered/"
            "--trace/--supervise/--checkpoint-dir/--resume (the store "
            "journals plain spawn_tpu runs; run those modes without the "
            "store)",
            file=sys.stderr,
        )
        return 2
    if xprof_dir is not None and (sub != "check-tpu" or supervise):
        print(
            "--xprof-dir requires the check-tpu subcommand without "
            "--supervise (the profiler wraps one in-process run; "
            "docs/OBSERVABILITY.md \"Timeline export and profiling\")",
            file=sys.stderr,
        )
        return 2
    if (sharded is not None or bucket_slack is not None) and sub != "check-tpu":
        print(
            "--sharded/--bucket-slack require the check-tpu subcommand",
            file=sys.stderr,
        )
        return 2
    if sort_lanes is not None and sub != "check-tpu":
        print(
            "--sort-lanes requires the check-tpu subcommand (it sizes "
            "the device engines' dedup-sort rung)",
            file=sys.stderr,
        )
        return 2
    if (sortless is not None or step_lanes is not None) and sub != "check-tpu":
        print(
            "--sortless/--no-sortless/--step-lanes require the "
            "check-tpu subcommand (they select the device engines' "
            "dedup path and chunk rung)",
            file=sys.stderr,
        )
        return 2
    if tiered and sub != "check-tpu":
        print(
            "--tiered/--memory-budget-mb require the check-tpu "
            "subcommand (the tiered engine is the out-of-core wavefront; "
            "docs/TIERED.md)",
            file=sys.stderr,
        )
        return 2
    if tiered and sharded is not None and trace:
        print(
            "--tiered --sharded does not combine with --trace (the "
            "composed pod-scale engine has no traced mode; trace the "
            "single-chip tiered engine or the plain sharded engine)",
            file=sys.stderr,
        )
        return 2
    if bucket_slack is not None and sharded is None:
        print(
            "--bucket-slack requires --sharded (it sizes the sharded "
            "engine's per-destination exchange buckets)",
            file=sys.stderr,
        )
        return 2
    if (
        sharded is not None and not tiered
        and (supervise or resume or ckpt_dir)
    ):
        print(
            "--sharded alone does not combine with --supervise/"
            "--checkpoint-dir/--resume from the CLI yet; use "
            "runtime.RunSupervisor with engine='sharded', or add "
            "--tiered (the tiered-sharded engine checkpoints, resumes, "
            "and supervises from the CLI; docs/TIERED.md)",
            file=sys.stderr,
        )
        return 2
    if (supervise or ckpt_dir or resume) and sub != "check-tpu":
        print(
            "--supervise/--checkpoint-dir/--resume require the check-tpu "
            "subcommand (the host engines have no snapshot support)",
            file=sys.stderr,
        )
        return 2
    if trace and sub not in ("check-tpu", "spawn"):
        print(
            "--trace requires the check-tpu subcommand (phase-timed "
            "device wave tracing) or spawn (the actor runtime's causal "
            "trace envelope); docs/OBSERVABILITY.md",
            file=sys.stderr,
        )
        return 2
    if trace and (supervise or resume):
        # Traced runs are diagnostic and do not support resume; a
        # supervised child auto-resumes on restart, so the combination
        # is refused loudly instead of dying mid-restart.
        print(
            "--trace cannot be combined with --supervise/--resume "
            "(traced runs do not resume; run the trace unsupervised)",
            file=sys.stderr,
        )
        return 2
    if supervise and ckpt_dir is None:
        print("--supervise requires --checkpoint-dir DIR", file=sys.stderr)
        return 2
    if resume and ckpt_dir is None:
        # Silently starting from scratch would discard exactly the
        # progress the flag was meant to continue.
        print("--resume requires --checkpoint-dir DIR", file=sys.stderr)
        return 2
    threads = os.cpu_count() or 1

    if sub in ("check", "check-bfs", "check-dfs", "check-sym", "check-tpu"):
        # check-sym --tpu: run the symmetry-reduced check on the TPU
        # wavefront engine (dedup on the compiled model's canonical-row
        # fingerprint, parallel/canon.py) instead of the host DFS.
        tpu_sym = sub == "check-sym" and "--tpu" in args
        if tpu_sym:
            args = [a for a in args if a != "--tpu"]
        n = _parse_n(args, spec.default_n)
        try:
            network = _parse_network(args, spec)
        except ValueError as e:
            print(e, file=sys.stderr)
            return 2
        _reject_leftovers(args, spec)
        if supervise:
            if not spec.tpu:
                print(f"{spec.name} has no compiled TPU form",
                      file=sys.stderr)
                return 2
            return _run_supervised(
                spec, n, network, ckpt_dir, resume,
                tiered=tiered, memory_budget_mb=memory_budget_mb,
                sharded=sharded,
            )
        xprof_active = False
        if xprof_dir is not None:
            # Hardware profiler hook (docs/OBSERVABILITY.md "Timeline
            # export and profiling"): wrap the whole run in a JAX
            # profiler trace.  The fused loop's per-quantum
            # StepTraceAnnotation names match the journal's host-span
            # phases, so the xprof timeline aligns with the journal's
            # `timeline export` view.
            from .obs.timeline import set_xprof

            try:
                import jax

                jax.profiler.start_trace(os.path.abspath(xprof_dir))
            except Exception as e:
                print(
                    f"--xprof-dir: profiler unavailable: {e}",
                    file=sys.stderr,
                )
                return 2
            set_xprof(True)
            xprof_active = True
        model = _build(spec, n, network)
        print(f"Checking {spec.name} with {spec.n_meta.lower()}={n}"
              + (f", network={network.kind}" if network is not None else ""))
        builder = model.checker().threads(threads)
        if sub == "check-tpu" and spec.tpu_target_max_depth is not None:
            builder = builder.target_max_depth(spec.tpu_target_max_depth)
        elif spec.target_max_depth is not None:
            # Some examples bound their default check (e.g. raft's
            # target_max_depth(12), examples/raft.rs:520-535).
            builder = builder.target_max_depth(spec.target_max_depth)
        if sub == "check-dfs":
            checker = builder.spawn_dfs()
        elif sub == "check-sym":
            if not spec.symmetry:
                print(f"{spec.name} has no symmetry reduction", file=sys.stderr)
                return 2
            if tpu_sym:
                if not spec.tpu:
                    print(f"{spec.name} has no compiled TPU form",
                          file=sys.stderr)
                    return 2
                checker = builder.symmetry().spawn_tpu(**dict(spec.tpu_kwargs))
            else:
                checker = builder.symmetry().spawn_dfs()
        elif sub == "check-tpu":
            if not spec.tpu:
                print(f"{spec.name} has no compiled TPU form", file=sys.stderr)
                return 2
            tpu_kwargs = dict(spec.tpu_kwargs)
            if ckpt_dir is not None:
                tpu_kwargs.update(_checkpointed_tpu_kwargs(ckpt_dir, resume))
            if trace:
                # Phase-timed wave tracing (docs/OBSERVABILITY.md); with
                # --checkpoint-dir the enriched wave records land in the
                # run dir's journal.jsonl — the wave-trace artifact.
                tpu_kwargs["trace"] = True
            if sort_lanes is not None:
                # The dedup-sort geometry rung — a knob every device
                # engine accepts (single-chip, sharded, tiered).
                tpu_kwargs["sort_lanes"] = sort_lanes
            if sortless is not None:
                # Dedup-path selection: the claim-plane election
                # (default) vs the sorted fallback rung.
                tpu_kwargs["sortless"] = sortless
            if step_lanes is not None:
                # The frontier-sized chunk rung (the second ladder).
                tpu_kwargs["step_lanes"] = step_lanes
            if sharded is not None:
                # Multi-chip run over the first SHARDS visible devices
                # (0 = all).  The spec's single-chip kwargs translate:
                # max_frontier becomes the per-shard chunk, and the
                # single-chip-only knobs drop.
                import jax
                import numpy as _np

                devs = jax.devices()
                n_mesh = sharded or len(devs)
                if n_mesh > len(devs):
                    print(
                        f"--sharded={n_mesh} exceeds the {len(devs)} "
                        "visible devices",
                        file=sys.stderr,
                    )
                    return 2
                mesh = jax.sharding.Mesh(
                    _np.array(devs[:n_mesh]), ("shards",)
                )
                if "max_frontier" in tpu_kwargs:
                    tpu_kwargs["chunk_size"] = tpu_kwargs.pop(
                        "max_frontier"
                    )
                for single_chip_only in (
                    "log_capacity", "waves_per_call", "auto_tune",
                ):
                    tpu_kwargs.pop(single_chip_only, None)
                if bucket_slack is not None:
                    tpu_kwargs["bucket_slack"] = bucket_slack
                if tiered:
                    # The composed pod-scale engine: the sharded BFS
                    # with the HBM budget applied PER SHARD
                    # (docs/TIERED.md "Composing the levers").
                    if memory_budget_mb is not None:
                        tpu_kwargs["memory_budget_mb"] = memory_budget_mb
                    checker = builder.spawn_tpu_tiered_sharded(
                        mesh=mesh, **tpu_kwargs
                    )
                else:
                    checker = builder.spawn_tpu_sharded(
                        mesh=mesh, **tpu_kwargs
                    )
            elif tiered:
                # Out-of-core run under an HBM budget (docs/TIERED.md).
                # The budget is authoritative in the engine itself: it
                # overrides any spec-tuned capacity hint riding along.
                if memory_budget_mb is not None:
                    tpu_kwargs["memory_budget_mb"] = memory_budget_mb
                checker = builder.spawn_tpu_tiered(**tpu_kwargs)
            elif store_dir is not None:
                # Incremental re-checking through the persistent
                # verification store (docs/INCREMENTAL.md): classify
                # the spec delta and take the cheapest sound path —
                # verdict cache / property re-eval / seeded widening /
                # loud cold run.  The store's journal.jsonl carries the
                # incr_* evidence plus any engine events.
                from .incr.recheck import incremental_check

                checker, recheck_info = incremental_check(
                    builder,
                    store_dir,
                    engine_kwargs=tpu_kwargs,
                    journal=os.path.join(
                        os.path.abspath(store_dir), "journal.jsonl"
                    ),
                    reuse=incremental,
                )
            else:
                checker = builder.spawn_tpu(**tpu_kwargs)
        else:
            checker = builder.spawn_bfs()
        checker.join_and_report(WriteReporter(sys.stdout))
        if xprof_active:
            from .obs.timeline import set_xprof

            import jax

            set_xprof(False)
            jax.profiler.stop_trace()
            print(f"xprof: profiler trace written under {xprof_dir}")
        if sub == "check-tpu" and store_dir is not None:
            # One parseable line with the recheck classification, so
            # shell pipelines and the CI smoke can gate on the mode
            # without reading the store journal.
            import json as _json

            print("recheck: " + _json.dumps(recheck_info, sort_keys=True))
        if sub == "check-tpu" and trace:
            # One parseable line with the roofline reduction, so shell
            # pipelines (and the CI trace smoke) can gate on it without
            # reading the journal.
            import json as _json

            print("trace: " + _json.dumps(checker.trace_summary()))
        if sub == "check-tpu":
            # Gateable verdict (docs/SERVING.md): a COMPLETED check that
            # discovered a counterexample exits VIOLATION_RC so CI and
            # service callers can gate on the result without parsing the
            # report.  Examples (sometimes-property discoveries) are not
            # violations.
            from .runtime.supervisor import VIOLATION_RC

            violations = sorted(
                name for name in checker.discoveries()
                if checker.discovery_classification(name) == "counterexample"
            )
            if violations:
                print(
                    "violation discovered: " + ", ".join(violations),
                    file=sys.stderr,
                )
                return VIOLATION_RC
        return 0

    if sub == "check-simulation":
        n = _parse_n(args, spec.default_n)
        seed = int(args.pop(0)) if args and args[0].isdigit() else 0
        try:
            network = _parse_network(args, spec)
        except ValueError as e:
            print(e, file=sys.stderr)
            return 2
        _reject_leftovers(args, spec)
        model = _build(spec, n, network)
        print(f"Simulating {spec.name} with {spec.n_meta.lower()}={n}, "
              f"seed={seed}")
        from .core.simulation import UniformChooser

        (
            model.checker()
            .threads(threads)
            .target_state_count(1_000_000)
            .spawn_simulation(seed, UniformChooser())
            .join_and_report(WriteReporter(sys.stdout))
        )
        return 0

    if sub == "explore":
        # Positionals mirror the reference: [N] [ADDRESS] [NETWORK].
        n = _parse_n(args, spec.default_n)
        address = spec.default_address
        if args and args[0] not in Network.names():
            address = args.pop(0)
        try:
            network = _parse_network(args, spec)
        except ValueError as e:
            print(e, file=sys.stderr)
            return 2
        _reject_leftovers(args, spec)
        host, _, port = address.partition(":")
        try:
            port = int(port or 3017)
        except ValueError:
            print(f"invalid ADDRESS port: {address!r}", file=sys.stderr)
            return 2
        model = _build(spec, n, network)
        print(
            f"Exploring state space for {spec.name} with "
            f"{spec.n_meta.lower()}={n} on http://{host}:{port}"
        )
        model.checker().threads(threads).serve((host, port))
        return 0

    if sub == "check-ensemble":
        if not spec.ensemble:
            print(
                f"{spec.name} has no ensemble workload (check-ensemble "
                "needs a model with a compiled fault hook; "
                "docs/CHAOS_ENSEMBLES.md)",
                file=sys.stderr,
            )
            return 2
        return _run_check_ensemble(spec, args)

    if sub == "spawn":
        if spec.spawn is None:
            print(f"{spec.name} has no spawn target", file=sys.stderr)
            return 2
        try:
            args, chaos = _parse_chaos_flags(args, trace=trace)
        except ValueError as e:
            print(e, file=sys.stderr)
            return 2
        _reject_leftovers(args, spec)
        if chaos is None:
            rc = spec.spawn()
            return int(rc) if rc else 0
        import inspect

        if "chaos" not in inspect.signature(spec.spawn).parameters:
            print(
                f"{spec.name}'s spawn target is not chaos-capable "
                "(it takes no `chaos` keyword)",
                file=sys.stderr,
            )
            return 2
        rc = spec.spawn(chaos=chaos)
        return int(rc) if rc else 0

    if sub == "serve":
        # The checking-service daemon (serve/server.py): one process,
        # one mesh, many jobs — every registered workload is servable,
        # whichever model module launched it.  --store-dir was consumed
        # by the shared runtime-flag parser above; hand it back to the
        # daemon's own parser.
        from .serve.__main__ import main as serve_main

        if store_dir is not None:
            args = args + ["--store-dir", store_dir]
        return serve_main(args)

    if sub == "fleet-worker":
        # One fleet worker process: claims jobs from the shared durable
        # store and runs them on this process's backend (fleet/worker.py,
        # docs/SERVING.md "Fleet mode").
        from .fleet.worker import worker_main

        return worker_main(args)

    if sub == "fleet":
        # Fleet operator verbs: submit/status/cancel/quota against a
        # fleet directory (fleet/__main__.py).
        from .fleet.__main__ import main as fleet_main

        return fleet_main(args)

    if sub == "submit":
        return _run_submit(spec, args)

    if sub == "status":
        return _run_status(spec, args)

    if sub == "report":
        # Journal analytics / bench trajectory (obs/report.py,
        # docs/OBSERVABILITY.md "Run reports"): model-agnostic, rides on
        # every model CLI like `serve` does.
        from .obs.report import report_main

        return report_main(args)

    if sub == "watch":
        # Live journal tail -> one-line refreshing progress view
        # (obs/watch.py, docs/OBSERVABILITY.md "watch"); model-agnostic
        # like `report`.  `--once` prints a single snapshot (the CI
        # smoke's mode).
        from .obs.watch import watch_main

        return watch_main(args)

    if sub == "timeline":
        # Journal -> Chrome trace-event export (obs/timeline.py,
        # docs/OBSERVABILITY.md "Timeline export and profiling"):
        # merges run/serve/fleet journals onto one aligned timeline for
        # Perfetto / chrome://tracing.  Model-agnostic like `report`.
        from .obs.timeline import timeline_main

        return timeline_main(args)

    if sub == "reshard":
        return _run_reshard(spec, args)

    print(_usage(spec.name, spec))
    return 2


# --- shared spawn helper for register-harness systems ------------------------


def spawn_register_system(
    make_actors, count: int, name: str, make_transport=None,
    metrics_port=None, trace: bool = False, journal=None,
) -> None:
    """Run register-protocol servers over real localhost UDP, mirroring the
    reference examples' ``spawn`` subcommands (examples/paxos.rs:488-512):
    servers at 127.0.0.1:3000+i, JSON-over-datagram message encoding, until
    interrupted.  ``make_actors(ids)`` builds the server actors given their
    real socket-addr ``Id``s (peers must reference these, not model
    indices).  ``make_transport(ids)`` overrides the wire — e.g. a
    ``runtime.chaos.FaultyTransport`` wrapping UDP (with the chaos spec's
    model indices remapped onto the real ids), which is how
    ``spawn --chaos`` (without ``--audit``) injects faults into a system
    being poked externally with ``nc -u``.

    Observability (docs/OBSERVABILITY.md "Actor-runtime observability"):
    the transport is wrapped in an ``ObservedTransport`` — per-link
    datagram/byte counters always, the causal trace envelope under
    ``trace=True`` (``actor_span`` events into ``journal``) — and
    ``metrics_port`` serves the runtime's live ``GET /.metrics`` (JSON +
    Prometheus; 0 picks an ephemeral port, printed at startup)."""
    from .actor.ids import Id
    from .actor.obs import ObservedTransport, serve_actor_metrics
    from .actor.spawn import spawn
    from .actor.transport import UdpTransport
    from .actor.wire import wire_deserialize, wire_serialize

    ids = [
        Id.from_socket_addr((127, 0, 0, 1), 3000 + i) for i in range(count)
    ]
    base = make_transport(ids) if make_transport is not None else UdpTransport()
    transport = ObservedTransport(base, trace=trace, journal=journal)
    server_actors = make_actors(ids)
    print(f"A set of {name} servers is now running on:")
    for i in ids:
        print(f"  udp://127.0.0.1:{i.to_socket_addr()[1]}")
    print("Messages are JSON, e.g.:")
    print('  {"__t": "Get", "request_id": 1}')
    print('  {"__t": "Put", "request_id": 2, "value": "X"}')
    runtime = spawn(
        wire_serialize,
        wire_deserialize,
        wire_serialize,
        wire_deserialize,
        list(zip(ids, server_actors)),
        transport=transport,
        metrics=transport.registry,
    )
    metrics_server = None
    if metrics_port is not None:
        metrics_server = serve_actor_metrics(
            runtime, ("127.0.0.1", int(metrics_port))
        )
        host, port = metrics_server.server_address[:2]
        print(f"Metrics: http://{host}:{port}/.metrics "
              "(?format=prometheus for the text exposition)")
    try:
        runtime.join()
    except KeyboardInterrupt:
        runtime.stop(raise_errors=False)
    finally:
        if metrics_server is not None:
            metrics_server.shutdown()
