"""stateright_tpu — a TPU-native explicit-state model checker.

A brand-new framework with the capability surface of the reference Rust
library *stateright* (mounted read-only at /root/reference; see SURVEY.md):
a ``Model`` abstraction, always/sometimes/eventually properties, parallel
BFS/DFS/on-demand/simulation checkers with fingerprint dedup and path
reconstruction, symmetry reduction, an actor framework with pluggable
network semantics plus a real UDP runtime, linearizability and sequential
consistency testers, and a web Explorer — with the checker's hot loop
(successor expansion + frontier dedup + property evaluation) compiled to
JAX/XLA as a vmapped wavefront over bit-packed states with an HBM-resident
fingerprint hash set, sharded across chips with collectives.
"""

from .core.model import Model, Property, Expectation
from .core.checker import Checker, CheckerBuilder
from .core.path import Path, NondeterminismError
from .core.has_discoveries import HasDiscoveries
from .core.visitor import CheckerVisitor, PathRecorder, StateRecorder
from .core.report import (
    JournalReporter,
    ReportData,
    ReportDiscovery,
    Reporter,
    WriteReporter,
)
from .obs import MetricsRegistry, WaveTracer
from .ops.fingerprint import fingerprint

__all__ = [
    "MetricsRegistry",
    "WaveTracer",
    "Model",
    "Property",
    "Expectation",
    "Checker",
    "CheckerBuilder",
    "Path",
    "NondeterminismError",
    "HasDiscoveries",
    "CheckerVisitor",
    "PathRecorder",
    "StateRecorder",
    "JournalReporter",
    "ReportData",
    "ReportDiscovery",
    "Reporter",
    "WriteReporter",
    "fingerprint",
]

__version__ = "0.1.0"
