"""Register operational semantics.

Reference: src/semantics/register.rs.  Ops are ``WriteOp(v)`` / ``ReadOp``;
returns are ``WriteOk`` / ``ReadOk(v)``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List

from .spec import SequentialSpec


@dataclass(frozen=True)
class WriteOp:
    value: Any


@dataclass(frozen=True)
class ReadOp:
    pass


READ = ReadOp()


@dataclass(frozen=True)
class WriteOk:
    pass


WRITE_OK = WriteOk()


@dataclass(frozen=True)
class ReadOk:
    value: Any


class Register(SequentialSpec):
    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value

    def invoke(self, op):
        if isinstance(op, WriteOp):
            self.value = op.value
            return WRITE_OK
        if isinstance(op, ReadOp):
            return ReadOk(self.value)
        raise TypeError(f"unknown op {op!r}")

    def is_valid_step(self, op, ret) -> bool:
        if isinstance(op, WriteOp) and isinstance(ret, WriteOk):
            self.value = op.value
            return True
        if isinstance(op, ReadOp) and isinstance(ret, ReadOk):
            return self.value == ret.value
        return False

    def clone(self) -> "Register":
        return Register(self.value)

    def __eq__(self, other) -> bool:
        return isinstance(other, Register) and self.value == other.value

    def __hash__(self) -> int:
        return hash(("Register", self.value))

    def __repr__(self) -> str:
        return f"Register({self.value!r})"

    def __canon_words__(self, out: List[int]) -> None:
        from ..ops.fingerprint import canon_words

        canon_words(("Register", self.value), out)
