"""Stack (Vec) operational semantics.

Reference: src/semantics/vec.rs — Push/Pop/Len with PushOk/PopOk/LenOk.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Optional, Tuple

from .spec import SequentialSpec


@dataclass(frozen=True)
class Push:
    value: Any


@dataclass(frozen=True)
class Pop:
    pass


@dataclass(frozen=True)
class Len:
    pass


@dataclass(frozen=True)
class PushOk:
    pass


@dataclass(frozen=True)
class PopOk:
    value: Optional[Any]


@dataclass(frozen=True)
class LenOk:
    len: int


class VecSpec(SequentialSpec):
    __slots__ = ("items",)

    def __init__(self, items: Tuple[Any, ...] = ()):
        self.items = tuple(items)

    def invoke(self, op):
        if isinstance(op, Push):
            self.items = self.items + (op.value,)
            return PushOk()
        if isinstance(op, Pop):
            if self.items:
                v, self.items = self.items[-1], self.items[:-1]
                return PopOk(v)
            return PopOk(None)
        if isinstance(op, Len):
            return LenOk(len(self.items))
        raise TypeError(f"unknown op {op!r}")

    def clone(self) -> "VecSpec":
        return VecSpec(self.items)

    def __eq__(self, other) -> bool:
        return isinstance(other, VecSpec) and self.items == other.items

    def __hash__(self) -> int:
        return hash(("VecSpec", self.items))

    def __repr__(self) -> str:
        return f"VecSpec({list(self.items)!r})"

    def __canon_words__(self, out: List[int]) -> None:
        from ..ops.fingerprint import canon_words

        canon_words(("VecSpec", self.items), out)
