"""Consistency semantics: sequential specs and concurrent-history testers.

Reference: src/semantics.rs and submodules.
"""

from .spec import SequentialSpec
from .register import Register, ReadOp, WriteOp, ReadOk, WriteOk, READ, WRITE_OK
from .write_once_register import WORegister, WriteFail
from .vec import VecSpec, Push, Pop, Len, PushOk, PopOk, LenOk
from .consistency import (
    ConsistencyTester,
    LinearizabilityTester,
    SequentialConsistencyTester,
)

__all__ = [
    "SequentialSpec", "Register", "ReadOp", "WriteOp", "ReadOk", "WriteOk",
    "READ", "WRITE_OK", "WORegister", "WriteFail", "VecSpec", "Push", "Pop",
    "Len", "PushOk", "PopOk", "LenOk", "ConsistencyTester",
    "LinearizabilityTester", "SequentialConsistencyTester",
]
