"""Sequential reference-object specifications.

Reference: the ``SequentialSpec`` trait, src/semantics.rs:73-98.
"""

from __future__ import annotations

from typing import Any, Iterable, Tuple


class SequentialSpec:
    """A sequential "reference object" against which concurrent histories are
    validated.  Implementations are small mutable objects with ``clone()``."""

    def invoke(self, op: Any) -> Any:
        """Apply ``op``, mutating self; returns the Ret value."""
        raise NotImplementedError

    def is_valid_step(self, op: Any, ret: Any) -> bool:
        """Whether invoking ``op`` may return ``ret`` (applying it if so)."""
        return self.invoke(op) == ret

    def is_valid_history(self, ops: Iterable[Tuple[Any, Any]]) -> bool:
        return all(self.is_valid_step(op, ret) for (op, ret) in ops)

    def clone(self) -> "SequentialSpec":
        raise NotImplementedError
