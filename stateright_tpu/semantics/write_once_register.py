"""Write-once register operational semantics.

Reference: src/semantics/write_once_register.rs.  Shares ``WriteOp`` /
``ReadOp`` / ``WriteOk`` / ``ReadOk`` with the plain register; adds
``WriteFail`` for a write after a different value was already written
(writing an *equal* value still succeeds).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List

from .register import ReadOk, ReadOp, WriteOk, WriteOp, WRITE_OK
from .spec import SequentialSpec


@dataclass(frozen=True)
class WriteFail:
    pass


WRITE_FAIL = WriteFail()


class WORegister(SequentialSpec):
    __slots__ = ("value",)  # None = unwritten

    def __init__(self, value: Any = None):
        self.value = value

    def invoke(self, op):
        if isinstance(op, WriteOp):
            if self.value is None or self.value == op.value:
                self.value = op.value
                return WRITE_OK
            return WRITE_FAIL
        if isinstance(op, ReadOp):
            return ReadOk(self.value)
        raise TypeError(f"unknown op {op!r}")

    def is_valid_step(self, op, ret) -> bool:
        if isinstance(op, WriteOp) and isinstance(ret, WriteOk):
            if self.value is None:
                self.value = op.value
                return True
            return self.value == op.value
        if isinstance(op, WriteOp) and isinstance(ret, WriteFail):
            return self.value is not None and self.value != op.value
        if isinstance(op, ReadOp) and isinstance(ret, ReadOk):
            return self.value == ret.value
        return False

    def clone(self) -> "WORegister":
        return WORegister(self.value)

    def __eq__(self, other) -> bool:
        return isinstance(other, WORegister) and self.value == other.value

    def __hash__(self) -> int:
        return hash(("WORegister", self.value))

    def __repr__(self) -> str:
        return f"WORegister({self.value!r})"

    def __canon_words__(self, out: List[int]) -> None:
        from ..ops.fingerprint import canon_words

        canon_words(("WORegister", self.value), out)
