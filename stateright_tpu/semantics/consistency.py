"""Concurrent-history consistency testers.

Reference: src/semantics/consistency_tester.rs,
src/semantics/linearizability.rs, src/semantics/sequential_consistency.rs.

Both testers record per-thread operation histories (invocations and
returns) and decide consistency by an exponential backtracking search for a
valid interleaving against a ``SequentialSpec``.  The linearizability
tester additionally snapshots, at each invocation, the index of the last
completed operation of every *other* thread; an interleaving that schedules
an operation before all such prerequisites violates real-time order and is
pruned (src/semantics/linearizability.rs:102-129, 221-234).

These testers are *model state*: they live in an ``ActorModel`` history and
run inside ``always`` property closures for every evaluated state, so they
are hashable, comparable, and cheaply clonable.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .spec import SequentialSpec


class ConsistencyTester:
    """Reference: src/semantics/consistency_tester.rs:15-43."""

    def on_invoke(self, thread_id, op) -> "ConsistencyTester":
        raise NotImplementedError

    def on_return(self, thread_id, ret) -> "ConsistencyTester":
        raise NotImplementedError

    def is_consistent(self) -> bool:
        raise NotImplementedError

    def on_invret(self, thread_id, op, ret) -> "ConsistencyTester":
        self.on_invoke(thread_id, op)
        self.on_return(thread_id, ret)
        return self


class _TesterBase(ConsistencyTester):
    __slots__ = ("init_ref_obj", "history_by_thread", "in_flight_by_thread", "is_valid_history")

    def __init__(self, init_ref_obj: SequentialSpec):
        self.init_ref_obj = init_ref_obj
        # thread -> tuple of completed entries (tester-specific entry shape)
        self.history_by_thread: Dict[Any, Tuple] = {}
        # thread -> in-flight entry
        self.in_flight_by_thread: Dict[Any, Any] = {}
        self.is_valid_history = True

    def clone(self):
        c = type(self)(self.init_ref_obj)
        c.history_by_thread = dict(self.history_by_thread)
        c.in_flight_by_thread = dict(self.in_flight_by_thread)
        c.is_valid_history = self.is_valid_history
        return c

    def __len__(self) -> int:
        return len(self.in_flight_by_thread) + sum(
            len(h) for h in self.history_by_thread.values()
        )

    def completed_count(self) -> int:
        """Operations with both invocation and return recorded — the live
        auditor's progress signal (runtime/chaos.py)."""
        return sum(len(h) for h in self.history_by_thread.values())

    def pending_count(self) -> int:
        """Invocations still in flight (no return recorded yet).  A
        serialization may schedule these or leave them out, so a live run
        stopped mid-operation still audits cleanly."""
        return len(self.in_flight_by_thread)

    def _key(self):
        return (
            type(self).__name__,
            self.init_ref_obj,
            tuple(sorted(self.history_by_thread.items())),
            tuple(sorted(self.in_flight_by_thread.items())),
            self.is_valid_history,
        )

    def __eq__(self, other) -> bool:
        return type(self) is type(other) and self._key() == other._key()

    def __hash__(self) -> int:
        return hash(self._key())

    def __canon_words__(self, out: List[int]) -> None:
        from ..ops.fingerprint import canon_words

        canon_words(
            (
                type(self).__name__,
                self.init_ref_obj,
                tuple(sorted(self.history_by_thread.items())),
                tuple(sorted(self.in_flight_by_thread.items())),
                self.is_valid_history,
            ),
            out,
        )

    def __repr__(self) -> str:
        return (
            f"{type(self).__name__}(history={self.history_by_thread!r}, "
            f"in_flight={self.in_flight_by_thread!r}, valid={self.is_valid_history})"
        )

    def is_consistent(self) -> bool:
        return self.serialized_history() is not None

    def serialized_history(self):
        raise NotImplementedError

    def rewrite(self, plan):
        """Renumber actor ids (thread ids, and any ids nested in entries) for
        symmetry reduction — the analog of the reference's ``Rewrite<Id>``
        bound on ActorModel histories (src/actor/model_state.rs:176-184)."""
        from ..core.symmetry import rewrite_value

        c = type(self)(self.init_ref_obj.clone())
        c.history_by_thread = {
            rewrite_value(tid, plan): rewrite_value(h, plan)
            for tid, h in self.history_by_thread.items()
        }
        c.in_flight_by_thread = {
            rewrite_value(tid, plan): rewrite_value(e, plan)
            for tid, e in self.in_flight_by_thread.items()
        }
        c.is_valid_history = self.is_valid_history
        return c


class SequentialConsistencyTester(_TesterBase):
    """Reference: src/semantics/sequential_consistency.rs.

    History entries are ``(op, ret)``; in-flight entries are ``op``.
    """

    def on_invoke(self, thread_id, op):
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id in self.in_flight_by_thread:
            self.is_valid_history = False
            raise ValueError(
                f"Thread already has an operation in flight. thread_id={thread_id!r}"
            )
        self.in_flight_by_thread[thread_id] = op
        self.history_by_thread.setdefault(thread_id, ())
        return self

    def on_return(self, thread_id, ret):
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id not in self.in_flight_by_thread:
            self.is_valid_history = False
            raise ValueError(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}"
            )
        op = self.in_flight_by_thread.pop(thread_id)
        self.history_by_thread[thread_id] = self.history_by_thread.get(
            thread_id, ()
        ) + ((op, ret),)
        return self

    def serialized_history(self) -> Optional[List[Tuple[Any, Any]]]:
        if not self.is_valid_history:
            return None
        remaining = {t: deque(h) for t, h in self.history_by_thread.items()}
        return _serialize_sc(
            [], self.init_ref_obj, remaining, dict(self.in_flight_by_thread)
        )


def _serialize_sc(valid, ref_obj, remaining, in_flight):
    if all(not h for h in remaining.values()):
        return valid
    for tid in sorted(remaining):
        h = remaining[tid]
        if not h:
            if tid not in in_flight:
                continue
            op = in_flight[tid]
            obj2 = ref_obj.clone()
            ret = obj2.invoke(op)
            nif = {k: v for k, v in in_flight.items() if k != tid}
            result = _serialize_sc(valid + [(op, ret)], obj2, remaining, nif)
        else:
            op, ret = h[0]
            obj2 = ref_obj.clone()
            if not obj2.is_valid_step(op, ret):
                continue
            nrem = dict(remaining)
            nh = deque(h)
            nh.popleft()
            nrem[tid] = nh
            result = _serialize_sc(valid + [(op, ret)], obj2, nrem, in_flight)
        if result is not None:
            return result
    return None


class LinearizabilityTester(_TesterBase):
    """Reference: src/semantics/linearizability.rs.

    History entries are ``(last_completed, op, ret)`` and in-flight entries
    are ``(last_completed, op)``, where ``last_completed`` maps every other
    thread (with completed ops at invocation time) to its last completed op
    index — the data that enforces real-time order.
    """

    def on_invoke(self, thread_id, op):
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id in self.in_flight_by_thread:
            self.is_valid_history = False
            raise ValueError(
                f"Thread already has an operation in flight. thread_id={thread_id!r}"
            )
        last_completed = tuple(
            sorted(
                (tid, len(h) - 1)
                for tid, h in self.history_by_thread.items()
                if tid != thread_id and h
            )
        )
        self.in_flight_by_thread[thread_id] = (last_completed, op)
        self.history_by_thread.setdefault(thread_id, ())
        return self

    def on_return(self, thread_id, ret):
        if not self.is_valid_history:
            raise ValueError("Earlier history was invalid.")
        if thread_id not in self.in_flight_by_thread:
            self.is_valid_history = False
            raise ValueError(
                f"There is no in-flight invocation for this thread ID. "
                f"thread_id={thread_id!r}, unexpected_return={ret!r}"
            )
        last_completed, op = self.in_flight_by_thread.pop(thread_id)
        self.history_by_thread[thread_id] = self.history_by_thread.get(
            thread_id, ()
        ) + ((last_completed, op, ret),)
        return self

    def serialized_history(self) -> Optional[List[Tuple[Any, Any]]]:
        if not self.is_valid_history:
            return None
        remaining = {
            t: deque(enumerate(h)) for t, h in self.history_by_thread.items()
        }
        return _serialize_lin(
            [], self.init_ref_obj, remaining, dict(self.in_flight_by_thread)
        )


def _rt_violation(last_completed, remaining) -> bool:
    """An op may not be scheduled while a prerequisite (an op completed
    before this op's invocation) is still unconsumed."""
    for peer_id, min_peer_time in last_completed:
        ops = remaining.get(peer_id)
        if ops:
            next_peer_time = ops[0][0]
            if next_peer_time <= min_peer_time:
                return True
    return False


def _serialize_lin(valid, ref_obj, remaining, in_flight):
    if all(not h for h in remaining.values()):
        return valid
    for tid in sorted(remaining):
        h = remaining[tid]
        if not h:
            # Case 1: no completed ops left; maybe in-flight (optional).
            if tid not in in_flight:
                continue
            last_completed, op = in_flight[tid]
            if _rt_violation(last_completed, remaining):
                continue
            obj2 = ref_obj.clone()
            ret = obj2.invoke(op)
            nif = {k: v for k, v in in_flight.items() if k != tid}
            result = _serialize_lin(valid + [(op, ret)], obj2, remaining, nif)
        else:
            # Case 2: next completed op for this thread.
            _idx, (last_completed, op, ret) = h[0]
            nrem = dict(remaining)
            nh = deque(h)
            nh.popleft()
            nrem[tid] = nh
            if _rt_violation(last_completed, nrem):
                continue
            obj2 = ref_obj.clone()
            if not obj2.is_valid_step(op, ret):
                continue
            result = _serialize_lin(valid + [(op, ret)], obj2, nrem, in_flight)
        if result is not None:
            return result
    return None
