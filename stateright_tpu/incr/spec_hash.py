"""Per-component spec hashing for the incremental verification store.

A verification request is identified by FIVE separately hashed
components, so the store can tell not just "same or different" but
*which part* changed — the classification the re-check modes hang off
(docs/INCREMENTAL.md):

- ``codec``      — the transition machinery's CODE: packed layout widths
                   and the bytecode digests of ``encode`` / ``step`` (and
                   the two-phase / boundary hooks).  Two specs with equal
                   codec hashes run the same kernels over the same row
                   layout.
- ``constants``  — the model's DATA (``CompiledModel.spec_constants``)
                   plus the packed-init digest: what the code closes
                   over.  Separated from the codec so "one constant
                   bumped" is visible as exactly one changed component.
- ``properties`` — property names, expectations, and the bytecode
                   digests of both the host conditions and the device
                   ``property_conds`` kernel.
- ``symmetry``   — off, or the canon spec's digest.
- ``bounds``     — exploration bounds that change what a "complete" run
                   means: target depth/state count and the finish_when
                   policy.

An ``engine`` hash (engine name + kwargs) is recorded as evidence but
deliberately EXCLUDED from every matching decision: the engines pin
discovery-set invariance across geometry (capacity, frontier, rungs,
mesh size — tests/test_sort_rung.py, test_tpu_sharded.py, test_tiered.py),
so a geometry-only change still hits the verdict cache.

Determinism is a hard requirement (the hashes persist across processes
and must survive a fresh ``PYTHONHASHSEED``): everything routes through
sha256 over canonically ordered bytes — no ``hash()``, no dict-order
dependence (pinned by the subprocess test in tests/test_incr.py).
Bytecode digests are interpreter-build-scoped, so the store version
string folds in ``sys.implementation.cache_tag``: a store written by
one Python reads as cold (never as wrong) under another.
"""

from __future__ import annotations

import hashlib
import json
import sys
from typing import Optional

from ..core.model import Expectation

# Bump when the hash recipe changes: old entries then classify as cold
# (a different spec_key), never as a false hit.
HASH_VERSION = "incr-spec-v1"


def _stable_repr(v) -> str:
    """A PYTHONHASHSEED-independent rendering of one constant-ish
    value.  Sets/frozensets iterate in hash order, so a plain ``repr``
    of a set literal inside a property condition would digest
    differently per process — they fold sorted.  Opaque objects (e.g. a
    model instance a lambda closed over) fold as their TYPE only: their
    DATA is the constants component's job (``spec_constants``), and an
    identity repr would leak a memory address into the digest."""
    if isinstance(v, (frozenset, set)):
        return "{" + ",".join(sorted(_stable_repr(x) for x in v)) + "}"
    if isinstance(v, tuple):
        return "(" + ",".join(_stable_repr(x) for x in v) + ")"
    if isinstance(v, dict):
        return "{" + ",".join(sorted(
            f"{_stable_repr(k)}:{_stable_repr(x)}" for k, x in v.items()
        )) + "}"
    if v is None or isinstance(
        v, (int, float, bool, str, bytes, complex)
    ):
        return repr(v)
    if hasattr(v, "co_code"):  # nested code object (a nested lambda)
        h = hashlib.sha256()
        _code_digest(h, v)
        return "code:" + h.hexdigest()
    return f"<{type(v).__qualname__}>"


def _code_digest(h, code) -> None:
    """Fold one code object into ``h``: opcode stream, names, and
    consts (recursing into nested code objects — property lambdas close
    over helpers)."""
    h.update(code.co_code)
    h.update(repr(code.co_names).encode())
    h.update(repr(code.co_varnames).encode())
    for const in code.co_consts:
        h.update(_stable_repr(const).encode())


def code_digest(fn) -> str:
    """Stable digest of a function's BEHAVIORAL identity: bytecode,
    referenced names, default arguments, captured closure values, and —
    one call-graph hop at a time, transitively — the code of
    MODULE-LEVEL functions (and the values of module-level primitives)
    it references by name.  A subclass that inherits a method digests
    identically to its parent; an edited source line, default,
    captured threshold, or shared module-level helper body — the
    classic one-line model edits — all change the digest.

    Known coarse spots, by design: helpers reached through ATTRIBUTE
    lookup (``self._helper``, ``module.fn``) are not resolvable from a
    name list and do not fold — the CompiledModel hooks the spec hash
    cares about are each digested explicitly (spec components), and
    model DATA lives in the constants component; closure cells and
    globals holding opaque objects fold as their type only
    (:func:`_stable_repr`)."""
    h = hashlib.sha256()
    _fold_function(h, fn, set())
    return h.hexdigest()


def _fold_function(h, fn, seen) -> None:
    fn = getattr(fn, "__func__", fn)
    code = getattr(fn, "__code__", None)
    if code is None:
        # Builtins / partials with no code object: fall back to the
        # qualified name (stable, just coarser).
        h.update(repr(getattr(fn, "__qualname__", repr(fn))).encode())
        return
    if id(code) in seen:  # recursion/cycles among helpers
        return
    seen.add(id(code))
    _code_digest(h, code)
    h.update(_stable_repr(getattr(fn, "__defaults__", None)
                          or ()).encode())
    h.update(_stable_repr(getattr(fn, "__kwdefaults__", None)
                          or {}).encode())
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            contents = cell.cell_contents
        except ValueError:  # empty cell
            h.update(b"<empty-cell>")
            continue
        if hasattr(contents, "__code__"):
            _fold_function(h, contents, seen)
        else:
            h.update(_stable_repr(contents).encode())
    # Referenced globals: plain functions fold their own code (so an
    # edit to a shared module-level helper changes every caller's
    # digest), primitive module constants fold their value.  Classes,
    # modules, and other opaque globals are skipped — the names
    # themselves already rode in via co_names.
    g = getattr(fn, "__globals__", None)
    if g is not None:
        for name in sorted(set(code.co_names)):
            if name not in g:
                continue
            v = g[name]
            if callable(v) and hasattr(v, "__code__"):
                _fold_function(h, v, seen)
            elif v is None or isinstance(
                v, (int, float, bool, str, bytes, complex, tuple,
                    frozenset)
            ):
                h.update(_stable_repr(v).encode())


def _hexdigest(*parts: str) -> str:
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode())
        h.update(b"\x00")
    return h.hexdigest()


def _method_digests(cm, names) -> str:
    out = []
    for name in names:
        fn = getattr(type(cm), name, None)
        out.append(f"{name}={'-' if fn is None else code_digest(fn)}")
    return ";".join(out)


class SpecFingerprint:
    """The hashed identity of one verification request.

    Attributes:
        components: component name -> hex digest.
        spec_key: content address of the FULL spec (engine excluded).
        family_key: codec+symmetry+bounds — the grouping under which
            property-only and constant-widening relatives are sought.
        constants: the raw ``spec_constants()`` dict (None when the
            model declares no stable constants — every reuse path then
            refuses loudly).
        property_names / expectations: the model's property list, in
            order (the device property vector's order).
        has_eventually: whether any property is EVENTUALLY — the re-eval
            and seeding modes refuse those (their verdicts depend on
            path structure, not per-state predicates alone).
        snapshot_key: the engine-snapshot compatibility key a stored
            run must carry for its snapshot to be seedable here.
    """

    def __init__(self, model, compiled=None, symmetry: bool = False,
                 target_max_depth: Optional[int] = None,
                 target_state_count: Optional[int] = None,
                 finish_when=None, engine: str = "tpu",
                 engine_kwargs: Optional[dict] = None):
        from ..parallel.compiled import compiled_model_for
        from ..parallel.wavefront import snapshot_engine_key

        cm = compiled or compiled_model_for(model)
        self.model = model
        self.compiled = cm
        self.model_label = type(cm).__qualname__
        props = model.properties()
        self.property_names = [p.name for p in props]
        self.expectations = [p.expectation.name for p in props]
        self.has_eventually = any(
            p.expectation is Expectation.EVENTUALLY for p in props
        )
        self.symmetry = bool(symmetry)
        self.engine = engine

        codec = _hexdigest(
            HASH_VERSION,
            sys.implementation.cache_tag or "py",
            str(cm.state_width),
            str(cm.max_actions),
            str(cm.fp_words or 0),
            str(bool(getattr(cm, "step_flags", False))),
            _method_digests(
                cm,
                ("encode", "step", "step_valid", "step_lane", "boundary"),
            ),
        )

        self.constants = cm.spec_constants()
        import numpy as np

        init_digest = hashlib.sha256(
            np.ascontiguousarray(cm.init_packed()).tobytes()
        ).hexdigest()
        if self.constants is None:
            constants = _hexdigest("unstable", init_digest)
        else:
            constants = _hexdigest(
                json.dumps(
                    {str(k): str(v) for k, v in self.constants.items()},
                    sort_keys=True,
                ),
                init_digest,
            )

        properties = _hexdigest(
            json.dumps(
                [
                    {"name": p.name, "expectation": p.expectation.name,
                     "condition": code_digest(p.condition)}
                    for p in props
                ]
            ),
            _method_digests(cm, ("property_conds",)),
        )

        if not symmetry:
            sym = _hexdigest("off")
        else:
            spec = cm.canon_spec() if hasattr(cm, "canon_spec") else None
            sym = _hexdigest(
                "on",
                repr(spec),
                _method_digests(cm, ("canon_rows",)),
            )

        fw_kind = getattr(finish_when, "_kind", "all")
        fw_names = sorted(getattr(finish_when, "_names", ()) or ())
        bounds = _hexdigest(
            str(target_max_depth or 0),
            str(target_state_count or 0),
            fw_kind,
            json.dumps(fw_names),
        )
        self.target_max_depth = target_max_depth
        self.target_state_count = target_state_count

        eng = _hexdigest(
            engine,
            json.dumps(
                {str(k): repr(v) for k, v in (engine_kwargs or {}).items()},
                sort_keys=True,
            ),
        )

        self.components = {
            "codec": codec,
            "constants": constants,
            "properties": properties,
            "symmetry": sym,
            "bounds": bounds,
            "engine": eng,
        }
        self.spec_key = _hexdigest(
            codec, constants, properties, sym, bounds
        )
        self.family_key = _hexdigest(codec, sym, bounds)
        self.snapshot_key = snapshot_engine_key(cm, props, symmetry)

    @classmethod
    def of_builder(cls, builder, compiled=None, engine: str = "tpu",
                   engine_kwargs: Optional[dict] = None
                   ) -> "SpecFingerprint":
        """Fingerprint a configured :class:`~..core.checker.
        CheckerBuilder` — the one construction path the CLI, the serve
        scheduler, and the tests all share, so the hashed bounds can
        never drift from what the spawned engine would actually run."""
        return cls(
            builder.model,
            compiled=compiled,
            symmetry=builder._symmetry is not None,
            target_max_depth=builder._target_max_depth,
            target_state_count=builder._target_state_count,
            finish_when=builder._finish_when,
            engine=engine,
            engine_kwargs=engine_kwargs,
        )
