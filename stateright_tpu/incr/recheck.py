"""The four re-check modes behind one call: ``incremental_check``.

Given a configured :class:`~..core.checker.CheckerBuilder` and a store
directory, classify the spec delta against the store and run the
cheapest sound path (incr/store.py documents the modes and the
soundness gates).  Every decision journals an ``incr_*`` event
(``incr_classified`` / ``incr_verdict_hit`` / ``incr_property_recheck``
/ ``incr_seeded`` / ``incr_stored`` / ``incr_store_skipped`` — rendered
by the ``watch`` verb and obs/report.py), so the journal answers "why
was this re-check cheap (or not)" after the fact.

The verdict-cache and property-re-eval paths return lightweight
:class:`~..core.checker.Checker` implementations over the stored data —
the full reporting surface (counts, discoveries with re-executed
counterexample paths, assert helpers, VIOLATION_RC classification)
works unchanged, with zero device dispatches for the verdict cache and
zero exploration waves for the property re-eval.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..core.checker import Checker
from ..core.model import Expectation
from ..core.path import Path
from .spec_hash import SpecFingerprint
from .store import (
    COLD, CONSTANT_WIDENING, IDENTICAL, PROPERTY_ONLY, StoreEntry,
    VerificationStore,
)

NO_SLOT = 0xFFFFFFFF

# Rows per device dispatch in the property re-eval (power of two; the
# eval is a vmapped predicate over fixed-width rows, so the chunk only
# trades dispatch count against padding waste).
PROPEVAL_CHUNK = 1 << 12


class StoredVerdictChecker(Checker):
    """A completed verdict served from the store — the content-addressed
    verdict cache (ROADMAP #3c).  Counts and per-property verdicts come
    from the verdict record; discovery PATHS re-execute the host model
    along the journaled fingerprint chains on first access (O(depth)
    host work — no device exists in this path at all)."""

    def __init__(self, model, entry: StoreEntry,
                 recheck_mode: str = IDENTICAL,
                 discoveries: Optional[Dict[str, Path]] = None):
        super().__init__(model)
        self._entry = entry
        self._summary = entry.summary
        self._recheck_mode = recheck_mode
        self._paths = discoveries
        self._lock = threading.Lock()

    def state_count(self) -> int:
        return int(self._summary.get("state_count", 0))

    def unique_state_count(self) -> int:
        return int(self._summary.get("unique_state_count", 0))

    def max_depth(self) -> int:
        return int(self._summary.get("max_depth", 0))

    def discoveries(self) -> Dict[str, Path]:
        with self._lock:
            if self._paths is None:
                self._paths = {
                    name: Path.from_fingerprints(
                        self._model, d["fingerprints"]
                    )
                    for name, d in self._summary.get(
                        "discoveries", {}
                    ).items()
                }
            return dict(self._paths)

    def discovered_fingerprints(self) -> np.ndarray:
        """The stored reachable set (ColdStore sorted runs) — same
        contract as the engines', read off disk instead of the device."""
        return self._entry.fingerprints()

    def is_done(self) -> bool:
        return True

    def join(self) -> "StoredVerdictChecker":
        return self

    def metrics(self) -> dict:
        out = super().metrics()
        out.update(
            engine="incr-verdict-cache",
            recheck_mode=self._recheck_mode,
            store_entry=self._entry.entry_id,
        )
        return out


def _walk_parent_chain(model, cm, rows: np.ndarray, parents: np.ndarray,
                       slot: int):
    """Host-side analog of the engine's device chain walk: BFS
    positions only ever point at earlier positions, so the chain is a
    bounded backward scan over two numpy arrays."""
    chain = []
    s = int(slot)
    while s != NO_SLOT and len(chain) <= parents.shape[0]:
        chain.append(s)
        s = int(parents[s])
    chain.reverse()
    fps = [model.fingerprint(cm.decode(rows[i])) for i in chain]
    return Path.from_fingerprints(model, fps)


def _property_recheck(spec: SpecFingerprint, entry: StoreEntry,
                      journal) -> StoredVerdictChecker:
    """Mode (b): evaluate the NEW property set over the stored row log
    on device — batched ``property_conds`` over fixed-width rows, no
    exploration.  Discovery semantics reproduce the engine's
    first-writer-wins-in-position-order rule exactly: the engines
    evaluate properties at expansion, expand positions in order, and
    take the first triggering lane, so a cold run's discovery slot for
    an ALWAYS/SOMETIMES property is the minimal triggering BFS position
    — which is precisely what the chunked scan below finds."""
    import jax
    import jax.numpy as jnp

    from ..parallel.wave_common import cached_program
    from ..parallel.wavefront import _PROGRAM_CACHE, _PROGRAM_CACHE_MAX

    t0 = time.monotonic()
    cm = spec.compiled
    model = spec.model
    props = model.properties()
    w = cm.state_width
    snap = np.load(entry.snapshot_path, allow_pickle=False)
    tail = int(snap["tail"])
    rows = np.asarray(snap["rows"])[: tail * w].reshape(tail, w)
    parents = np.asarray(snap["parent"])[:tail]

    chunk = PROPEVAL_CHUNK
    key = ("incr-propeval", cm.cache_key(),
           tuple((p.name, p.expectation) for p in props), chunk)

    def build():
        @jax.jit
        def eval_chunk(rows_d):
            return jax.vmap(cm.property_conds)(rows_d)  # [chunk, P]

        return eval_chunk

    eval_chunk = cached_program(
        _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build,
        label="incr.propeval", journal=journal,
        provenance={"model": spec.model_label, "rows": tail,
                    "chunk": chunk},
    )

    pending = {
        i: p for i, p in enumerate(props)
        if p.expectation is not Expectation.EVENTUALLY
    }
    slots: Dict[str, int] = {}
    dispatches = 0
    for off in range(0, tail, chunk):
        if not pending:
            break
        n = min(chunk, tail - off)
        block = rows[off:off + n]
        if n < chunk:
            block = np.concatenate(
                [block, np.zeros((chunk - n, w), np.uint32)]
            )
        conds = np.asarray(eval_chunk(jnp.asarray(block)))
        dispatches += 1
        for i in list(pending):
            p = pending[i]
            col = conds[:n, i]
            hit = ~col if p.expectation is Expectation.ALWAYS else col
            idx = np.flatnonzero(hit)
            if idx.size:
                slots[p.name] = off + int(idx[0])
                del pending[i]

    paths = {
        name: _walk_parent_chain(model, cm, rows, parents, slot)
        for name, slot in slots.items()
    }
    sec = time.monotonic() - t0
    if journal is not None:
        journal.append(
            "incr_property_recheck",
            entry=entry.entry_id,
            rows=tail,
            dispatches=dispatches,
            discoveries=sorted(slots),
            sec=round(sec, 4),
        )
    # The re-check result rides the stored COUNTS (the reachable set —
    # and therefore state/unique/depth — is property-independent for
    # rows-reusable entries, incr/store.py's gate) with the freshly
    # computed discovery paths; every derived verdict-record field
    # (per-property verdicts, violation, fingerprint chains) is built
    # by the ONE summary builder when the entry is stored
    # (store._summarize via record_derived), never hand-rolled here.
    synthetic = StoreEntry(entry.path, dict(entry.record))
    synthetic.record["summary"] = {
        "state_count": entry.summary.get("state_count", 0),
        "unique_state_count": entry.summary.get("unique_state_count", 0),
        "max_depth": entry.summary.get("max_depth", 0),
    }
    return StoredVerdictChecker(
        model, synthetic, recheck_mode=PROPERTY_ONLY, discoveries=paths,
    )


def _seeded_snapshot(entry: StoreEntry, out_path: str) -> int:
    """Mode (c)'s snapshot surgery: rewrite the stored COMPLETED
    snapshot so the whole reachable set becomes level 0 of a resumed
    run — level_start 0, level_end tail, depth 0, discoveries cleared —
    while the row log, parent links, and fingerprint table carry over
    verbatim.  The resumed engine then re-expands every stored state:
    successors inside the old set dedup against the carried table, and
    only the newly-admitted region explores (docs/INCREMENTAL.md states
    the completeness argument).  Returns the seeded state count."""
    snap = np.load(entry.snapshot_path, allow_pickle=False)
    data = {k: snap[k] for k in snap.files}
    tail = int(data["tail"])
    data["level_start"] = np.uint32(0)
    data["level_end"] = np.uint32(tail)
    data["depth"] = np.uint32(0)
    data["disc"] = np.full_like(np.asarray(data["disc"]), NO_SLOT)
    tmp = f"{out_path}.tmp"
    with open(tmp, "wb") as fh:
        np.savez_compressed(fh, **data)
    os.replace(tmp, out_path)
    return tail


def _join_cancellable(checker, cancel, poll_interval: float = 0.05):
    """Join an engine run while honoring a cooperative cancel event
    (the serve scheduler's job.cancel): the engine winds down at its
    next host-side check, the store's completeness gate then refuses
    the partial verdict, and the caller sees ``stop_requested()``."""
    import time as _time

    if cancel is None:
        return checker.join()
    while not checker.is_done():
        if cancel.is_set():
            checker.request_stop()
        _time.sleep(poll_interval)
    return checker.join()


def incremental_check(
    builder,
    store_dir: str,
    engine_kwargs: Optional[dict] = None,
    journal=None,
    reuse: bool = True,
    store_result: bool = True,
    cancel=None,
    on_spawn=None,
) -> Tuple[Checker, dict]:
    """Run one verification request through the store.

    ``builder`` is a configured CheckerBuilder (model, symmetry,
    bounds); ``engine_kwargs`` are the ``spawn_tpu`` knobs a cold or
    seeded run spawns with.  ``reuse=False`` records without reusing
    (the CLI's ``--store-dir`` without ``--incremental``);
    ``store_result=False`` reuses without recording (bench's repeated
    measurement legs).  ``cancel`` (a ``threading.Event``) makes the
    cold/seeded device runs cooperatively cancellable — a fired event
    stops the engine, the partial verdict is refused by the store's
    completeness gate, and the returned checker reports
    ``stop_requested()``.  ``on_spawn`` (a callable taking the checker)
    fires right after a cold/seeded engine spawns — the serve
    scheduler's hook for attaching live vitals to a RUNNING job.
    Returns ``(checker, info)`` where ``info``
    carries ``mode`` / ``reason`` / ``spec_key`` / ``sec`` — the
    ``recheck_mode`` evidence the CLI prints and the serve scheduler
    folds into job results.

    The returned checker is JOINED: cache hits are done by
    construction, and recording a run requires completion anyway.
    """
    from ..runtime.journal import as_journal

    engine_kwargs = dict(engine_kwargs or {})
    # The store owns journal/resume routing; a caller-supplied copy of
    # either would silently fork the evidence trail (or fight the
    # widening path's seeded resume).
    for reserved in ("journal", "resume_from"):
        engine_kwargs.pop(reserved, None)
    journal = as_journal(journal)
    store = VerificationStore(store_dir, journal=journal)
    t0 = time.monotonic()
    spec = SpecFingerprint.of_builder(
        builder, engine="tpu", engine_kwargs=engine_kwargs,
    )
    delta = store.classify(spec) if reuse else None
    mode = delta.mode if delta is not None else COLD
    reason = (
        delta.reason if delta is not None
        else "store recording only (reuse disabled)"
    )
    entry = delta.entry if delta is not None else None
    if journal is not None:
        journal.append(
            "incr_classified",
            mode=mode,
            reason=reason,
            spec_key=spec.spec_key,
            entry=entry.entry_id if entry is not None else None,
            model=spec.model_label,
        )

    info = {
        "mode": mode,
        "reason": reason,
        "spec_key": spec.spec_key,
        "entry": entry.entry_id if entry is not None else None,
    }

    if mode == IDENTICAL:
        checker = StoredVerdictChecker(builder.model, entry)
        if journal is not None:
            journal.append(
                "incr_verdict_hit",
                entry=entry.entry_id,
                violation=entry.summary.get("violation"),
                unique=entry.summary.get("unique_state_count"),
            )
        info["sec"] = round(time.monotonic() - t0, 4)
        return checker, info

    if mode == PROPERTY_ONLY:
        checker = _property_recheck(spec, entry, journal)
        info["sec"] = round(time.monotonic() - t0, 4)
        if store_result:
            store.record_derived(
                spec, checker, entry, engine_kwargs=engine_kwargs,
                elapsed_sec=info["sec"],
            )
        return checker, info

    if mode == CONSTANT_WIDENING:
        seed_path = os.path.join(
            store.store_dir,
            f"seed-{os.getpid()}-{threading.get_ident()}-"
            f"{spec.spec_key[:8]}.npz",
        )
        seeded_states = _seeded_snapshot(entry, seed_path)
        if journal is not None:
            journal.append(
                "incr_seeded",
                entry=entry.entry_id,
                seeded_states=seeded_states,
            )
        try:
            checker = builder.spawn_tpu(
                resume_from=seed_path, journal=journal, **engine_kwargs
            )
            if on_spawn is not None:
                on_spawn(checker)
            _join_cancellable(checker, cancel)
        finally:
            try:
                os.remove(seed_path)
            except OSError:
                pass
        info["seeded_states"] = seeded_states
        info["sec"] = round(time.monotonic() - t0, 4)
        if store_result:
            store.record(
                spec, checker, engine_kwargs=engine_kwargs,
                recheck_mode=CONSTANT_WIDENING,
                elapsed_sec=info["sec"], seeded=True,
            )
        return checker, info

    # Cold: the ordinary engine run, journaled into the store.
    checker = builder.spawn_tpu(journal=journal, **engine_kwargs)
    if on_spawn is not None:
        on_spawn(checker)
    _join_cancellable(checker, cancel)
    info["sec"] = round(time.monotonic() - t0, 4)
    if store_result:
        store.record(
            spec, checker, engine_kwargs=engine_kwargs,
            recheck_mode=COLD, elapsed_sec=info["sec"],
        )
    return checker, info
