"""Incremental re-checking: a persistent, content-addressed verification
store that makes near-identical re-checks cheap (ROADMAP item #5 —
verification as CI, not batch).

The warm-start story used to stop at *identical* resubmission (knob +
program caches); real verification traffic is mostly *near*-identical —
the same model with one property tweaked or one constant widened,
re-checked on every commit.  This package keys a completed run's
reachable set, row log, and verdict to per-component hashes of the model
spec (incr/spec_hash.py), persists them in a directory store built on
the tiered engine's ColdStore sorted-run format (incr/store.py), and on
resubmission classifies the delta and picks the cheapest sound path
(incr/recheck.py):

- identical spec          -> journaled verdict + counterexample paths,
                             O(1), no device dispatch;
- property-only change    -> re-evaluate the new properties over the
                             stored row log on device, no re-exploration;
- constant widening       -> seed the frontier and hash set from the
                             prior reachable set, explore only the new
                             region;
- anything else           -> degrade LOUDLY to a cold run, with the
                             incompatibility reason journaled.

docs/INCREMENTAL.md documents the store layout, the hash components,
the four modes, and the soundness arguments.
"""

from .recheck import incremental_check
from .spec_hash import SpecFingerprint
from .store import VerificationStore

__all__ = [
    "SpecFingerprint",
    "VerificationStore",
    "incremental_check",
]
