"""The persistent verification store: entries, classification, journaling.

Layout (one directory per store, safe to rsync/commit as an artifact)::

    <store_dir>/
      journal.jsonl                 # incr_* events (+ engine events when
                                    #   the CLI routes runs through here)
      index.json                    # entry index: every verdict record
                                    #   sans summary + freshness stats;
                                    #   rebuilt on any mismatch, so
                                    #   classify() never re-parses
                                    #   per-entry records as stores grow
      entries/<spec_key[:24]>/
        verdict.json                # the verdict record (see below)
        snapshot.npz                # engine snapshot: row log + parents +
                                    #   fingerprint table (rows-reusable
                                    #   entries only)
        cold/cold_run_*.npy         # the reachable set as ColdStore
                                    #   sorted uint64 runs (tiered/
                                    #   cold_store.py's format)

The verdict record carries the per-component spec hashes
(incr/spec_hash.py), the raw constants (so ``spec_widens`` can compare
data, not digests), counts, per-property verdicts with counterexample
fingerprint chains, and the ROW-REUSE eligibility flag.

Row-reuse eligibility is the store's soundness gate: the property-only
and constant-widening modes treat the stored row log as *the complete
reachable set, independent of the property set* — which holds exactly
when (a) the run drained its frontier with no stop/timeout/target
truncation and no depth bound, and (b) at least one property ended
UNDISCOVERED.  (b) is the exhaustiveness witness: the engines stop
expanding a state once every property has a discovery and the state
contributes none (wave_common.wave_eval's awaiting gate, mirroring
src/checker/bfs.rs:231-281), so a run whose every property discovered
may have pruned — but a property undiscovered at the end was
undiscovered at every wave start, kept every state awaited, and forced
the full reachable set out.  Entries failing the gate still serve the
O(1) verdict cache; the reuse modes degrade loudly past them.

Writes are crash-safe by ordering: ``snapshot.npz`` and the cold runs
land first, ``verdict.json`` last via atomic write + rename — an entry
without a verdict record does not exist to readers.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, NamedTuple, Optional

import numpy as np

from ..tiered.cold_store import ColdStore
from .spec_hash import HASH_VERSION, SpecFingerprint

STORE_FORMAT = 1
# The entry index (ROADMAP #5 remainder): one JSON file beside the
# entries holding every verdict record MINUS its summary block, plus a
# per-entry [mtime_ns, size] freshness token.  classify() scales with
# this file instead of re-parsing every verdict.json as stores grow;
# any mismatch with the live directory (names or stats) rebuilds it.
INDEX_FILE = "index.json"

# Classification modes, in preference order (docs/INCREMENTAL.md).
IDENTICAL = "identical"
PROPERTY_ONLY = "property_only"
CONSTANT_WIDENING = "constant_widening"
COLD = "cold"


# Serializes entry writes within this process (the serve scheduler may
# run store jobs on several worker threads; the remove-artifacts/
# rewrite sequence of two writers hitting one spec's entry dir must not
# interleave).  ACROSS processes the store follows the knob cache's
# contract: last whole-entry writer wins — every entry is independently
# re-derivable, and the verdict-last write order keeps a torn loser
# invisible rather than wrong.
_WRITE_LOCK = threading.Lock()


class Delta(NamedTuple):
    """One classification decision: the chosen mode, the donor entry
    (None for cold), and the human-readable reason journaled with it."""

    mode: str
    entry: Optional["StoreEntry"]
    reason: str


class StoreEntry:
    """One persisted run: the parsed verdict record + file handles.

    Entries served from the store's ``index.json`` carry the record
    WITHOUT its (large) ``summary`` block; ``loader`` lazily fetches
    the full ``verdict.json`` on first ``summary`` access, so the
    classification family scan never parses per-entry records while
    the one chosen donor still reads exactly one file."""

    def __init__(self, path: str, record: dict, loader=None):
        self.path = path  # entry directory
        self.record = record
        self._loader = loader  # lazy full-record fetch (index-backed)

    @property
    def entry_id(self) -> str:
        return os.path.basename(self.path)

    @property
    def components(self) -> dict:
        return self.record.get("components", {})

    @property
    def rows_reusable(self) -> bool:
        return bool(self.record.get("rows_reusable"))

    @property
    def snapshot_path(self) -> str:
        return os.path.join(self.path, "snapshot.npz")

    @property
    def summary(self) -> dict:
        if "summary" not in self.record and self._loader is not None:
            full = self._loader(self.path)
            self._loader = None
            if full is not None:
                self.record = full
        return self.record.get("summary", {})

    def fingerprints(self) -> np.ndarray:
        """The stored reachable set, sorted uint64 — read back through
        the ColdStore run files, no device involved."""
        cold = ColdStore.open(os.path.join(self.path, "cold"))
        if not cold.runs:
            return np.zeros((0,), np.uint64)
        out = np.sort(np.concatenate([np.asarray(r) for r in cold.runs]))
        cold.close()
        return out


def _atomic_write_json(path: str, data: dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _summarize(checker, model) -> dict:
    """Counts + per-property verdicts + discovery fingerprint chains —
    everything a cache hit needs to reconstruct the report and the
    counterexample paths (Path.from_fingerprints re-executes the host
    model over the chain; O(depth) host work, no device).  The
    verdict/violation rows come from the shared
    core/checker.property_verdicts, so a stored record and a serve job
    result can never disagree about the same run; only the
    fingerprint-chain encoding is local."""
    from ..core.checker import property_verdicts

    discoveries = checker.discoveries()
    props, violation = property_verdicts(checker)
    disc_out = {}
    for name, path in discoveries.items():
        disc_out[name] = {
            "classification": checker.discovery_classification(name),
            "fingerprints": [
                int(model.fingerprint(s)) for s in path.into_states()
            ],
        }
    return {
        "state_count": checker.state_count(),
        "unique_state_count": checker.unique_state_count(),
        "max_depth": checker.max_depth(),
        "properties": props,
        "discoveries": disc_out,
        "violation": violation,
    }


class VerificationStore:
    """Directory-backed store of completed verification runs."""

    def __init__(self, store_dir: str, journal=None):
        from ..runtime.journal import as_journal

        self.store_dir = os.path.abspath(store_dir)
        self.entries_dir = os.path.join(self.store_dir, "entries")
        os.makedirs(self.entries_dir, exist_ok=True)
        self.journal = as_journal(journal)
        # Per-entry verdict.json parses this instance performed — the
        # observable evidence that classification scales with the
        # INDEX, not the store (pinned in tests/test_incr.py): on an
        # index hit, classify() parses zero per-entry records; only
        # the chosen donor's lazy summary load (and the exact-match
        # lookup) read one file each.
        self.verdict_reads = 0

    # -- read surface ----------------------------------------------------------

    def _read_verdict(self, entry_dir: str) -> Optional[dict]:
        """Parse one entry's verdict.json (None on torn/missing —
        invisible by design); the ONE place per-entry records are read,
        so ``verdict_reads`` counts every such parse."""
        try:
            with open(
                os.path.join(entry_dir, "verdict.json"),
                "r", encoding="utf-8",
            ) as fh:
                record = json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None
        self.verdict_reads += 1
        return record

    def _index_path(self) -> str:
        return os.path.join(self.store_dir, INDEX_FILE)

    def _verdict_stat(self, entry_dir: str):
        """Cheap freshness token for one entry's verdict.json: [mtime_ns,
        size] (None when absent) — an os.stat, never a parse."""
        try:
            st = os.stat(os.path.join(entry_dir, "verdict.json"))
            return [st.st_mtime_ns, st.st_size]
        except OSError:
            return None

    def _load_index(self) -> dict:
        """The entry index ``{entry_id: {"record": slim, "stat": ...}}``
        (``index.json``; ``record`` is the verdict record WITHOUT its
        ``summary`` block, None for torn entries), validated against
        the live directory — name set plus per-entry verdict.json
        stats, all via listdir/os.stat with zero JSON parses — and
        REBUILT on any mismatch (missing/stale/foreign-writer index).
        This is what keeps :meth:`classify`'s family scan O(index)
        instead of O(store) as stores grow (ROADMAP #5 remainder)."""
        names = sorted(
            n for n in os.listdir(self.entries_dir)
            if os.path.isdir(os.path.join(self.entries_dir, n))
        )
        try:
            with open(self._index_path(), "r", encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, json.JSONDecodeError):
            data = None
        if (
            isinstance(data, dict)
            and data.get("format") == STORE_FORMAT
            and data.get("hash_version") == HASH_VERSION
            and isinstance(data.get("entries"), dict)
        ):
            ent = data["entries"]
            if sorted(ent) == names and all(
                isinstance(v, dict)
                and v.get("stat") == self._verdict_stat(
                    os.path.join(self.entries_dir, n)
                )
                for n, v in ent.items()
            ):
                return ent
        return self._rebuild_index(names)

    def _rebuild_index(self, names: List[str]) -> dict:
        """Scan every verdict.json once and persist the index (atomic
        write + rename, like every store artifact).  Torn entries are
        indexed with ``record: None`` so their presence alone does not
        force a rebuild on every read."""
        ent = {}
        for name in names:
            path = os.path.join(self.entries_dir, name)
            record = self._read_verdict(path)
            slim = (
                None if record is None
                else {k: v for k, v in record.items() if k != "summary"}
            )
            ent[name] = {"record": slim, "stat": self._verdict_stat(path)}
        _atomic_write_json(self._index_path(), {
            "format": STORE_FORMAT,
            "hash_version": HASH_VERSION,
            "entries": ent,
        })
        return ent

    def _index_update(self, entry_dir: str, record: dict) -> None:
        """Incrementally fold one just-written entry into the index
        (called under the write lock).  A concurrent foreign writer may
        race the whole-file write; the stat validation in
        :meth:`_load_index` turns any lost update into a rebuild, never
        a stale read."""
        try:
            with open(self._index_path(), "r", encoding="utf-8") as fh:
                data = json.load(fh)
        except (OSError, json.JSONDecodeError):
            data = None
        if not (
            isinstance(data, dict)
            and data.get("format") == STORE_FORMAT
            and data.get("hash_version") == HASH_VERSION
            and isinstance(data.get("entries"), dict)
        ):
            data = {
                "format": STORE_FORMAT,
                "hash_version": HASH_VERSION,
                "entries": {},
            }
        data["entries"][os.path.basename(entry_dir)] = {
            "record": {
                k: v for k, v in record.items() if k != "summary"
            },
            "stat": self._verdict_stat(entry_dir),
        }
        _atomic_write_json(self._index_path(), data)

    def entries(self) -> List[StoreEntry]:
        out = []
        idx = self._load_index()
        for name in sorted(idx):
            record = (idx[name] or {}).get("record")
            if not isinstance(record, dict):
                continue  # torn/in-progress entry: invisible by design
            if record.get("format") != STORE_FORMAT:
                continue
            if record.get("hash_version") != HASH_VERSION:
                continue
            out.append(StoreEntry(
                os.path.join(self.entries_dir, name), record,
                loader=self._read_verdict,
            ))
        return out

    def lookup(self, spec: SpecFingerprint) -> Optional[StoreEntry]:
        """O(1) exact-match read: entry directories are content-
        addressed by ``spec_key[:24]``, so the identical-hit path reads
        exactly one verdict record — it must not scale with store size
        (the family scan in :meth:`classify` still walks the entries;
        indexing that is a named ROADMAP follow-up)."""
        path = os.path.join(self.entries_dir, spec.spec_key[:24])
        record = self._read_verdict(path)
        if record is None:
            return None
        if (
            record.get("format") != STORE_FORMAT
            or record.get("hash_version") != HASH_VERSION
            or record.get("spec_key") != spec.spec_key
        ):
            return None
        return StoreEntry(path, record)

    # -- classification --------------------------------------------------------

    def classify(self, spec: SpecFingerprint) -> Delta:
        """Pick the cheapest sound path for ``spec`` against the stored
        entries: identical > property-only > constant-widening > cold.
        Every refusal carries the reason (the loud half of "degrade
        loudly"); the caller journals it."""
        # Refused BEFORE the exact-match check: without declared
        # constants, two differently-parameterized instances of the
        # same model class can hash alike (the transition constants
        # live outside the bytecode), and an "exact" hit could serve
        # the wrong verdict.
        if spec.constants is None:
            return Delta(
                COLD, None,
                f"{spec.model_label} declares no stable spec_constants() "
                "(parallel/compiled.py); near-identical reuse would risk "
                "matching differently-parameterized models",
            )

        exact = self.lookup(spec)
        if exact is not None:
            return Delta(IDENTICAL, exact, "spec unchanged")
        entries = self.entries()

        family = [
            e for e in entries
            if e.record.get("family_key") == spec.family_key
        ]
        # Relatives are tried NEWEST-FIRST until one passes the reuse
        # gate: a recent sibling whose rows are ineligible (e.g. a
        # derived entry with no exhaustiveness witness) must not shadow
        # an older entry that can serve the re-check.  A refused
        # property-only candidate FALLS THROUGH to the widening
        # candidates (the next-cheapest sound mode), and only when
        # every relative refused does the submission go cold — with
        # the first (most-preferred) refusal as the reason.
        refusals = []
        prop_only = self._newest_first([
            e for e in family
            if e.components.get("constants")
            == spec.components["constants"]
        ])
        for entry in prop_only:
            reason = self._reuse_refusal(spec, entry)
            if reason is None:
                return Delta(
                    PROPERTY_ONLY, entry,
                    "only the property component changed; re-evaluating "
                    "the new properties over the stored row log",
                )
            refusals.append((entry, reason))

        widen = self._newest_first([
            e for e in family
            if e.components.get("properties")
            == spec.components["properties"]
        ])
        for entry in widen:
            reason = self._widen_refusal(spec, entry)
            if reason is None:
                return Delta(
                    CONSTANT_WIDENING, entry,
                    "constants changed by a declared monotone widening; "
                    "seeding the frontier from the stored reachable set",
                )
            refusals.append((entry, reason))
        if refusals:
            entry, reason = refusals[0]
            return Delta(COLD, entry, reason)

        if family:
            return Delta(
                COLD, self._newest(family),
                "constants AND properties both changed vs every stored "
                "relative; no sound reuse path",
            )
        return Delta(COLD, self._nearest(spec, entries), self._cold_reason(
            spec, entries
        ))

    @staticmethod
    def _newest(entries: List[StoreEntry]) -> StoreEntry:
        return max(entries, key=lambda e: e.record.get("created_at", 0))

    @staticmethod
    def _newest_first(entries: List[StoreEntry]) -> List[StoreEntry]:
        return sorted(
            entries, key=lambda e: e.record.get("created_at", 0),
            reverse=True,
        )

    def _reuse_refusal(self, spec: SpecFingerprint,
                       entry: StoreEntry) -> Optional[str]:
        """Why the stored row log cannot back a property-only re-eval
        of ``spec`` (None = it can)."""
        if not entry.rows_reusable:
            return (
                "stored entry's row log is not reusable "
                f"({entry.record.get('rows_reason', 'unknown')})"
            )
        if spec.has_eventually:
            return (
                "the new property set contains EVENTUALLY properties, "
                "whose verdicts depend on path structure (eventually-bit "
                "propagation), not per-state predicates over the row log"
            )
        if not os.path.exists(entry.snapshot_path):
            return "stored entry is missing its snapshot.npz"
        return None

    def _widen_refusal(self, spec: SpecFingerprint,
                       entry: StoreEntry) -> Optional[str]:
        refusal = self._reuse_refusal(spec, entry)
        if refusal is not None:
            return refusal
        old_constants = entry.record.get("constants")
        if not isinstance(old_constants, dict):
            return "stored entry carries no constants data"
        if not spec.compiled.spec_widens(old_constants):
            return (
                "constants changed but the model does not declare the "
                "change a monotone widening (CompiledModel.spec_widens); "
                "a narrowing — or any unclassified constant edit — must "
                "re-explore from scratch"
            )
        if entry.record.get("snapshot_key") != spec.snapshot_key:
            return (
                "the stored snapshot's engine key does not match this "
                "spec (init states or packed layout shifted with the "
                "constant); seeding would corrupt the run"
            )
        return None

    def _nearest(self, spec: SpecFingerprint,
                 entries: List[StoreEntry]) -> Optional[StoreEntry]:
        """The entry sharing the most components — diagnostics only."""
        def score(e):
            return sum(
                1 for k, v in spec.components.items()
                if k != "engine" and e.components.get(k) == v
            )

        scored = [e for e in entries if score(e) > 0]
        return max(scored, key=score) if scored else None

    def _cold_reason(self, spec: SpecFingerprint,
                     entries: List[StoreEntry]) -> str:
        if not entries:
            return "empty store (first run of this spec is the cold baseline)"
        near = self._nearest(spec, entries)
        if near is None:
            return "no stored entry shares any spec component"
        changed = sorted(
            k for k, v in spec.components.items()
            if k != "engine" and near.components.get(k) != v
        )
        return (
            f"changed component(s) vs nearest entry {near.entry_id}: "
            + ", ".join(changed)
        )

    # -- write surface ---------------------------------------------------------

    def record(self, spec: SpecFingerprint, checker, *,
               engine_kwargs: Optional[dict] = None,
               recheck_mode: str = COLD,
               elapsed_sec: Optional[float] = None,
               seeded: bool = False) -> Optional[StoreEntry]:
        """Journal one COMPLETED run into the store.  Returns the entry,
        or None when the run is not storable (error'd / partial — the
        skip is journaled, never silent)."""
        model = spec.model
        if spec.constants is None:
            # The classify() refusal's storage-side twin: an entry
            # whose spec key cannot distinguish constants must never
            # exist to be matched.
            self._log(
                "incr_store_skipped", spec_key=spec.spec_key,
                reason=(
                    f"{spec.model_label} declares no stable "
                    "spec_constants(); entry would be ambiguous"
                ),
            )
            return None
        try:
            checker.join()
        except Exception as exc:  # journal, don't store (KeyboardInterrupt
            # and friends still propagate — shutdown is not ours to eat)
            self._log("incr_store_skipped", spec_key=spec.spec_key,
                      reason=f"run failed: {type(exc).__name__}: {exc}"[:300])
            return None
        complete, why = self._verdict_complete(spec, checker)
        if not complete:
            self._log("incr_store_skipped", spec_key=spec.spec_key,
                      reason=why)
            return None
        reusable, rows_reason = self._rows_reusable(spec, checker, seeded)
        fps = checker.discovered_fingerprints()
        summary = _summarize(checker, model)
        entry_dir = os.path.join(
            self.entries_dir, spec.spec_key[:24]
        )
        with _WRITE_LOCK:
            os.makedirs(entry_dir, exist_ok=True)
            # Overwrite-in-place of a re-recorded spec: drop the old
            # verdict first so a reader never pairs the new snapshot
            # with the old record, then lay the artifacts down,
            # verdict last.
            verdict_path = os.path.join(entry_dir, "verdict.json")
            try:
                os.remove(verdict_path)
            except OSError:
                pass
            cold_dir = os.path.join(entry_dir, "cold")
            if os.path.isdir(cold_dir):
                for f in os.listdir(cold_dir):
                    try:
                        os.remove(os.path.join(cold_dir, f))
                    except OSError:
                        pass
            cold = ColdStore(spill_dir=cold_dir)
            cold.add_run(fps)
            cold.close()
            snapshot_path = os.path.join(entry_dir, "snapshot.npz")
            if reusable:
                checker.save_snapshot(snapshot_path)
            else:
                try:
                    os.remove(snapshot_path)
                except OSError:
                    pass
            return self._write_record(
                spec, entry_dir,
                summary=summary,
                engine_kwargs=engine_kwargs,
                recheck_mode=recheck_mode,
                seeded=seeded,
                rows_reusable=reusable,
                rows_reason=rows_reason,
                cold_entries=int(fps.shape[0]),
                elapsed_sec=elapsed_sec,
            )

    def record_derived(self, spec: SpecFingerprint, checker,
                       donor: StoreEntry, *,
                       engine_kwargs: Optional[dict] = None,
                       elapsed_sec: Optional[float] = None,
                       ) -> StoreEntry:
        """Persist a property-re-eval verdict as a first-class entry so
        the NEXT identical submission of the edited spec is an O(1)
        verdict hit.  The row artifacts are the DONOR's (same
        codec+constants ⇒ same reachable set): the snapshot and cold
        runs are hard-linked (copied on filesystems without links)
        rather than re-journaled from a device that was never touched.
        Verdict completeness needs no gate here: the re-eval covered
        the donor's complete row log by construction."""
        import shutil

        summary = _summarize(checker, spec.model)
        entry_dir = os.path.join(self.entries_dir, spec.spec_key[:24])

        def link_or_copy(src, dst):
            if os.path.abspath(src) == os.path.abspath(dst):
                return
            try:
                os.remove(dst)
            except OSError:
                pass
            try:
                os.link(src, dst)
            except OSError:
                shutil.copyfile(src, dst)

        with _WRITE_LOCK:
            os.makedirs(entry_dir, exist_ok=True)
            try:
                os.remove(os.path.join(entry_dir, "verdict.json"))
            except OSError:
                pass
            if os.path.exists(donor.snapshot_path):
                link_or_copy(
                    donor.snapshot_path,
                    os.path.join(entry_dir, "snapshot.npz"),
                )
            donor_cold = os.path.join(donor.path, "cold")
            cold_dir = os.path.join(entry_dir, "cold")
            if os.path.isdir(donor_cold) and os.path.abspath(
                donor_cold
            ) != os.path.abspath(cold_dir):
                os.makedirs(cold_dir, exist_ok=True)
                for f in os.listdir(cold_dir):
                    try:
                        os.remove(os.path.join(cold_dir, f))
                    except OSError:
                        pass
                for f in sorted(os.listdir(donor_cold)):
                    link_or_copy(
                        os.path.join(donor_cold, f),
                        os.path.join(cold_dir, f),
                    )
            return self._write_record(
                spec, entry_dir,
                summary=summary,
                engine_kwargs=engine_kwargs,
                recheck_mode=PROPERTY_ONLY,
                seeded=bool(donor.record.get("seeded")),
                rows_reusable=(
                    donor.rows_reusable and not spec.has_eventually
                ),
                rows_reason=(
                    f"rows inherited from donor entry {donor.entry_id} "
                    f"({donor.record.get('rows_reason', '')})"
                ),
                cold_entries=int(donor.record.get("cold_entries", 0)),
                elapsed_sec=elapsed_sec,
                donor=donor.entry_id,
            )

    def _write_record(self, spec: SpecFingerprint, entry_dir: str, *,
                      summary: dict, engine_kwargs: Optional[dict],
                      recheck_mode: str, seeded: bool,
                      rows_reusable: bool, rows_reason: str,
                      cold_entries: int,
                      elapsed_sec: Optional[float],
                      donor: Optional[str] = None) -> StoreEntry:
        """The ONE place the verdict-record schema exists — cold,
        seeded, and derived entries all land through here."""
        record = {
            "format": STORE_FORMAT,
            "hash_version": HASH_VERSION,
            "created_at": time.time(),
            "spec_key": spec.spec_key,
            "family_key": spec.family_key,
            "components": spec.components,
            "constants": spec.constants,
            "model": spec.model_label,
            "property_names": spec.property_names,
            "expectations": spec.expectations,
            "snapshot_key": spec.snapshot_key,
            "engine": {
                "name": spec.engine, "kwargs": engine_kwargs or {},
            },
            "recheck_mode": recheck_mode,
            "seeded": bool(seeded),
            "rows_reusable": bool(rows_reusable),
            "rows_reason": rows_reason,
            "cold_entries": int(cold_entries),
            "elapsed_sec": elapsed_sec,
            "summary": summary,
        }
        _atomic_write_json(os.path.join(entry_dir, "verdict.json"), record)
        self._index_update(entry_dir, record)
        entry = StoreEntry(entry_dir, record)
        self._log(
            "incr_stored",
            spec_key=spec.spec_key,
            entry=entry.entry_id,
            unique=summary.get("unique_state_count"),
            rows_reusable=bool(rows_reusable),
            cold_entries=int(cold_entries),
            seeded=bool(seeded),
            **({"donor": donor} if donor else {}),
        )
        return entry

    def _verdict_complete(self, spec: SpecFingerprint, checker):
        """May this run's VERDICT enter the cache at all?  A truncated
        run (wall timeout, cooperative stop, target_state_count) has a
        partial verdict — its "no violation found" claims cover only
        the explored prefix, and the truncating knob (timeout in
        particular) is deliberately NOT part of the spec hash, so a
        stored partial verdict would later serve as "identical" for an
        untruncated resubmission.  Complete means one of: the frontier
        drained; every property has a discovery (a finish_when early
        exit then asserts nothing negative); or the run hit exactly its
        hashed depth bound."""
        if checker.stop_requested():
            return False, (
                "run was cooperatively stopped; the verdict is partial"
            )
        carry = getattr(checker, "_carry_dev", None)
        if carry is None:
            return False, "no run state to certify"
        remaining = int(carry["level_end"]) - int(carry["level_start"])
        if remaining == 0:
            return True, ""
        if not (set(spec.property_names) - set(checker.discoveries())):
            # Every property discovered: the verdict makes only
            # positive claims, each backed by a concrete path.
            return True, ""
        if (
            spec.target_max_depth
            and not spec.target_state_count
            and int(carry["depth"]) + 1 >= int(spec.target_max_depth)
        ):
            return True, ""  # complete w.r.t. the HASHED depth bound
        return False, (
            "frontier not drained (timeout/target/finish_when exit); a "
            "partial verdict must not enter the cache"
        )

    def _rows_reusable(self, spec: SpecFingerprint, checker,
                       seeded: bool):
        """The soundness gate (module docstring): complete, untruncated,
        unbounded, with an undiscovered-property exhaustiveness
        witness."""
        from ..parallel.wavefront import TpuChecker

        if type(checker) is not TpuChecker:
            return False, (
                f"engine {type(checker).__name__} does not journal a "
                "reusable snapshot (single-chip spawn_tpu runs only)"
            )
        if checker.stop_requested():
            return False, "run was cooperatively stopped (partial)"
        if spec.target_max_depth:
            return False, (
                "depth-bounded runs evaluate nothing past the target "
                "depth; the row log is complete only w.r.t. the bound"
            )
        if spec.target_state_count:
            return False, "target_state_count bounds truncate exploration"
        carry = getattr(checker, "_carry_dev", None)
        if carry is None:
            return False, "no run state to snapshot"
        if int(carry["level_start"]) < int(carry["level_end"]):
            return False, (
                "frontier not drained (timeout/finish_when exit); the "
                "row log is a prefix, not the reachable set"
            )
        discovered = set(checker.discoveries())
        if not (set(spec.property_names) - discovered):
            return False, (
                "every property discovered: the awaiting gate may have "
                "pruned expansion (no exhaustiveness witness)"
            )
        return True, "complete exhaustive run" + (
            " (seeded re-check)" if seeded else ""
        )

    def _log(self, event: str, **fields) -> None:
        if self.journal is not None:
            self.journal.append(event, **fields)
