"""On-demand checking: compute nothing until asked.

Reference: src/checker/on_demand.rs.  A BFS-flavored engine whose workers
block on a control channel; ``check_fingerprint(fp)`` expands only the
pending job matching the fingerprint the Explorer user clicked, and
``run_to_completion()`` switches to normal exhaustive checking.  This is
the engine behind ``CheckerBuilder.serve``.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .checker import Checker
from .job_market import JobMarket
from .model import Expectation
from .path import Path

BLOCK_SIZE = 1500


class _CheckFingerprint:
    __slots__ = ("fp",)

    def __init__(self, fp: int):
        self.fp = fp


_RUN_TO_COMPLETION = object()
_SHUTDOWN = object()


class OnDemandChecker(Checker):
    def __init__(self, options):
        super().__init__(options.model)
        model = self._model
        self._options = options
        self._properties = model.properties()
        self._visitor = options._visitor
        self._target_state_count = options._target_state_count

        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._max_depth = 0
        self._count_lock = threading.Lock()
        # fp -> Optional[parent fp] predecessor tree (src/checker/on_demand.rs:60-67)
        self._generated: Dict[int, Optional[int]] = {
            model.fingerprint(s): None for s in init_states
        }
        self._gen_lock = threading.Lock()
        self._discoveries: Dict[str, int] = {}
        self._errors: List[BaseException] = []

        ebits = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY
        )
        pending = deque(
            (s, model.fingerprint(s), ebits, 1) for s in init_states
        )

        close_at = (
            time.monotonic() + options._timeout
            if options._timeout is not None
            else None
        )
        thread_count = options._thread_count
        self._market: JobMarket = JobMarket(thread_count, close_at)
        self._market.push(pending)

        # Control-flow fan-out: one queue per worker, fed by a forwarder
        # (src/checker/on_demand.rs:221-227).
        self._control: "queue.Queue" = queue.Queue()
        self._worker_controls: List["queue.Queue"] = [
            queue.Queue() for _ in range(thread_count)
        ]
        self._handles: List[threading.Thread] = []
        for t in range(thread_count):
            th = threading.Thread(
                target=self._worker,
                args=(self._worker_controls[t],),
                name=f"checker-{t}",
                daemon=True,
            )
            self._handles.append(th)
        # The forwarder is not joined: it parks on the control queue for the
        # checker's lifetime (the analog of the reference's forwarder thread
        # exiting only when the sender is dropped).
        self._forwarder = threading.Thread(
            target=self._forward_control, name="control-forwarder", daemon=True
        )
        self._forwarder.start()
        for th in self._handles:
            th.start()

    def _forward_control(self) -> None:
        while True:
            msg = self._control.get()
            for q in self._worker_controls:
                q.put(msg)
            if msg is _SHUTDOWN:
                return

    # --- worker loop (src/checker/on_demand.rs:108-215) ----------------------

    def _worker(self, control: "queue.Queue") -> None:
        try:
            pending: deque = deque()
            targetted: deque = deque()
            wait_for_fingerprints = True
            while True:
                if not pending:
                    pending = self._market.pop()
                    if not pending:
                        return

                if wait_for_fingerprints:
                    # Step 0: wait for someone to ask for work.
                    while True:
                        msg = control.get()
                        if msg is _SHUTDOWN:
                            return
                        if msg is _RUN_TO_COMPLETION:
                            wait_for_fingerprints = False
                            break
                        # _CheckFingerprint
                        if not pending:
                            break
                        index = next(
                            (
                                i
                                for i, job in enumerate(pending)
                                if job[1] == msg.fp
                            ),
                            None,
                        )
                        if index is not None:
                            job = pending[index]
                            del pending[index]
                            targetted.append(job)
                            break
                else:
                    targetted.extend(pending)
                    pending.clear()

                # Step 1: do work.
                self._check_block(targetted, BLOCK_SIZE)
                pending.extend(targetted)
                targetted.clear()
                if len(self._discoveries) == len(self._properties):
                    return
                if (
                    self._target_state_count is not None
                    and self._target_state_count <= self._state_count
                ):
                    return

                # Step 2: share work.
                if len(pending) > 1 and len(self._worker_controls) > 1:
                    self._market.split_and_push(pending)
        except BaseException as e:
            self._errors.append(e)
        finally:
            self._market.worker_done()

    def _check_block(self, pending: deque, max_count: int) -> None:
        model = self._model
        properties = self._properties
        local = deque()
        for _ in range(min(max_count, len(pending))):
            local.append(pending.popleft())
        while local:
            state, state_fp, ebits, depth = local.pop()

            with self._count_lock:
                if depth > self._max_depth:
                    self._max_depth = depth

            if self._visitor is not None:
                self._visitor.visit(model, self._reconstruct(state_fp))

            is_awaiting_discoveries = False
            for i, prop in enumerate(properties):
                if prop.name in self._discoveries:
                    continue
                if prop.expectation is Expectation.ALWAYS:
                    if not prop.condition(model, state):
                        self._discoveries.setdefault(prop.name, state_fp)
                    else:
                        is_awaiting_discoveries = True
                elif prop.expectation is Expectation.SOMETIMES:
                    if prop.condition(model, state):
                        self._discoveries.setdefault(prop.name, state_fp)
                    else:
                        is_awaiting_discoveries = True
                else:
                    is_awaiting_discoveries = True
                    if prop.condition(model, state):
                        ebits = ebits - {i}
            if not is_awaiting_discoveries:
                return

            is_terminal = True
            actions: List[Any] = []
            model.actions(state, actions)
            for action in actions:
                next_state = model.next_state(state, action)
                if next_state is None:
                    continue
                if not model.within_boundary(next_state):
                    continue
                with self._count_lock:
                    self._state_count += 1
                next_fp = model.fingerprint(next_state)
                with self._gen_lock:
                    if next_fp in self._generated:
                        is_terminal = False
                        continue
                    self._generated[next_fp] = state_fp
                is_terminal = False
                pending.appendleft((next_state, next_fp, ebits, depth + 1))
            if is_terminal:
                for i, prop in enumerate(properties):
                    if i in ebits:
                        self._discoveries.setdefault(prop.name, state_fp)

    def _reconstruct(self, fp: int) -> Path:
        fps: deque = deque()
        next_fp: Optional[int] = fp
        while next_fp is not None and next_fp in self._generated:
            fps.appendleft(next_fp)
            next_fp = self._generated[next_fp]
        return Path.from_fingerprints(self._model, list(fps))

    # --- Checker surface (src/checker/on_demand.rs:397-446) ------------------

    def check_fingerprint(self, fingerprint: int) -> None:
        self._control.put(_CheckFingerprint(fingerprint))

    def run_to_completion(self) -> None:
        self._control.put(_RUN_TO_COMPLETION)

    def shutdown(self) -> None:
        """Stop waiting workers (the Python analog of dropping the control
        channel senders)."""
        self._market.close()
        self._control.put(_SHUTDOWN)

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: self._reconstruct(fp)
            for name, fp in list(self._discoveries.items())
        }

    def handles(self) -> List[threading.Thread]:
        return self._handles

    def is_done(self) -> bool:
        return self._market.is_closed or len(self._discoveries) == len(
            self._properties
        )

    def join(self) -> "OnDemandChecker":
        for h in self._handles:
            h.join()
        if self._errors:
            raise self._errors[0]
        return self
