"""The central ``Model`` abstraction and ``Property`` declarations.

Reference: the ``Model`` trait (src/lib.rs:158-257), ``Property`` and
``Expectation`` (src/lib.rs:264-338).  Semantics are kept identical —
``next_state`` returning ``None`` means "the action does not change the
state", ``within_boundary`` prunes the state space, properties are named
``always`` / ``sometimes`` / ``eventually`` predicates over (model, state).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Tuple


class Expectation(enum.Enum):
    """Whether a property is always, eventually, or sometimes true.

    Reference: src/lib.rs:320-338.
    """

    ALWAYS = "always"
    EVENTUALLY = "eventually"
    SOMETIMES = "sometimes"

    @property
    def discovery_is_failure(self) -> bool:
        return self is not Expectation.SOMETIMES


@dataclass(frozen=True)
class Property:
    """A named predicate over (model, state).

    Reference: src/lib.rs:264-317.
    """

    expectation: Expectation
    name: str
    condition: Callable[[Any, Any], bool]

    @staticmethod
    def always(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        return Property(Expectation.ALWAYS, name, condition)

    @staticmethod
    def eventually(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        """Note: per the reference semantics (src/lib.rs:286-290), `eventually`
        properties only work correctly on acyclic paths; a path ending in a
        cycle is not viewed as terminating, a documented false negative that
        this implementation intentionally reproduces."""
        return Property(Expectation.EVENTUALLY, name, condition)

    @staticmethod
    def sometimes(name: str, condition: Callable[[Any, Any], bool]) -> "Property":
        return Property(Expectation.SOMETIMES, name, condition)


class Model:
    """Implementations model a nondeterministic system's evolution.

    Reference: the ``Model`` trait, src/lib.rs:158-257.  States and actions
    are arbitrary hashable Python values; states must be canonically
    encodable (see ``stateright_tpu.ops.fingerprint``).
    """

    def init_states(self) -> List[Any]:
        raise NotImplementedError

    def actions(self, state: Any, actions: List[Any]) -> None:
        raise NotImplementedError

    def next_state(self, last_state: Any, action: Any) -> Optional[Any]:
        raise NotImplementedError

    def properties(self) -> List[Property]:
        return []

    def within_boundary(self, state: Any) -> bool:
        return True

    def format_action(self, action: Any) -> str:
        return repr(action)

    def format_step(self, last_state: Any, action: Any) -> Optional[str]:
        next_state = self.next_state(last_state, action)
        return None if next_state is None else repr(next_state)

    def as_svg(self, path) -> Optional[str]:
        return None

    def next_steps(self, last_state: Any) -> List[Tuple[Any, Any]]:
        actions: List[Any] = []
        self.actions(last_state, actions)
        steps = []
        for action in actions:
            state = self.next_state(last_state, action)
            if state is not None:
                steps.append((action, state))
        return steps

    def next_states(self, last_state: Any) -> List[Any]:
        return [s for (_a, s) in self.next_steps(last_state)]

    def get_property(self, name: str) -> Property:
        """Look up a property by name (the reference's ``Model::property``;
        renamed because ``ActorModel.property`` is the property-*adding*
        builder method, mirroring the reference's ``ActorModel::property``)."""
        for p in self.properties():
            if p.name == name:
                return p
        available = [p.name for p in self.properties()]
        raise KeyError(f"Unknown property. requested={name}, available={available}")

    # Alias for reference-API parity on plain models; ActorModel overrides
    # ``property`` with its builder method.
    property = get_property

    def checker(self) -> "CheckerBuilder":
        from .checker import CheckerBuilder

        return CheckerBuilder(self)

    def fingerprint(self, state: Any) -> int:
        """Fingerprint a state.  Overridable so compiled/TPU models can hash
        their packed representation instead of the generic host encoding."""
        from ..ops.fingerprint import fingerprint

        return fingerprint(state)
