"""Checker surface and fluent builder.

Reference: src/checker.rs — ``CheckerBuilder`` (fluent config + spawn_bfs /
spawn_dfs / spawn_on_demand / spawn_simulation / serve) and the ``Checker``
trait (counts, discoveries, join/report, assertion helpers).  This module
adds ``spawn_tpu`` — the TPU wavefront engine that is the point of this
framework — as a first-class sibling of the reference spawn methods.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from .has_discoveries import HasDiscoveries
from .model import Expectation, Model
from .path import Path
from .report import ReportData, ReportDiscovery, Reporter
from .visitor import as_visitor


class CheckerBuilder:
    def __init__(self, model: Model):
        self.model = model
        self._symmetry = None
        self._target_state_count: Optional[int] = None
        self._target_max_depth: Optional[int] = None
        self._thread_count = 1
        self._visitor = None
        self._finish_when: HasDiscoveries = HasDiscoveries.ALL
        self._timeout: Optional[float] = None

    def symmetry(self) -> "CheckerBuilder":
        """Enable symmetry reduction via the state's ``representative()``
        method.  Reference: src/checker.rs:222-227.

        Engine support mirrors the reference plus the device path:
        ``spawn_dfs`` dedups on the representative's fingerprint host-side
        (src/checker/dfs.rs:309-334); ``spawn_bfs`` ignores the option
        (reference parity, SURVEY §2.1); ``spawn_tpu`` /
        ``spawn_tpu_sharded`` honor it when the compiled model declares a
        device canonicalization (``canon_spec()``/``canon_rows``,
        parallel/canon.py) and raise loudly otherwise — never a silent
        fall-through to unreduced exploration (docs/SYMMETRY.md)."""
        return self.symmetry_fn(lambda s: s.representative())

    def symmetry_fn(self, representative) -> "CheckerBuilder":
        self._symmetry = representative
        return self

    def finish_when(self, has_discoveries: HasDiscoveries) -> "CheckerBuilder":
        self._finish_when = has_discoveries
        return self

    def target_state_count(self, count: int) -> "CheckerBuilder":
        self._target_state_count = count if count > 0 else None
        return self

    def target_max_depth(self, depth: int) -> "CheckerBuilder":
        self._target_max_depth = depth if depth > 0 else None
        return self

    def threads(self, thread_count: int) -> "CheckerBuilder":
        if thread_count < 1:
            raise ValueError("thread_count must be >= 1")
        self._thread_count = thread_count
        return self

    def visitor(self, visitor) -> "CheckerBuilder":
        self._visitor = as_visitor(visitor)
        return self

    def timeout(self, seconds: float) -> "CheckerBuilder":
        self._timeout = seconds
        return self

    def spawn_bfs(self) -> "Checker":
        from .engine import GraphChecker

        return GraphChecker(self, dfs=False)

    def spawn_dfs(self) -> "Checker":
        from .engine import GraphChecker

        return GraphChecker(self, dfs=True)

    @staticmethod
    def _require(module: str, what: str) -> None:
        """Distinguish "engine not written yet" from a genuinely broken
        transitive import inside an existing engine module."""
        import importlib.util

        if importlib.util.find_spec(module) is None:
            raise NotImplementedError(f"{what} not yet implemented in this build")

    def spawn_simulation(self, seed: int, chooser=None) -> "Checker":
        self._require("stateright_tpu.core.simulation", "simulation checker")
        from .simulation import SimulationChecker, UniformChooser

        return SimulationChecker(self, seed, chooser or UniformChooser())

    def spawn_on_demand(self) -> "Checker":
        self._require("stateright_tpu.core.on_demand", "on-demand checker")
        from .on_demand import OnDemandChecker

        return OnDemandChecker(self)

    def spawn_tpu(self, **kwargs) -> "Checker":
        """Spawn the TPU wavefront checker: successor expansion, frontier
        dedup, and property evaluation run on-device as a vmapped wavefront
        BFS (the replacement for the reference's thread-pool hot loop,
        src/checker/bfs.rs:177-335).  With ``symmetry()``, dedup keys on
        the canonical row's fingerprint via the compiled model's canon
        spec (parallel/canon.py) while logging original rows; models
        without a canon spec fail the spawn loudly.

        ``trace=True`` runs the wave loop in phase-timed segments with
        roofline byte accounting (obs/, docs/OBSERVABILITY.md).  Coarse
        wave-granularity visitors are supported via the traced readback
        path: a ``visitor()`` forces tracing on and receives every
        unique state once, at expansion, as a single-state path — BFS
        level order across waves, fingerprint-sorted within a level."""
        self._require("stateright_tpu.parallel.wavefront", "TPU wavefront checker")
        from ..parallel.wavefront import TpuChecker

        return TpuChecker(self, **kwargs)

    def spawn_tpu_simulation(self, seed: int, **kwargs) -> "Checker":
        """Spawn the device Monte-carlo checker: a batch of random trace
        walks per program call, one walker per vmap lane (the stochastic
        sibling of ``spawn_tpu``; host engine: core/simulation.py).  Runs
        until ``finish_when`` / ``target_state_count`` / ``timeout``
        stops it, like the host simulation engine."""
        self._require(
            "stateright_tpu.parallel.simulation_tpu", "TPU simulation checker"
        )
        from ..parallel.simulation_tpu import TpuSimulationChecker

        return TpuSimulationChecker(self, seed, **kwargs)

    def spawn_tpu_tiered(self, **kwargs) -> "Checker":
        """Spawn the tiered out-of-core wavefront checker: the same
        wavefront BFS as ``spawn_tpu`` under a fixed HBM budget
        (``memory_budget_mb``) — the device hash set is the hot tier,
        evicted fingerprint partitions live in host RAM (optionally
        disk, ``cold_dir=``) as sorted immutable runs, and candidate
        waves are merge-joined against the cold runs on device before
        commit, so the discovery set is bit-identical to an
        unconstrained run (docs/TIERED.md).  Use for state spaces whose
        fingerprint set exceeds one chip's HBM, or whenever the table
        footprint must be capped; resumable mid-run like ``spawn_tpu``."""
        self._require(
            "stateright_tpu.tiered.engine", "tiered TPU checker"
        )
        from ..tiered.engine import TieredTpuChecker

        return TieredTpuChecker(self, **kwargs)

    def spawn_tpu_tiered_sharded(self, **kwargs) -> "Checker":
        """Spawn the composed pod-scale engine: the sharded wavefront
        BFS of ``spawn_tpu_sharded`` with the tiered engine's hard
        memory cap applied PER SHARD (``memory_budget_mb`` bounds each
        shard's fingerprint table; evicted partitions live in shard-
        local cold stores — owner-sharded fingerprints mean the
        pre-commit cold merge-join never crosses shards).  Snapshots
        embed mesh size × cold tiers and can be re-keyed onto a larger
        or smaller mesh with ``stateright_tpu.tiered.reshard`` (the
        ``reshard`` CLI verb); discovery sets stay bit-identical to an
        unconstrained single-chip run (docs/TIERED.md)."""
        self._require(
            "stateright_tpu.tiered.sharded_engine",
            "tiered sharded TPU checker",
        )
        from ..tiered.sharded_engine import TieredShardedTpuChecker

        return TieredShardedTpuChecker(self, **kwargs)

    def spawn_tpu_sharded(self, **kwargs) -> "Checker":
        """Spawn the multi-chip wavefront checker: frontier and visited set
        sharded over a ``jax.sharding.Mesh`` by fingerprint ownership, with
        an all_to_all successor exchange per wave and psum termination —
        the ICI-collective replacement for the reference's job market
        (src/job_market.rs; SURVEY §2.7)."""
        self._require(
            "stateright_tpu.parallel.sharded", "sharded TPU wavefront checker"
        )
        from ..parallel.sharded import ShardedTpuChecker

        return ShardedTpuChecker(self, **kwargs)

    def serve(self, address, **kwargs) -> "Checker":
        """Serve the interactive Explorer on ``address`` backed by an
        on-demand checker (reference: src/checker.rs:144-151).  Blocks by
        default like the reference; pass ``block=False`` to serve in the
        background and get the checker back immediately."""
        self._require("stateright_tpu.explorer.server", "explorer server")
        from ..explorer.server import serve

        return serve(self, address, **kwargs)


def property_verdicts(checker):
    """Per-property verdict rows for a finished checker, plus the first
    failure-classified discovery name (in the model's property order —
    the deterministic ``violation`` the serving layer and the
    incremental verification store both report).  ONE definition so a
    job result (serve/portfolio.checker_summary) and a stored verdict
    record (incr/store._summarize) can never disagree about the same
    run."""
    model = checker.model()
    discoveries = checker.discoveries()
    props = []
    violation = None
    for p in model.properties():
        found = p.name in discoveries
        classification = (
            checker.discovery_classification(p.name) if found else None
        )
        if found and classification == "counterexample" and violation is None:
            violation = p.name
        props.append({
            "name": p.name,
            "expectation": p.expectation.name,
            "discovered": found,
            "classification": classification,
        })
    return props, violation


class Checker:
    """Base checker surface.  Reference: the ``Checker`` trait,
    src/checker.rs:294-578."""

    def __init__(self, model: Model):
        self._model = model
        # Cooperative cancellation (the serving layer's job-cancel path,
        # serve/scheduler.py): request_stop() asks the engine to wind
        # down at its next host-side check, exactly like a wall-clock
        # timeout — partial counts stand, is_done() becomes true, join()
        # returns.  Engines poll stop_requested() at the same points they
        # poll their deadline.
        self._stop_requested = threading.Event()

    # --- interface implemented by engines -----------------------------------

    def model(self) -> Model:
        return self._model

    def state_count(self) -> int:
        raise NotImplementedError

    def unique_state_count(self) -> int:
        raise NotImplementedError

    def max_depth(self) -> int:
        raise NotImplementedError

    def discoveries(self) -> Dict[str, Path]:
        raise NotImplementedError

    def handles(self) -> list:
        return []

    def is_done(self) -> bool:
        raise NotImplementedError

    def join(self) -> "Checker":
        for h in self.handles():
            h.join()
        return self

    def check_fingerprint(self, fingerprint: int) -> None:
        pass  # only meaningful for on-demand checking

    def run_to_completion(self) -> None:
        pass  # only meaningful for on-demand checking

    def request_stop(self) -> None:
        """Ask a running check to stop early (cooperative, never blocks):
        the engine finishes its current block/device call, keeps every
        committed count and discovery, and completes like a timed-out
        run.  Idempotent; a no-op on an already-finished checker.
        Engines with extra wakeup machinery extend this (the host graph
        engine closes its job market so idle workers drain)."""
        self._stop_requested.set()

    def stop_requested(self) -> bool:
        return self._stop_requested.is_set()

    def metrics(self) -> dict:
        """Live observability snapshot — counts every engine has; the
        device engines extend it with their registry (wave cadence,
        table occupancy, device-call time, always-on vitals histograms)
        and, under ``trace=True``, the roofline trace summary.  Served
        by the Explorer's ``GET /.metrics`` (docs/OBSERVABILITY.md names
        the fields); never blocks on a still-running checker.

        The keys emitted HERE are the guaranteed cross-engine schema
        (pinned by tests/test_metrics_schema.py): every engine — host
        graph, simulation, and all device engines — reports them with
        these types.  ``table_load_factor`` is 0.0 for engines with no
        device fingerprint table; the program-cache counters are the
        process-global compiled-program cache
        (parallel/wave_common.cached_program), included everywhere so
        one scrape answers "is this process reusing compiles"."""
        from ..obs.metrics import GLOBAL

        return {
            "engine": type(self).__name__,
            "done": self.is_done(),
            "state_count": self.state_count(),
            "unique_state_count": self.unique_state_count(),
            "max_depth": self.max_depth(),
            "table_load_factor": 0.0,
            "program_cache_hits": int(GLOBAL.get("program_cache_hits", 0)),
            "program_cache_misses": int(
                GLOBAL.get("program_cache_misses", 0)
            ),
            # Compile observability (wave_common.cached_program, docs/
            # OBSERVABILITY.md "Compile events"): accumulated first-call
            # compile wall time and the storm counter — included on
            # every engine so one scrape answers "is this process
            # recompiling, and is it thrashing".
            "compile_sec_total": round(
                float(GLOBAL.get("compile_sec_total", 0.0)), 4
            ),
            "recompile_storms": int(GLOBAL.get("recompile_storms", 0)),
        }

    # --- shared functionality -----------------------------------------------

    def discovery(self, name: str) -> Optional[Path]:
        return self.discoveries().get(name)

    def try_discovery(self, name: str) -> Optional[Path]:
        """Like :meth:`discovery`, but never blocks on a still-running
        checker (device engines override this; the Explorer's status view
        polls it mid-run)."""
        return self.discovery(name)

    def discovery_classification(self, name: str) -> str:
        prop = self._model.get_property(name)
        return "example" if prop.expectation is Expectation.SOMETIMES else "counterexample"

    def _report_data(self, start: float, done: bool) -> ReportData:
        return ReportData(
            total_states=self.state_count(),
            unique_states=self.unique_state_count(),
            max_depth=self.max_depth(),
            duration=time.monotonic() - start,
            done=done,
        )

    def _report_final(self, reporter: Reporter, start: float) -> None:
        reporter.report_checking(self._report_data(start, done=True))
        discoveries = {
            name: ReportDiscovery(path, self.discovery_classification(name))
            for name, path in self.discoveries().items()
        }
        reporter.report_discoveries(self._model, discoveries)

    def report(self, reporter: Reporter) -> "Checker":
        """Reference: src/checker.rs:412-452."""
        start = time.monotonic()
        while not self.is_done():
            reporter.report_checking(self._report_data(start, done=False))
            time.sleep(reporter.delay())
        self._report_final(reporter, start)
        return self

    def join_and_report(self, reporter: Reporter) -> "Checker":
        """Join while reporting; final timing is accurate rather than rounded
        to the polling interval.  Reference: src/checker.rs:351-409."""
        import threading

        start = time.monotonic()
        stop = threading.Event()

        def poll():
            while not stop.is_set() and not self.is_done():
                reporter.report_checking(self._report_data(start, done=False))
                stop.wait(reporter.delay())

        poller = threading.Thread(target=poll, daemon=True)
        poller.start()
        self.join()
        stop.set()
        poller.join()
        self._report_final(reporter, start)
        return self

    # --- assertion helpers (src/checker.rs:468-577) -------------------------

    def assert_properties(self) -> None:
        for p in self._model.properties():
            if p.expectation is Expectation.SOMETIMES:
                self.assert_any_discovery(p.name)
            else:
                self.assert_no_discovery(p.name)

    def assert_any_discovery(self, name: str) -> Path:
        found = self.discovery(name)
        if found is not None:
            return found
        assert self.is_done(), (
            f'Discovery for "{name}" not found, but model checking is incomplete.'
        )
        raise AssertionError(f'Discovery for "{name}" not found.')

    def assert_no_discovery(self, name: str) -> None:
        found = self.discovery(name)
        if found is not None:
            raise AssertionError(
                f'Unexpected "{name}" {self.discovery_classification(name)} '
                f"{found}Last state: {found.last_state()!r}\n"
            )
        assert self.is_done(), (
            f'Discovery for "{name}" not found, but model checking is incomplete.'
        )

    def assert_discovery(self, name: str, actions: List[Any]) -> None:
        """Re-execute ``actions`` and validate they constitute a genuine
        discovery per the property's semantics.  Reference:
        src/checker.rs:521-577."""
        additional_info: List[str] = []
        found = self.assert_any_discovery(name)
        model = self._model
        for init_state in model.init_states():
            path = Path.from_actions(model, init_state, actions)
            if path is None:
                continue
            prop = model.get_property(name)
            if prop.expectation is Expectation.ALWAYS:
                if not prop.condition(model, path.last_state()):
                    return
            elif prop.expectation is Expectation.EVENTUALLY:
                states = path.into_states()
                is_liveness_satisfied = any(
                    prop.condition(model, s) for s in states
                )
                acts: List[Any] = []
                model.actions(states[-1], acts)
                is_path_terminal = not acts
                if not is_liveness_satisfied and is_path_terminal:
                    return
                if is_liveness_satisfied:
                    additional_info.append(
                        "incorrect counterexample satisfies eventually property"
                    )
                if not is_path_terminal:
                    additional_info.append("incorrect counterexample is nonterminal")
            else:  # SOMETIMES
                if prop.condition(model, path.last_state()):
                    return
        extra = f" ({'; '.join(additional_info)})" if additional_info else ""
        raise AssertionError(
            f'Invalid discovery for "{name}"{extra}, but a valid one was found. '
            f"found={found.into_actions()!r}"
        )
