"""Host graph-search checker engines (BFS and DFS).

Reference: src/checker/bfs.rs and src/checker/dfs.rs.  The two engines share
one worker skeleton here, parameterized by the three points where they
genuinely differ (the reference deliberately keeps them unfactored pending
DPOR work — src/checker/bfs.rs:17-18):

- queue discipline: BFS pops from the back and pushes successors to the
  front (FIFO level order); DFS pushes to the back (LIFO).
- discovery representation: BFS stores one fingerprint per discovery and
  reconstructs the path by walking a predecessor map
  (src/checker/bfs.rs:380-409); DFS jobs carry their full fingerprint trail.
- symmetry reduction is honored only by DFS (BFS ignores the option, noted
  in SURVEY §2.1): dedup keys on the canonicalized state's fingerprint while
  the path continues with the original state (src/checker/dfs.rs:309-334).

Eventually-property machinery: one bit per `eventually` property travels
with each job; a bit is cleared when the property's condition holds at a
state along the path; bits remaining at a terminal state are
counterexamples.  The reference's two documented false negatives (cycles
treated as DAG joins; ebits excluded from the dedup fingerprint) are
reproduced intentionally so discovery sets match (src/checker/bfs.rs:295-315).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .has_discoveries import HasDiscoveries
from .job_market import JobMarket
from .model import Expectation
from .path import Path
from .checker import Checker

BLOCK_SIZE = 1500  # states between market interactions (src/checker/bfs.rs:130)


class _NativeGenerated:
    """Mapping facade over the C++ lock-striped visited set (ops/native.py).

    Used by the graph engines at ``threads > 1``: `insert_if_absent` is one
    GIL-releasing ctypes call contending on a per-stripe C++ mutex — the
    DashMap analog — instead of a Python-level lock serializing every
    worker.  (At ``threads == 1`` a plain dict wins: a dict op is ~50 ns
    against a ~1 µs ctypes round trip.)  Parent None <-> native parent 0
    (fingerprints themselves are nonzero, so 0 is unambiguous).
    """

    __slots__ = ("_set",)

    def __init__(self):
        from ..ops.native import NativeFpSet

        self._set = NativeFpSet()

    def insert_if_absent(self, fp, parent) -> bool:
        return self._set.insert(fp, 0 if parent is None else parent)

    def setdefault(self, fp, parent) -> None:
        self.insert_if_absent(fp, parent)

    def __contains__(self, fp) -> bool:
        return fp in self._set

    def __getitem__(self, fp):
        p = self._set.parent(fp)
        if p is None:
            raise KeyError(fp)
        return p or None

    def __len__(self) -> int:
        return len(self._set)


class GraphChecker(Checker):
    """Shared implementation of the BFS and DFS checkers."""

    def __init__(self, options, dfs: bool):
        super().__init__(options.model)
        self._dfs = dfs
        self._options = options
        # Per reference behavior BFS ignores the symmetry option (it is only
        # read in DFS spawn); see SURVEY §2.1 / src/checker/bfs.rs.
        self._symmetry = options._symmetry if dfs else None
        self._properties = self._model.properties()
        self._visitor = options._visitor
        self._finish_when: HasDiscoveries = options._finish_when
        self._target_state_count = options._target_state_count
        self._target_max_depth = options._target_max_depth
        thread_count = options._thread_count

        model = self._model
        init_states = [s for s in model.init_states() if model.within_boundary(s)]
        self._state_count = len(init_states)
        self._max_depth = 0
        self._count_lock = threading.Lock()

        # BFS: fp -> Optional[parent fp] (predecessor tree).  DFS: set of fps.
        from ..ops import native as _native

        if thread_count > 1 and _native.available():
            self._generated = _NativeGenerated()
            self._insert_if_absent = self._generated.insert_if_absent
        else:
            self._generated: Dict[int, Optional[int]] = {}
            self._insert_if_absent = self._dict_insert_if_absent
        self._gen_lock = threading.Lock()
        for s in init_states:
            if self._symmetry is not None:
                self._generated.setdefault(
                    model.fingerprint(self._symmetry(s)), None
                )
            else:
                self._generated.setdefault(model.fingerprint(s), None)

        ebits = frozenset(
            i
            for i, p in enumerate(self._properties)
            if p.expectation is Expectation.EVENTUALLY
        )
        pending = deque()
        for s in init_states:
            fp = model.fingerprint(s)
            # DFS jobs carry their full fingerprint trail (reference:
            # src/checker/dfs.rs:31) — represented as cons cells so pushing a
            # successor is O(1) instead of an O(depth) copy.
            trail = (fp, None) if dfs else fp
            pending.append((s, trail, ebits, 1))

        # name -> fp (BFS) | trail list (DFS); first writer wins, races fine
        # (src/checker/bfs.rs:243).
        self._discoveries: Dict[str, Any] = {}

        close_at = (
            time.monotonic() + options._timeout if options._timeout is not None else None
        )
        self._close_at = close_at
        self._market: JobMarket = JobMarket(thread_count, close_at)
        self._market.push(pending)

        self._errors: List[BaseException] = []
        self._handles: List[threading.Thread] = []
        for t in range(thread_count):
            th = threading.Thread(
                target=self._worker, name=f"checker-{t}", daemon=True
            )
            self._handles.append(th)
        for th in self._handles:
            th.start()

    def _dict_insert_if_absent(self, fp, parent) -> bool:
        with self._gen_lock:
            if fp in self._generated:
                return False
            self._generated[fp] = parent
            return True

    # --- worker loop (src/checker/bfs.rs:103-161) ---------------------------

    def _worker(self) -> None:
        try:
            pending: deque = deque()
            while True:
                if not pending:
                    pending = self._market.pop()
                    if not pending:
                        return
                self._check_block(pending, BLOCK_SIZE)
                if self._stop_requested.is_set():
                    return
                if (
                    self._close_at is not None
                    and time.monotonic() >= self._close_at
                ):
                    return
                if self._finish_when.matches(
                    frozenset(self._discoveries), self._properties
                ):
                    return
                if (
                    self._target_state_count is not None
                    and self._target_state_count <= self._state_count
                ):
                    return
                if len(pending) > 1 and len(self._handles) > 1:
                    self._market.split_and_push(pending)
        except BaseException as e:  # propagate at join (src/checker/bfs.rs:479-488)
            self._errors.append(e)
        finally:
            self._market.worker_done()

    def _check_block(self, pending: deque, max_count: int) -> None:
        model = self._model
        properties = self._properties
        dfs = self._dfs
        symmetry = self._symmetry
        insert_if_absent = self._insert_if_absent
        discoveries = self._discoveries
        target_max_depth = self._target_max_depth
        local_state_count = 0
        local_max_depth = self._max_depth

        try:
            while True:
                if max_count == 0:
                    return
                max_count -= 1
                if not pending:
                    return
                if local_state_count >= 64:
                    # Flush periodically (not per 1500-state block) so
                    # concurrent reporters see a live view without taking the
                    # lock on every evaluated state.
                    with self._count_lock:
                        self._state_count += local_state_count
                        if local_max_depth > self._max_depth:
                            self._max_depth = local_max_depth
                    local_state_count = 0
                state, trail, ebits, depth = pending.pop()
                state_fp = trail[0] if dfs else trail

                if depth > local_max_depth:
                    local_max_depth = depth

                if target_max_depth is not None and depth >= target_max_depth:
                    continue

                if self._visitor is not None:
                    self._visitor.visit(model, self._reconstruct(trail))

                # Property evaluation (src/checker/bfs.rs:230-281).
                is_awaiting_discoveries = False
                for i, prop in enumerate(properties):
                    if prop.name in discoveries:
                        continue
                    if prop.expectation is Expectation.ALWAYS:
                        if not prop.condition(model, state):
                            discoveries.setdefault(prop.name, trail)
                        else:
                            is_awaiting_discoveries = True
                    elif prop.expectation is Expectation.SOMETIMES:
                        if prop.condition(model, state):
                            discoveries.setdefault(prop.name, trail)
                        else:
                            is_awaiting_discoveries = True
                    else:  # EVENTUALLY: only discovered at terminal states.
                        is_awaiting_discoveries = True
                        if prop.condition(model, state):
                            ebits = ebits - {i}
                if not is_awaiting_discoveries:
                    return

                # Expand successors (src/checker/bfs.rs:283-325).
                is_terminal = True
                actions: List[Any] = []
                model.actions(state, actions)
                for action in actions:
                    next_state = model.next_state(state, action)
                    if next_state is None:
                        continue
                    if not model.within_boundary(next_state):
                        continue
                    local_state_count += 1

                    if symmetry is not None:
                        rep_fp = model.fingerprint(symmetry(next_state))
                        if not insert_if_absent(rep_fp, None):
                            is_terminal = False
                            continue
                        # Continue the path with the pre-canonicalized state
                        # (src/checker/dfs.rs:315-318).
                        next_fp = model.fingerprint(next_state)
                    else:
                        next_fp = model.fingerprint(next_state)
                        if not insert_if_absent(
                            next_fp, None if dfs else state_fp
                        ):
                            is_terminal = False
                            continue

                    is_terminal = False
                    next_trail = (next_fp, trail) if dfs else next_fp
                    job = (next_state, next_trail, ebits, depth + 1)
                    if dfs:
                        pending.append(job)
                    else:
                        pending.appendleft(job)

                if is_terminal:
                    for i, prop in enumerate(properties):
                        if i in ebits:
                            discoveries.setdefault(prop.name, trail)
        finally:
            with self._count_lock:
                self._state_count += local_state_count
                if local_max_depth > self._max_depth:
                    self._max_depth = local_max_depth

    # --- Checker surface ----------------------------------------------------

    def _reconstruct(self, trail) -> Path:
        if self._dfs:
            fps: deque = deque()
            cell = trail
            while cell is not None:
                fps.appendleft(cell[0])
                cell = cell[1]
            return Path.from_fingerprints(self._model, list(fps))
        # BFS: walk the predecessor map back to a root
        # (src/checker/bfs.rs:380-409).
        fps: deque = deque()
        next_fp: Optional[int] = trail
        while next_fp is not None and next_fp in self._generated:
            fps.appendleft(next_fp)
            next_fp = self._generated[next_fp]
        return Path.from_fingerprints(self._model, list(fps))

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        return len(self._generated)

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: self._reconstruct(trail)
            for name, trail in list(self._discoveries.items())
        }

    def handles(self) -> List[threading.Thread]:
        return self._handles

    def request_stop(self) -> None:
        # Busy workers see the event after their current block; idle
        # workers blocked in market.pop() need the market closed to wake.
        super().request_stop()
        self._market.close()

    def is_done(self) -> bool:
        return self._market.is_closed or len(self._discoveries) == len(
            self._properties
        )

    def join(self) -> "GraphChecker":
        for h in self._handles:
            h.join()
        if self._errors:
            raise self._errors[0]
        return self
