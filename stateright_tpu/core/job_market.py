"""Work-sharing market coordinating host checker threads.

Reference: src/job_market.rs.  Semantics mirrored exactly:

- ``open_count`` starts at the worker count; a worker idling inside ``pop``
  decrements it, and the last idle worker closes the market (distributed
  termination detection, src/job_market.rs:100-111).
- Any worker exiting — normal return *or* exception — closes the market and
  clears outstanding batches (the reference does this via ``Drop``,
  src/job_market.rs:24-36), which is how early-exit and panic shutdown
  propagate to sibling threads.
- ``split_and_push`` hands ``1 + min(idle, len)`` pieces off the back of the
  worker's deque to idle workers (src/job_market.rs:140-167).
- An optional deadline closes the market when reached (src/job_market.rs:64-77).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Generic, List, Optional, TypeVar

T = TypeVar("T")


class JobMarket(Generic[T]):
    def __init__(self, thread_count: int, close_at: Optional[float] = None):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._batches: List[Deque[T]] = []
        self._open = True
        self._thread_count = thread_count
        self._open_count = thread_count
        self._close_at = close_at

    def push(self, jobs: Deque[T]) -> None:
        with self._cond:
            if not self._open:
                return
            self._batches.append(jobs)
            self._cond.notify()

    def pop(self) -> Deque[T]:
        """Pop a batch; empty deque means no more jobs are coming."""
        with self._cond:
            if not self._open:
                return deque()
            while True:
                if self._close_at is not None and time.monotonic() >= self._close_at:
                    self._open = False
                    self._cond.notify_all()
                    return deque()
                if self._batches:
                    return self._batches.pop()
                self._open_count -= 1
                if self._open_count == 0:
                    self._open = False
                    self._cond.notify_all()
                    return deque()
                if not self._open:
                    # Market closed while we were working; drain out.
                    self._cond.notify_all()
                    return deque()
                if self._close_at is not None:
                    timeout = max(0.0, self._close_at - time.monotonic())
                    self._cond.wait(timeout=min(timeout, 0.25))
                else:
                    self._cond.wait()
                self._open_count += 1

    def split_and_push(self, jobs: Deque[T]) -> None:
        with self._cond:
            if not self._open:
                jobs.clear()
                return
            pieces = 1 + min(self._thread_count - self._open_count, len(jobs))
            size = len(jobs) // pieces
            if size == 0:
                return
            for _ in range(pieces - 1):
                batch: Deque[T] = deque()
                for _ in range(size):
                    batch.append(jobs.pop())
                batch.reverse()
                if batch:
                    self._batches.append(batch)
                    self._cond.notify()

    def worker_done(self) -> None:
        """A worker exited (normally or exceptionally).  The reference models
        this via ``Drop`` on the broker clone: close the market, discard
        outstanding work, wake everyone (src/job_market.rs:24-36)."""
        with self._cond:
            self._open = False
            self._batches.clear()
            self._open_count = max(0, self._open_count - 1)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._open = False
            self._cond.notify_all()

    @property
    def is_closed(self) -> bool:
        with self._lock:
            return not self._open and not self._batches and self._open_count == 0
