"""Paths (traces / behaviors) through a model's state graph.

Reference: src/checker/path.rs.  A path is a sequence of (state, action)
pairs; it is reconstructed from a chain of fingerprints by re-executing the
model (the TLC technique), or validated from a user-supplied action list.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple


class NondeterminismError(RuntimeError):
    """Raised when a fingerprint chain cannot be re-executed.

    Reference: the diagnostic panic in src/checker/path.rs:36-55,70-89.
    """


_NONDET_HINT = (
    "This usually happens when the model varies given the same inputs — "
    "e.g. it reads untracked external state (files, clocks, randomness) or "
    "iterates an unordered container nondeterministically."
)


class Path:
    """``state --action--> state ... --action--> state``.

    Reference: src/checker/path.rs:16.
    """

    __slots__ = ("_steps",)

    def __init__(self, steps: Sequence[Tuple[Any, Optional[Any]]]):
        self._steps = tuple(steps)

    @staticmethod
    def from_fingerprints(model, fingerprints: Sequence[int]) -> "Path":
        """Re-execute ``model`` along a fingerprint chain.

        Reference: src/checker/path.rs:20-97.
        """
        fps = list(fingerprints)
        if not fps:
            raise NondeterminismError("empty path is invalid")
        init_fp = fps[0]
        last_state = None
        for s in model.init_states():
            if model.fingerprint(s) == init_fp:
                last_state = s
                break
        if last_state is None:
            raise NondeterminismError(
                f"No init state has the expected fingerprint ({init_fp}). "
                + _NONDET_HINT
            )
        steps: List[Tuple[Any, Optional[Any]]] = []
        for i, next_fp in enumerate(fps[1:]):
            found = None
            for action, state in model.next_steps(last_state):
                if model.fingerprint(state) == next_fp:
                    found = (action, state)
                    break
            if found is None:
                raise NondeterminismError(
                    f"{i + 1} previous state(s) reconstructed, but no successor "
                    f"has the next fingerprint ({next_fp}). " + _NONDET_HINT
                )
            steps.append((last_state, found[0]))
            last_state = found[1]
        steps.append((last_state, None))
        return Path(steps)

    @staticmethod
    def from_actions(model, init_state, actions) -> Optional["Path"]:
        """Build a path by following ``actions`` from ``init_state``; ``None``
        if unreachable.  Reference: src/checker/path.rs:101-131."""
        if init_state not in model.init_states():
            return None
        steps: List[Tuple[Any, Optional[Any]]] = []
        prev_state = init_state
        for action in actions:
            found = None
            for a, s in model.next_steps(prev_state):
                if a == action:
                    found = (a, s)
                    break
            if found is None:
                return None
            steps.append((prev_state, found[0]))
            prev_state = found[1]
        steps.append((prev_state, None))
        return Path(steps)

    @staticmethod
    def final_state(model, fingerprints: Sequence[int]) -> Optional[Any]:
        """Reference: src/checker/path.rs:134-165."""
        fps = list(fingerprints)
        if not fps:
            return None
        state = None
        for s in model.init_states():
            if model.fingerprint(s) == fps[0]:
                state = s
                break
        if state is None:
            return None
        for next_fp in fps[1:]:
            state = next(
                (s for s in model.next_states(state) if model.fingerprint(s) == next_fp),
                None,
            )
            if state is None:
                return None
        return state

    def last_state(self) -> Any:
        return self._steps[-1][0]

    def into_states(self) -> List[Any]:
        return [s for (s, _a) in self._steps]

    def into_actions(self) -> List[Any]:
        return [a for (_s, a) in self._steps if a is not None]

    def into_vec(self) -> List[Tuple[Any, Optional[Any]]]:
        return list(self._steps)

    def encode(self, model) -> str:
        """`/`-joined fingerprints (Explorer URLs, reports).
        Reference: src/checker/path.rs:189-198."""
        return "/".join(str(model.fingerprint(s)) for (s, _a) in self._steps)

    def __len__(self) -> int:
        return len(self._steps)

    def __iter__(self):
        return iter(self._steps)

    def __getitem__(self, i):
        return self._steps[i]

    def __eq__(self, other) -> bool:
        return isinstance(other, Path) and self._steps == other._steps

    def __hash__(self) -> int:
        return hash(self._steps)

    def __repr__(self) -> str:
        return f"Path({list(self._steps)!r})"

    def __str__(self) -> str:
        # Reference Display impl: src/checker/path.rs:207-221.
        lines = [f"Path[{len(self._steps) - 1}]:"]
        for _state, action in self._steps:
            if action is not None:
                lines.append(f"- {action!r}")
        return "\n".join(lines) + "\n"
