"""Symmetry reduction: representatives and rewrite plans.

Reference: src/checker/representative.rs, src/checker/rewrite.rs,
src/checker/rewrite_plan.rs.  A state's ``representative()`` maps it to a
canonical member of its symmetry equivalence class; the DFS checker dedups
on the representative's fingerprint while continuing paths with original
states (src/checker/dfs.rs:309-334).

``RewritePlan.from_values_to_sort`` builds a permutation by stable-sorting
values (e.g. per-actor states); ``rewrite(i)`` maps an old index to its new
index, and ``reindex`` permutes an indexed collection while recursively
rewriting the elements (src/checker/rewrite_plan.rs:81-123).

This module is the HOST side (used by spawn_dfs); the device analog —
sort-of-record-blocks canonicalization kernels over packed state rows,
used by spawn_tpu / spawn_tpu_sharded — lives in ``parallel/canon.py``
(docs/SYMMETRY.md).

Where the reference dispatches on the ``Rewrite<Id>`` trait to renumber
``Id`` values nested inside state, Python has no type-directed dispatch, so
``rewrite_value`` recurses structurally and rewrites values of the marker
type (``stateright_tpu.actor.Id`` by default); data that should not be
rewritten simply doesn't use the marker type.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence


class Representative:
    """Duck-typed marker: states implementing ``representative()`` can use
    ``CheckerBuilder.symmetry()``.  Reference: src/checker/representative.rs."""

    def representative(self):
        raise NotImplementedError


class RewritePlan:
    __slots__ = ("_map", "_inverse", "_rewritten_type")

    def __init__(self, mapping: Sequence[int], rewritten_type: Optional[type] = None):
        """``mapping[old_index] = new_index``."""
        self._map = list(mapping)
        inverse = [0] * len(self._map)
        for old_i, new_i in enumerate(self._map):
            inverse[new_i] = old_i
        self._inverse = inverse  # inverse[new_index] = old_index
        self._rewritten_type = rewritten_type

    @staticmethod
    def from_values_to_sort(
        values: Sequence[Any], rewritten_type: Optional[type] = None
    ) -> "RewritePlan":
        """Build the permutation that stable-sorts ``values``.
        Reference: src/checker/rewrite_plan.rs:81-106."""
        order = sorted(range(len(values)), key=lambda i: values[i])
        mapping = [0] * len(values)
        for new_i, old_i in enumerate(order):
            mapping[old_i] = new_i
        return RewritePlan(mapping, rewritten_type)

    def rewrite(self, x: int) -> int:
        return self._map[int(x)]

    def reindex(self, indexed: Sequence[Any], rewrite_elems: bool = True) -> List[Any]:
        """Permute ``indexed`` so the value at old index i lands at new index
        ``mapping[i]``, recursively rewriting elements.
        Reference: src/checker/rewrite_plan.rs:110-123."""
        if rewrite_elems:
            return [rewrite_value(indexed[old_i], self) for old_i in self._inverse]
        return [indexed[old_i] for old_i in self._inverse]

    def __len__(self) -> int:
        return len(self._map)

    def __repr__(self) -> str:
        return f"RewritePlan({self._map})"


def rewrite_value(value: Any, plan: RewritePlan) -> Any:
    """Structurally rewrite index-like marker values nested inside ``value``.

    The analog of the reference's blanket ``Rewrite`` impls for scalars,
    tuples, collections, and maps (src/checker/rewrite.rs).
    """
    rt = plan._rewritten_type
    if rt is None:
        from ..actor.ids import Id as rt  # default marker type

    t = type(value)
    if t is rt:
        return t(plan.rewrite(value))
    if value is None or t in (bool, int, float, str, bytes):
        return value
    if t is tuple or t is list:
        return t(rewrite_value(v, plan) for v in value)
    if t is frozenset or t is set:
        return t(rewrite_value(v, plan) for v in value)
    if t is dict:
        return {
            rewrite_value(k, plan): rewrite_value(v, plan) for k, v in value.items()
        }
    from ..utils.dense_nat_map import DenseNatMap

    if t is DenseNatMap:
        # Reference impl for DenseNatMap permutes entries by the plan and
        # rewrites the values (src/util/densenatmap.rs Rewrite impl).
        return DenseNatMap(plan.reindex(value.values(), rewrite_elems=True))
    rw = getattr(value, "rewrite", None)
    if rw is not None:
        return rw(plan)
    if dataclasses.is_dataclass(value):
        return t(
            **{
                f.name: rewrite_value(getattr(value, f.name), plan)
                for f in dataclasses.fields(value)
            }
        )
    import enum

    if isinstance(value, enum.Enum):
        return value
    if isinstance(value, int):  # bools/int subclasses other than the marker
        return value
    # Refusing to guess is load-bearing: silently passing a container of Ids
    # through unrewritten would make symmetry reduction unsound (two
    # non-equivalent states could share a representative and the checker
    # would silently prune reachable states).  The reference enforces this
    # statically via the Rewrite<Id> bound (src/actor/model_state.rs:176-184).
    raise TypeError(
        f"cannot rewrite {type(value).__name__!r} for symmetry reduction; "
        "define a rewrite(plan) method on it"
    )
