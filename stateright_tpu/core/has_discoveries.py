"""Early-exit policies for checker runs.

Reference: src/has_discoveries.rs.
"""

from __future__ import annotations

from typing import FrozenSet, Iterable, Sequence

from .model import Property


class HasDiscoveries:
    """When to finish a checker run."""

    _kind: str
    _names: FrozenSet[str]

    def __init__(self, kind: str, names: Iterable[str] = ()):
        self._kind = kind
        self._names = frozenset(names)

    def matches(self, discoveries: FrozenSet[str], properties: Sequence[Property]) -> bool:
        k = self._kind
        if k == "all":
            return len(discoveries) == len(properties)
        if k == "any":
            return bool(discoveries)
        if k == "any_failures":
            return any(
                p.name in discoveries
                for p in properties
                if p.expectation.discovery_is_failure
            )
        if k == "all_failures":
            return all(
                p.name in discoveries
                for p in properties
                if p.expectation.discovery_is_failure
            )
        if k == "all_of":
            return self._names <= discoveries
        if k == "any_of":
            return bool(self._names & discoveries)
        raise ValueError(k)

    @staticmethod
    def all_of(names: Iterable[str]) -> "HasDiscoveries":
        return HasDiscoveries("all_of", names)

    @staticmethod
    def any_of(names: Iterable[str]) -> "HasDiscoveries":
        return HasDiscoveries("any_of", names)

    def __repr__(self) -> str:
        if self._names:
            return f"HasDiscoveries.{self._kind}({sorted(self._names)})"
        return f"HasDiscoveries.{self._kind.upper()}"


HasDiscoveries.ALL = HasDiscoveries("all")
HasDiscoveries.ANY = HasDiscoveries("any")
HasDiscoveries.ANY_FAILURES = HasDiscoveries("any_failures")
HasDiscoveries.ALL_FAILURES = HasDiscoveries("all_failures")
