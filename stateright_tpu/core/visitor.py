"""Checker visitors — the primary test instrumentation.

Reference: src/checker/visitor.rs.  A visitor is applied to the ``Path`` of
every evaluated state.  Plain callables are accepted wherever a visitor is.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Set

from .path import Path


class CheckerVisitor:
    def visit(self, model, path: Path) -> None:
        raise NotImplementedError


class _FnVisitor(CheckerVisitor):
    def __init__(self, fn: Callable[[Path], None]):
        self._fn = fn

    def visit(self, model, path: Path) -> None:
        self._fn(path)


def as_visitor(v) -> CheckerVisitor:
    if isinstance(v, CheckerVisitor):
        return v
    if callable(v):
        return _FnVisitor(v)
    raise TypeError(f"not a visitor: {v!r}")


class PathRecorder(CheckerVisitor):
    """Records the set of visited paths.  Reference: src/checker/visitor.rs:47-73."""

    def __init__(self):
        self._lock = threading.Lock()
        self._paths: Set[Path] = set()

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self._paths.add(path)

    @staticmethod
    def new_with_accessor():
        recorder = PathRecorder()

        def accessor() -> Set[Path]:
            with recorder._lock:
                return set(recorder._paths)

        return recorder, accessor


class StateRecorder(CheckerVisitor):
    """Records evaluated states in visit order.  Reference: src/checker/visitor.rs:87-111."""

    def __init__(self):
        self._lock = threading.Lock()
        self._states: List[Any] = []

    def visit(self, model, path: Path) -> None:
        with self._lock:
            self._states.append(path.last_state())

    @staticmethod
    def new_with_accessor():
        recorder = StateRecorder()

        def accessor() -> List[Any]:
            with recorder._lock:
                return list(recorder._states)

        return recorder, accessor
