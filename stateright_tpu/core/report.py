"""Progress reporting.

Reference: src/report.rs.  ``WriteReporter`` reproduces the reference's text
protocol (``Checking. states=… unique=… depth=…`` / ``Done. … sec=…`` /
``Discovered "name" example Path[n]: …`` + ``Fingerprint path: a/b/c``),
which doubles as the benchmark measurement surface (bench greps ``sec=``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, TextIO


@dataclass
class ReportData:
    total_states: int
    unique_states: int
    max_depth: int
    duration: float  # seconds
    done: bool


@dataclass
class ReportDiscovery:
    path: "Path"
    classification: str  # "example" | "counterexample"


class Reporter:
    def report_checking(self, data: ReportData) -> None:
        raise NotImplementedError

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        raise NotImplementedError

    def delay(self) -> float:
        return 1.0


class WriteReporter(Reporter):
    def __init__(self, writer: TextIO, delay: float = 1.0):
        self._writer = writer
        self._delay = delay

    def delay(self) -> float:
        return self._delay

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self._writer.write(
                f"Done. states={data.total_states}, unique={data.unique_states}, "
                f"depth={data.max_depth}, sec={int(data.duration)}\n"
            )
        else:
            self._writer.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}\n"
            )

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        for name in sorted(discoveries):
            d = discoveries[name]
            self._writer.write(f'Discovered "{name}" {d.classification} {d.path}')
            self._writer.write(f"Fingerprint path: {d.path.encode(model)}\n")
