"""Progress reporting.

Reference: src/report.rs.  ``WriteReporter`` reproduces the reference's text
protocol (``Checking. states=… unique=… depth=…`` / ``Done. … sec=…`` /
``Discovered "name" example Path[n]: …`` + ``Fingerprint path: a/b/c``),
which doubles as the benchmark measurement surface (bench greps ``sec=``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, TextIO


@dataclass
class ReportData:
    total_states: int
    unique_states: int
    max_depth: int
    duration: float  # seconds
    done: bool


@dataclass
class ReportDiscovery:
    path: "Path"
    classification: str  # "example" | "counterexample"


class Reporter:
    def report_checking(self, data: ReportData) -> None:
        raise NotImplementedError

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        raise NotImplementedError

    def delay(self) -> float:
        return 1.0


class JournalReporter(Reporter):
    """Reporter writing the checking progress stream into a telemetry
    :class:`~stateright_tpu.runtime.journal.Journal` instead of a text
    stream — the machine-readable sibling of :class:`WriteReporter`, so a
    supervised run's artifact carries the same data the reference's text
    protocol would print (``progress`` events while checking, one
    ``done`` event, one ``discovery`` event per discovery)."""

    def __init__(self, journal, delay: float = 1.0):
        from ..runtime.journal import as_journal

        self._journal = as_journal(journal)
        self._delay = delay

    def delay(self) -> float:
        return self._delay

    def report_checking(self, data: ReportData) -> None:
        self._journal.append(
            "done" if data.done else "progress",
            states=data.total_states,
            unique=data.unique_states,
            depth=data.max_depth,
            sec=round(data.duration, 3),
        )

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        for name in sorted(discoveries):
            d = discoveries[name]
            self._journal.append(
                "discovery",
                name=name,
                classification=d.classification,
                fingerprint_path=d.path.encode(model),
            )


class WriteReporter(Reporter):
    def __init__(self, writer: TextIO, delay: float = 1.0):
        self._writer = writer
        self._delay = delay

    def delay(self) -> float:
        return self._delay

    def report_checking(self, data: ReportData) -> None:
        if data.done:
            self._writer.write(
                f"Done. states={data.total_states}, unique={data.unique_states}, "
                f"depth={data.max_depth}, sec={int(data.duration)}\n"
            )
        else:
            self._writer.write(
                f"Checking. states={data.total_states}, "
                f"unique={data.unique_states}, depth={data.max_depth}\n"
            )

    def report_discoveries(self, model, discoveries: Dict[str, ReportDiscovery]) -> None:
        for name in sorted(discoveries):
            d = discoveries[name]
            self._writer.write(f'Discovered "{name}" {d.classification} {d.path}')
            self._writer.write(f"Fingerprint path: {d.path.encode(model)}\n")
