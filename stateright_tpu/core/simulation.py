"""Stochastic (Monte-Carlo) checking by repeated random trace walks.

Reference: src/checker/simulation.rs.  Each thread repeatedly walks a trace
from a chosen init state to a terminal state / cycle / boundary, choosing
among enabled actions through a pluggable :class:`Chooser`; properties are
evaluated at every visited state exactly as in the graph engines, and
leftover eventually-bits at the end of a trace become counterexamples
(a cycle or boundary exit ends the trace, src/checker/simulation.rs:455-465
and 393-396).  There is no global dedup: ``unique_state_count`` equals
``state_count`` (src/checker/simulation.rs:413-417).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

from .checker import Checker
from .model import Expectation
from .path import Path


class Chooser:
    """Chooses transitions during a simulation run.

    Reference: the ``Chooser`` trait, src/checker/simulation.rs:19-39.
    """

    def new_state(self, seed: int) -> Any:
        raise NotImplementedError

    def choose_initial_state(self, chooser_state, initial_states: List[Any]) -> int:
        raise NotImplementedError

    def choose_action(self, chooser_state, current_state, actions: List[Any]) -> int:
        raise NotImplementedError


class UniformChooser(Chooser):
    """Uniformly random choices from a seeded RNG.

    Reference: src/checker/simulation.rs:40-79.
    """

    def new_state(self, seed: int) -> random.Random:
        return random.Random(seed)

    def choose_initial_state(self, rng, initial_states):
        return rng.randrange(len(initial_states))

    def choose_action(self, rng, _current_state, actions):
        return rng.randrange(len(actions))


class SimulationChecker(Checker):
    def __init__(self, options, seed: int, chooser: Chooser):
        super().__init__(options.model)
        self._options = options
        self._chooser = chooser
        self._symmetry = options._symmetry
        self._properties = self._model.properties()
        self._state_count = 0
        self._max_depth = 0
        self._count_lock = threading.Lock()
        # name -> full fingerprint path of the discovery trace.
        self._discoveries: Dict[str, List[int]] = {}
        self._shutdown = threading.Event()
        self._errors: List[BaseException] = []

        deadline = (
            time.monotonic() + options._timeout
            if options._timeout is not None
            else None
        )
        self._deadline = deadline

        self._handles: List[threading.Thread] = []
        for t in range(options._thread_count):
            th = threading.Thread(
                target=self._worker, args=(seed + t,), name=f"checker-{t}",
                daemon=True,
            )
            self._handles.append(th)
        for th in self._handles:
            th.start()

    # --- worker (src/checker/simulation.rs:138-200) --------------------------

    def _worker(self, thread_seed: int) -> None:
        try:
            rng = random.Random(thread_seed)
            trace_seed = thread_seed
            while not self._shutdown.is_set():
                if (
                    self._deadline is not None
                    and time.monotonic() >= self._deadline
                ):
                    return
                self._check_trace_from_initial(trace_seed)
                if self._options._finish_when.matches(
                    frozenset(self._discoveries), self._properties
                ):
                    return
                if (
                    self._options._target_state_count is not None
                    and self._options._target_state_count <= self._state_count
                ):
                    return
                trace_seed = rng.getrandbits(64)
        except BaseException as e:
            self._errors.append(e)
            self._shutdown.set()

    # --- one trace (src/checker/simulation.rs:213-397) -----------------------

    def _check_trace_from_initial(self, seed: int) -> None:
        model = self._model
        properties = self._properties
        chooser = self._chooser
        chooser_state = chooser.new_state(seed)
        visitor = self._options._visitor
        target_max_depth = self._options._target_max_depth
        symmetry = self._symmetry

        initial_states = list(model.init_states())
        index = chooser.choose_initial_state(chooser_state, initial_states)
        state = initial_states[index]

        fingerprint_path: List[int] = []
        generated = set()
        ebits = {
            i
            for i, p in enumerate(properties)
            if p.expectation is Expectation.EVENTUALLY
        }

        ended_by_depth = False
        while True:
            if len(fingerprint_path) > self._max_depth:
                with self._count_lock:
                    if len(fingerprint_path) > self._max_depth:
                        self._max_depth = len(fingerprint_path)
            if (
                target_max_depth is not None
                and len(fingerprint_path) >= target_max_depth
            ):
                # Not necessarily terminal: skip the eventually check
                # (src/checker/simulation.rs:263-272).
                ended_by_depth = True
                break

            if not model.within_boundary(state):
                break

            fingerprint_path.append(model.fingerprint(state))
            rep_fp = (
                model.fingerprint(symmetry(state))
                if symmetry is not None
                else fingerprint_path[-1]
            )
            if rep_fp in generated:
                break  # found a loop
            generated.add(rep_fp)

            with self._count_lock:
                self._state_count += 1

            if visitor is not None:
                visitor.visit(
                    model, Path.from_fingerprints(model, fingerprint_path)
                )

            is_awaiting_discoveries = False
            for i, prop in enumerate(properties):
                if prop.name in self._discoveries:
                    continue
                if prop.expectation is Expectation.ALWAYS:
                    if not prop.condition(model, state):
                        self._discoveries.setdefault(
                            prop.name, list(fingerprint_path)
                        )
                    else:
                        is_awaiting_discoveries = True
                elif prop.expectation is Expectation.SOMETIMES:
                    if prop.condition(model, state):
                        self._discoveries.setdefault(
                            prop.name, list(fingerprint_path)
                        )
                    else:
                        is_awaiting_discoveries = True
                else:  # EVENTUALLY: discovered only at trace end.
                    is_awaiting_discoveries = True
                    if prop.condition(model, state):
                        ebits.discard(i)
            if not is_awaiting_discoveries:
                break

            actions: List[Any] = []
            model.actions(state, actions)
            advanced = False
            while actions:
                index = chooser.choose_action(chooser_state, state, actions)
                action = actions[index]
                # swap_remove (src/checker/simulation.rs:373)
                actions[index] = actions[-1]
                actions.pop()
                next_state = model.next_state(state, action)
                if next_state is not None:
                    state = next_state
                    advanced = True
                    break
            if not advanced:
                break  # terminal: no actions produced a next state

        # Leftover eventually-bits at the end of the trace are
        # counterexamples (src/checker/simulation.rs:390-396).
        if not ended_by_depth:
            for i, prop in enumerate(properties):
                if i in ebits:
                    self._discoveries[prop.name] = list(fingerprint_path)

    # --- Checker surface -----------------------------------------------------

    def state_count(self) -> int:
        return self._state_count

    def unique_state_count(self) -> int:
        # No global visited set is kept (src/checker/simulation.rs:413-417).
        return self._state_count

    def max_depth(self) -> int:
        return self._max_depth

    def discoveries(self) -> Dict[str, Path]:
        return {
            name: Path.from_fingerprints(self._model, fps)
            for name, fps in list(self._discoveries.items())
        }

    def handles(self) -> List[threading.Thread]:
        return self._handles

    def shutdown(self) -> None:
        """Stop every worker after its in-flight trace (the only exit for
        runs whose ``finish_when`` never matches and that set neither
        ``timeout`` nor ``target_state_count``)."""
        self._shutdown.set()

    def request_stop(self) -> None:
        super().request_stop()
        self._shutdown.set()

    def is_done(self) -> bool:
        return all(not h.is_alive() for h in self._handles)

    def join(self) -> "SimulationChecker":
        for h in self._handles:
            h.join()
        if self._errors:
            raise self._errors[0]
        return self
