"""Host-side cold tier: evicted fingerprint partitions as sorted runs.

The TLC lineage (Yu–Manolios–Lamport, PAPERS.md) keeps the fingerprint
set on disk as sorted immutable runs and merge-joins candidate batches
against them; this is the same structure one level up the hierarchy —
host RAM first, disk optionally under it — holding the partitions the
device engine evicts when its HBM hash table crosses the memory budget
(tiered/engine.py).

Each spill adds one immutable run: a sorted ``uint64`` fingerprint array
(8 bytes/state — 10⁸ states ≈ 800 MB of host RAM, far under a typical
host's memory next to a 16 GB chip).  Runs may overlap (the hot tier
caches cold-duplicate keys, and those ride along on the next spill);
membership is "present in ANY run", so overlap costs probe passes, never
correctness.  When the run count passes ``max_runs`` the store compacts
every run into one deduplicated array — the classic LSM merge, amortized
O(total) per spill epoch.

With ``spill_dir`` set, runs live on disk as ``.npy`` files opened back
memory-mapped, so the host RSS holds only the pages the merge-join
windows actually touch — the optional disk tier.  The engine's snapshot
embeds the whole store in its checkpoint.npz (``save_snapshot`` format,
docs/TIERED.md) so a killed run resumes with its tiers intact.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np


class ColdStore:
    """Sorted immutable uint64 fingerprint runs with LSM-style merging."""

    def __init__(self, spill_dir: Optional[str] = None, max_runs: int = 8):
        if max_runs < 1:
            raise ValueError("max_runs must be >= 1")
        self._runs: List[np.ndarray] = []
        self._paths: List[Optional[str]] = []  # disk backing, when spilled
        self.spill_dir = spill_dir
        self.max_runs = max_runs
        self._seq = 0  # monotonic file-name counter (never reused)

    # -- read surface ---------------------------------------------------------

    @property
    def runs(self) -> List[np.ndarray]:
        return list(self._runs)

    @property
    def run_count(self) -> int:
        return len(self._runs)

    @property
    def entries(self) -> int:
        """Total stored fingerprints, overlap included."""
        return int(sum(r.shape[0] for r in self._runs))

    @property
    def nbytes(self) -> int:
        return self.entries * 8

    def contains(self, fps) -> np.ndarray:
        """Host-side membership of a uint64 fingerprint batch — the
        reference implementation the device merge-join is pinned
        against (tests/test_tiered.py), and small enough callers'
        diagnostics can afford."""
        fps = np.asarray(fps, dtype=np.uint64)
        hit = np.zeros(fps.shape, dtype=bool)
        for run in self._runs:
            idx = np.searchsorted(run, fps)
            in_range = idx < run.shape[0]
            safe = np.minimum(idx, max(run.shape[0] - 1, 0))
            if run.shape[0]:
                hit |= in_range & (np.asarray(run)[safe] == fps)
        return hit

    # -- write surface --------------------------------------------------------

    def add_run(self, fps: np.ndarray) -> None:
        """Add one spill's fingerprints as a new immutable run (sorted
        here; the caller's segment readback arrives in row-log order).
        Empty spills are dropped.  Past ``max_runs`` the store merges
        everything into one deduplicated run."""
        fps = np.sort(np.asarray(fps, dtype=np.uint64))
        if fps.shape[0] == 0:
            return
        self._append(fps)
        if len(self._runs) > self.max_runs:
            self.merge()

    def merge(self) -> None:
        """Compact every run into one sorted, deduplicated run."""
        if not self._runs:
            return
        merged = np.unique(
            np.concatenate([np.asarray(r) for r in self._runs])
        )
        self._drop_files()
        self._runs = []
        self._paths = []
        self._append(merged)

    def _append(self, fps: np.ndarray) -> None:
        if self.spill_dir is not None:
            os.makedirs(self.spill_dir, exist_ok=True)
            self._seq += 1
            path = os.path.join(self.spill_dir, f"cold_run_{self._seq}.npy")
            np.save(path, fps)
            # Reopen memory-mapped: the RAM copy is released and probe
            # windows fault in only the pages they touch.
            self._runs.append(np.load(path, mmap_mode="r"))
            self._paths.append(path)
        else:
            self._runs.append(fps)
            self._paths.append(None)

    def _drop_files(self) -> None:
        # Unlinking while a memory map still references the file is fine
        # on POSIX (the map keeps the inode alive); best effort elsewhere.
        for path in self._paths:
            if path is None:
                continue
            try:
                os.remove(path)
            except OSError:
                pass

    # -- snapshot round trip (the checkpoint.npz container) -------------------

    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """``(concatenated_fps, run_lengths)`` for embedding in the
        engine's snapshot npz — runs stay distinct so a resume restores
        the exact tier shape (and probe-pass accounting) it left."""
        if not self._runs:
            return (
                np.zeros((0,), np.uint64), np.zeros((0,), np.int64),
            )
        return (
            np.concatenate([np.asarray(r) for r in self._runs]),
            np.asarray([r.shape[0] for r in self._runs], np.int64),
        )

    @classmethod
    def from_arrays(
        cls, fps: np.ndarray, lens: np.ndarray,
        spill_dir: Optional[str] = None, max_runs: int = 8,
    ) -> "ColdStore":
        store = cls(spill_dir=spill_dir, max_runs=max_runs)
        off = 0
        for n in np.asarray(lens, np.int64):
            n = int(n)
            store._append(np.asarray(fps[off:off + n], np.uint64))
            off += n
        return store
