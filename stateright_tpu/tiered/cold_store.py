"""Host-side cold tier: evicted fingerprint partitions as sorted runs.

The TLC lineage (Yu–Manolios–Lamport, PAPERS.md) keeps the fingerprint
set on disk as sorted immutable runs and merge-joins candidate batches
against them; this is the same structure one level up the hierarchy —
host RAM first, disk optionally under it — holding the partitions the
device engine evicts when its HBM hash table crosses the memory budget
(tiered/engine.py).

Each spill adds one immutable run: a sorted ``uint64`` fingerprint array
(8 bytes/state — 10⁸ states ≈ 800 MB of host RAM, far under a typical
host's memory next to a 16 GB chip).  Runs may overlap (the hot tier
caches cold-duplicate keys, and those ride along on the next spill);
membership is "present in ANY run", so overlap costs probe passes, never
correctness.  When the run count passes ``max_runs`` the store compacts
every run into one deduplicated array — the classic LSM merge, amortized
O(total) per spill epoch.

With ``spill_dir`` set, runs live on disk as ``.npy`` files opened back
memory-mapped, so the host RSS holds only the pages the merge-join
windows actually touch — the optional disk tier.  The engine's snapshot
embeds the whole store in its checkpoint.npz (``save_snapshot`` format,
docs/TIERED.md) so a killed run resumes with its tiers intact.
"""

from __future__ import annotations

import os
import re
from typing import List, Optional, Tuple

import numpy as np

_RUN_FILE = re.compile(r"^cold_run_(\d+)\.npy$")


class ColdStore:
    """Sorted immutable uint64 fingerprint runs with LSM-style merging."""

    def __init__(self, spill_dir: Optional[str] = None, max_runs: int = 8):
        if max_runs < 1:
            raise ValueError("max_runs must be >= 1")
        self._runs: List[np.ndarray] = []
        self._paths: List[Optional[str]] = []  # disk backing, when spilled
        self.spill_dir = spill_dir
        self.max_runs = max_runs
        # Monotonic file-name counter (never reused).  Seeded PAST any
        # run files already in ``spill_dir``: a fresh store (or a
        # ``from_arrays`` resume) pointed at a directory a previous
        # process spilled into must never overwrite a prior run's
        # ``.npy`` — a half-overwritten file is exactly the torn-run
        # state the disk tier promises not to have.
        self._seq = self._scan_seq(spill_dir)

    @staticmethod
    def _scan_seq(spill_dir: Optional[str]) -> int:
        if spill_dir is None or not os.path.isdir(spill_dir):
            return 0
        seqs = [
            int(m.group(1))
            for m in (_RUN_FILE.match(f) for f in os.listdir(spill_dir))
            if m
        ]
        return max(seqs, default=0)

    # -- read surface ---------------------------------------------------------

    @property
    def runs(self) -> List[np.ndarray]:
        return list(self._runs)

    @property
    def run_count(self) -> int:
        return len(self._runs)

    @property
    def entries(self) -> int:
        """Total stored fingerprints, overlap included."""
        return int(sum(r.shape[0] for r in self._runs))

    @property
    def nbytes(self) -> int:
        return self.entries * 8

    def contains(self, fps) -> np.ndarray:
        """Host-side membership of a uint64 fingerprint batch — the
        reference implementation the device merge-join is pinned
        against (tests/test_tiered.py), and small enough callers'
        diagnostics can afford."""
        fps = np.asarray(fps, dtype=np.uint64)
        hit = np.zeros(fps.shape, dtype=bool)
        for run in self._runs:
            idx = np.searchsorted(run, fps)
            in_range = idx < run.shape[0]
            safe = np.minimum(idx, max(run.shape[0] - 1, 0))
            if run.shape[0]:
                hit |= in_range & (np.asarray(run)[safe] == fps)
        return hit

    # -- write surface --------------------------------------------------------

    def add_run(self, fps: np.ndarray) -> None:
        """Add one spill's fingerprints as a new immutable run (sorted
        here; the caller's segment readback arrives in row-log order).
        Empty spills are dropped.  Past ``max_runs`` the store merges
        everything into one deduplicated run."""
        fps = np.sort(np.asarray(fps, dtype=np.uint64))
        if fps.shape[0] == 0:
            return
        self._append(fps)
        if len(self._runs) > self.max_runs:
            self.merge()

    def merge(self) -> None:
        """Compact every run into one sorted, deduplicated run."""
        if not self._runs:
            return
        merged = np.unique(
            np.concatenate([np.asarray(r) for r in self._runs])
        )
        self._drop_files()
        self._runs = []
        self._paths = []
        self._append(merged)

    def _append(self, fps: np.ndarray) -> None:
        if self.spill_dir is not None:
            os.makedirs(self.spill_dir, exist_ok=True)
            self._seq += 1
            path = os.path.join(self.spill_dir, f"cold_run_{self._seq}.npy")
            # Torn-run proofing: write + fsync a temp file, then rename
            # it into place.  A process killed mid-spill leaves either
            # the complete old state or a stray ``.tmp`` (ignored by the
            # name scan), never a half-written run a resume would mmap.
            tmp = f"{path}.tmp"
            with open(tmp, "wb") as fh:
                np.save(fh, fps)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            # Reopen memory-mapped: the RAM copy is released and probe
            # windows fault in only the pages they touch.
            self._runs.append(np.load(path, mmap_mode="r"))
            self._paths.append(path)
        else:
            self._runs.append(fps)
            self._paths.append(None)

    def close(self) -> None:
        """Release every memory map (the run FILES stay on disk).  A
        long-lived process holding many finished stores — the
        incremental verification store keeps one per entry
        (incr/store.py) — would otherwise pin a descriptor and address
        mapping per run forever.  The store is empty afterwards; reopen
        the directory with :meth:`open` to read it again."""
        self._runs = []
        self._paths = []

    @classmethod
    def open(
        cls, spill_dir: str, max_runs: int = 8
    ) -> "ColdStore":
        """Open a directory of previously spilled runs (memory-mapped,
        in spill order) WITHOUT rewriting them — the read-only reopen
        path for persisted stores (incr/store.py's fingerprint sets;
        post-mortem inspection of a tiered run's disk tier)."""
        store = cls(spill_dir=spill_dir, max_runs=max_runs)
        if not os.path.isdir(spill_dir):
            return store
        named = sorted(
            (int(m.group(1)), f)
            for m, f in (
                (_RUN_FILE.match(f), f) for f in os.listdir(spill_dir)
            )
            if m
        )
        for _seq, fname in named:
            path = os.path.join(spill_dir, fname)
            store._runs.append(np.load(path, mmap_mode="r"))
            store._paths.append(path)
        return store

    def _drop_files(self) -> None:
        # Unlinking while a memory map still references the file is fine
        # on POSIX (the map keeps the inode alive); best effort elsewhere.
        for path in self._paths:
            if path is None:
                continue
            try:
                os.remove(path)
            except OSError:
                pass

    # -- snapshot round trip (the checkpoint.npz container) -------------------

    def to_arrays(self) -> Tuple[np.ndarray, np.ndarray]:
        """``(concatenated_fps, run_lengths)`` for embedding in the
        engine's snapshot npz — runs stay distinct so a resume restores
        the exact tier shape (and probe-pass accounting) it left."""
        if not self._runs:
            return (
                np.zeros((0,), np.uint64), np.zeros((0,), np.int64),
            )
        return (
            np.concatenate([np.asarray(r) for r in self._runs]),
            np.asarray([r.shape[0] for r in self._runs], np.int64),
        )

    @classmethod
    def from_arrays(
        cls, fps: np.ndarray, lens: np.ndarray,
        spill_dir: Optional[str] = None, max_runs: int = 8,
        clean_stale: bool = True,
    ) -> "ColdStore":
        """Rebuild a store from its snapshot arrays.  With ``spill_dir``
        set, the restored runs are re-spilled under fresh sequence
        numbers (the counter scans past existing files, so a prior
        process's runs are never clobbered) and — with ``clean_stale``
        (default) — run files the restore did NOT claim are unlinked:
        the snapshot is authoritative, and leaving the dead process's
        duplicates behind would leak one directory's worth of disk per
        crash-resume cycle."""
        store = cls(spill_dir=spill_dir, max_runs=max_runs)
        off = 0
        for n in np.asarray(lens, np.int64):
            n = int(n)
            store._append(np.asarray(fps[off:off + n], np.uint64))
            off += n
        if clean_stale and spill_dir is not None and os.path.isdir(spill_dir):
            claimed = {
                os.path.basename(p) for p in store._paths if p is not None
            }
            for fname in os.listdir(spill_dir):
                if _RUN_FILE.match(fname) and fname not in claimed:
                    try:
                        os.remove(os.path.join(spill_dir, fname))
                    except OSError:
                        pass
        return store
