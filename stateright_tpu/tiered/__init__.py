"""Tiered out-of-core checking: HBM hot tier, host-RAM/disk cold tier.

Breaks the single-chip HBM ceiling on the fingerprint set (ROADMAP open
item #2, VERDICT missing #3): the device hash table holds the hot
working set under a fixed ``memory_budget_mb``, evicted partitions live
as sorted immutable runs in the host :class:`ColdStore`, and each wave's
hot-tier-new candidates are merge-joined against the overlapping run
windows on device before commit — same discovery set as an unconstrained
run, bit-identical (``discovered_fingerprints()`` pins).  docs/TIERED.md
has the full design.
"""

from .cold_store import ColdStore
from .engine import TieredTpuChecker, capacity_for_budget

__all__ = ["ColdStore", "TieredTpuChecker", "capacity_for_budget"]
