"""Tiered × sharded: pod-scale exact checking under a per-shard memory
budget.

Composes the two scale levers the engines grew separately:

- the SHARDED axis (parallel/sharded.py): frontier + fingerprint space
  owner-partitioned over a mesh, candidates exchanged per wave with one
  bucketed ``all_to_all``;
- the TIERED axis (tiered/engine.py): the hot fingerprint table bounded
  by ``memory_budget_mb``, evicted partitions living as sorted cold runs
  merged-joined back in before commit.

The composition is owner-local by construction: every fingerprint has
one owner shard, so shard ``d``'s cold runs hold only fingerprints shard
``d`` owns — the pre-commit cold merge-join needs NO cross-shard lookup,
exactly like the hot insert.  Each shard gets its own :class:`ColdStore`
(under ``cold_dir/shard_<d>/`` when disk-backed), its own spill
watermark, and its own budget-pinned hot table of ``capacity_for_budget``
slots.

Unlike the base sharded engine, the log is the BFS-ordered row log
itself (the tiered engine's layout), not slot-indexed storage: global
ids are ``log_position * n_shards + shard``, which stay valid across
spills, hot-table rebuilds, AND log growth — and which an offline
re-keying pass (tiered/reshard.py) can translate to a different mesh
width, something the base engine's ``shard << slot_bits | slot`` ids
cannot do.

The host drives one wave per ``_wl_call`` through the base engine's
traced-mode phase programs (step / canon / prededup / exchange /
insert), with the cold filter between insert and append — the same
shape as the single-chip tiered loop, under the shared
:class:`FusedWaveLoop`.  Snapshots embed the full per-shard tier state
(``ts_*`` keys); key planes are NOT persisted — a resume rebuilds them
from the committed log segment, so a kill can never leave a snapshot
with an aborted wave's keys.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

from ..parallel.sharded import (
    _PROGRAM_CACHE,
    _PROGRAM_CACHE_MAX,
    NO_GID,
    ShardedTpuChecker,
    _owner_mix_host_np,
    _shard_map,
)
from .cold_store import ColdStore
from .engine import capacity_for_budget


class TieredShardedTpuChecker(ShardedTpuChecker):
    """Sharded wavefront checker with budget-bounded per-shard hot
    tables and owner-local cold tiers."""

    def __init__(
        self,
        options,
        memory_budget_mb: Optional[float] = None,
        spill_threshold: float = 0.45,
        cold_max_runs: int = 8,
        cold_dir: Optional[str] = None,
        **kwargs,
    ):
        """``memory_budget_mb`` bounds EACH SHARD's hot fingerprint
        table (the tiered engine's budget semantics, applied per
        device): when given it derives the per-shard capacity,
        overriding any explicit ``capacity``.  ``spill_threshold`` /
        ``cold_max_runs`` / ``cold_dir`` keep the tiered engine's
        contracts; with ``cold_dir`` set, shard ``d`` spills under
        ``cold_dir/shard_<d>/`` — sibling stores never share a
        directory, so concurrent spills cannot clobber or cross-adopt
        runs (tests/test_tiered.py pins this).

        ``trace=True`` is refused like the single-chip tiered engine:
        this loop is already host-driven per wave; trace the tiered
        single-chip engine (``spawn_tpu_tiered(trace=True)``) or the
        plain sharded engine instead."""
        if kwargs.get("trace"):
            raise ValueError(
                "spawn_tpu_tiered_sharded(trace=True) is not supported: "
                "the tiered-sharded loop is already host-driven per "
                "wave; run the roofline trace on spawn_tpu_tiered or "
                "spawn_tpu_sharded instead"
            )
        if not 0.0 < float(spill_threshold) <= 0.5:
            raise ValueError(
                "spill_threshold must be in (0, 0.5]: the insert flags "
                "the table overfull beyond 50% load"
            )
        import jax

        mesh = kwargs.get("mesh")
        n = mesh.devices.size if mesh is not None else len(jax.devices())
        # The budget derives the PER-SHARD capacity; the base
        # constructor floors cap_s at 1024, so the true (possibly
        # smaller) budgeted capacity is re-pinned at the top of _check
        # — safe, the run thread is the only _cap_s consumer.
        self._ts_cap_s: Optional[int] = None
        if memory_budget_mb is not None:
            self._ts_cap_s = capacity_for_budget(memory_budget_mb)
            kwargs["capacity"] = self._ts_cap_s * n
        self._memory_budget_mb = (
            None if memory_budget_mb is None else float(memory_budget_mb)
        )
        self._spill_threshold = float(spill_threshold)
        self._cold_max_runs = int(cold_max_runs)
        self._cold_dir = cold_dir
        self._colds = [
            ColdStore(
                spill_dir=(
                    None if cold_dir is None
                    else os.path.join(cold_dir, f"shard_{d}")
                ),
                max_runs=self._cold_max_runs,
            )
            for d in range(n)
        ]
        # Per-shard host bookkeeping (the tiered engine's scalars, one
        # lane per shard).  Log positions, not table slots.
        self._ts_level_start = np.zeros(n, np.int64)
        self._ts_level_end = np.zeros(n, np.int64)
        self._ts_tails = np.zeros(n, np.int64)
        self._ts_spill_tails = np.zeros(n, np.int64)
        self._ts_hot = np.zeros(n, np.int64)
        self._ts_cand = np.zeros(n, np.int64)
        self._ts_spill_counts = np.zeros(n, np.int64)
        self._ts_flag1_shards = np.zeros(n, bool)
        self._ts_planes_dirty = False
        self._ts_log_cap = 0  # per-shard row-log capacity (grows, flag 2)
        self._ts_pad = 0  # fixed slice padding, minted at run start
        self._t_depth = 0
        self._t_unique = 0
        self._t_states = 0
        self._t_flags = 0
        self._t_disc = None  # device uint32[n, P] discovery gids
        self._t_disc_h = None
        self._ts_cold_last = None  # last wave's cold-probe accounting
        # The base constructor starts the run thread as its LAST
        # statement; every tiered attribute must exist before it.
        super().__init__(options, **kwargs)

    # --- device programs ------------------------------------------------------

    def _ts_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self._mesh, P("shards"))

    def _ts_up(self, x):
        """Sharded upload into DEVICE-OWNED buffers (the programs donate
        their log/plane arguments; see wavefront._device_owned)."""
        import jax
        import jax.numpy as jnp

        from ..parallel.wavefront import _device_owned

        return _device_owned(
            jax.device_put(jnp.asarray(x), self._ts_sharding())
        )

    def _ts_programs(self):
        """The engine-specific phase programs (step over the row log,
        fresh-masked append, spill segment fingerprinting, plane rebuild
        and clear), cached like every other program set.  canon /
        prededup / exchange / insert are REUSED from the base engine's
        traced set — identical kernels, one definition."""
        key = (
            "tiered-sharded",
            self._compiled.cache_key(),
            hasattr(self._compiled, "step_valid")
            and hasattr(self._compiled, "step_lane"),
            self._canon is not None,
            self._cap_s,
            self._chunk,
            self._dedup_factor,
            self._sortless,
            self._sort_width(),
            self._step_width(),
            self._bucket_slack,
            self._ts_log_cap,
            self._ts_pad,
            tuple((d.platform, d.id) for d in self._mesh.devices.flat),
            tuple(p.expectation for p in self._properties),
        )
        from ..parallel.wave_common import cached_program

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, self._ts_build,
            label="TieredShardedTpuChecker.programs",
            journal=self._journal,
            provenance=self._key_provenance(),
        )

    def _ts_build(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..ops.device_fp import device_fp64
        from ..parallel.hashset import (
            HashSet, compact_valid_indices, insert_batch_claim,
        )
        from ..parallel.wave_common import wave_eval

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w
        canon = self._canon
        a = cm.max_actions
        f_eff = self._step_width()
        n = self._n
        props = self._properties
        ev_indices = self._ev_indices
        dedup_factor = self._dedup_factor
        sort_lanes = (
            None if self._sort_lanes is None else self._sort_width()
        )
        b = f_eff * a
        seg = self._ts_pad  # fixed window width for segfp/rehash
        u = jnp.uint32
        shard = P("shards")

        def sharded(fn, n_in, donate=()):
            return jax.jit(
                _shard_map(
                    fn, mesh=self._mesh,
                    in_specs=(shard,) * n_in, out_specs=shard,
                ),
                donate_argnums=donate,
            )

        def fp_of(rows):
            rows_c = rows if canon is None else jax.vmap(canon)(rows)
            return device_fp64(rows_c[:, :fpw])

        def step_shard(rows2d, ebits1d, disc, ctrl):
            # The base step over the ROW LOG instead of a slot queue:
            # the frontier is the log slice [level_start, level_end),
            # consumed f_eff lanes at a time; gids encode the log
            # position (pos * n + shard), stable across spills and log
            # growth.  The pad past log_cap keeps the dynamic_slice
            # from ever clamping (level_start <= log_cap, f_eff <= pad).
            me = jax.lax.axis_index("shards").astype(u)
            level_start = ctrl[0, 0]
            level_end = ctrl[0, 1]
            count = jnp.minimum(level_end - level_start, u(f_eff))
            states = jax.lax.dynamic_slice(
                rows2d, (level_start, u(0)), (f_eff, w)
            )
            eb_in = jax.lax.dynamic_slice(
                ebits1d, (level_start,), (f_eff,)
            )
            lane = jnp.arange(f_eff, dtype=u)
            active = lane < count
            my_gids = (level_start + lane) * u(n) + me
            disc_v, eb, nexts, valid, gen_local, step_flag = wave_eval(
                cm, props, ev_indices, states, active, my_gids, eb_in,
                disc[0], allow_two_phase=True,
            )
            flat_valid = valid.reshape(b)
            v_orig, v_act, _n_valid, local_overflow = (
                compact_valid_indices(
                    flat_valid, dedup_factor, sort_lanes=sort_lanes
                )
            )
            if nexts is None:
                rows_v, _vv, lane_flags_v = jax.vmap(cm.step_lane)(
                    states[v_orig // u(a)], v_orig % u(a)
                )
                step_flag = step_flag | jnp.any(lane_flags_v & v_act)
            else:
                rows_v = nexts.reshape(b, w)[v_orig]
            gid_v = my_gids[v_orig // u(a)]
            eb_v = eb[v_orig // u(a)]
            return (
                disc_v[None], rows_v, gid_v, eb_v, v_act,
                local_overflow[None], gen_local.astype(u)[None],
                step_flag[None],
            )

        def append_shard(rows2d, parent1d, ebits1d, rw, rg, reb,
                         r_origin, fresh, ctrl):
            # The base append with the FRESH mask in place of r_new:
            # lanes the cold filter disqualified (already in a cold
            # run) are dropped — their hot-table entry stays as the
            # negative cache, exactly the single-chip tiered rule.
            tail = ctrl[0, 0]
            fr = fresh[0]
            pos = tail + jnp.cumsum(fr) - u(1)
            idx = jnp.where(fr != u(0), pos, u(0xFFFFFFFF))
            rows2d = rows2d.at[idx].set(rw[r_origin], mode="drop")
            parent1d = parent1d.at[idx].set(rg[r_origin], mode="drop")
            ebits1d = ebits1d.at[idx].set(reb[r_origin], mode="drop")
            return rows2d, parent1d, ebits1d

        def segfp_shard(rows2d, ctrl):
            # One seg-wide spill window: canonical fingerprints of the
            # log slice starting at ctrl[0,0] (the caller masks the
            # valid count host-side; lanes past it are padding).
            off = ctrl[0, 0]
            states = jax.lax.dynamic_slice(rows2d, (off, u(0)), (seg, w))
            return fp_of(states)

        def rehash_shard(kh, kl, rows2d, ctrl):
            # One seg-wide plane-rebuild window: re-insert the log
            # slice [off, off+cnt) into the hot planes.  Log entries
            # are distinct by construction, so the claim insert is
            # duplicate-free and probe_ok is the only failure mode.
            off = ctrl[0, 0]
            cnt = ctrl[0, 1]
            states = jax.lax.dynamic_slice(rows2d, (off, u(0)), (seg, w))
            hi, lo = fp_of(states)
            act = jnp.arange(seg, dtype=u) < cnt
            (
                table, _slot, _new, _orig, _ra, probe_ok,
                _dd, _rounds,
            ) = insert_batch_claim(
                HashSet(kh, kl), hi, lo, act, with_rounds=True,
            )
            return table.key_hi, table.key_lo, probe_ok[None]

        def clear_shard(kh, kl, mask):
            # Zero the planes of spilling shards only (mask is per-shard
            # 0/1); non-spilling shards keep their live entries.
            keep = mask[0, 0] == u(0)
            return jnp.where(keep, kh, u(0)), jnp.where(keep, kl, u(0))

        return {
            "step": sharded(step_shard, 4),
            "append": sharded(append_shard, 9, donate=(0, 1, 2)),
            "segfp": sharded(segfp_shard, 2),
            "rehash": sharded(rehash_shard, 4, donate=(0, 1)),
            "clear": sharded(clear_shard, 3, donate=(0, 1)),
        }

    # --- the tiered-sharded wave (one _wl_call) -------------------------------

    def _wl_call(self, carry):
        """One wave: step → canon → prededup → exchange → insert, one
        combined flag readback, the owner-local cold filter, then the
        fresh-masked append.  Host bookkeeping commits only at
        flags == 0; an aborted wave leaves every counter and the log at
        its pre-wave state (the hot planes, which the insert already
        consumed, are marked dirty and rebuilt by recovery)."""
        key_hi, key_lo, rows, parent, ebits = carry
        n = self._n
        backlog = self._ts_level_end - self._ts_level_start
        td = self._options._target_max_depth or 0
        if int(backlog.sum()) <= 0 or (td and self._t_depth >= td - 1):
            # Drained level (a completed snapshot being resumed) or the
            # next wave would expand past the target depth: clean no-op;
            # the shared termination tail stops the loop.
            self._t_flags = 0
            self._ts_cold_last = None
            return carry
        f_eff = self._step_width()
        if f_eff < self._chunk and int(backlog.max()) > f_eff:
            # Step-rung clamp (flag 128), decided BEFORE dispatch — the
            # host knows the backlog, so unlike the fused loop no device
            # work is wasted discovering it.
            self._t_flags = 128
            self._ts_cold_last = None
            return carry
        progs = self._ts_programs()
        base = self._traced_programs()
        counts = np.minimum(backlog, f_eff)
        ctrl_np = np.zeros((n, 2), np.uint32)
        ctrl_np[:, 0] = self._ts_level_start
        ctrl_np[:, 1] = self._ts_level_end
        disc_prev = self._t_disc  # step does not donate it
        (
            disc, rows_v, gid_v, eb_v, v_act,
            local_ovf_d, gen_d, stepflag_d,
        ) = progs["step"](rows, ebits, disc_prev, self._ts_up(ctrl_np))
        hi, lo = base["canon"](rows_v)
        u_hi, u_lo, rows_u, gid_u, eb_u, u_valid, n_cand_d = (
            base["prededup"](hi, lo, rows_v, gid_v, eb_v, v_act)
        )
        if n > 1:
            rw, rg, reb, rv, rhi, rlo, bucket_ovf_d = base["exchange"](
                u_hi, u_lo, rows_u, gid_u, eb_u, u_valid
            )
        else:
            rw, rg, reb, rv = rows_u, gid_u, eb_u, u_valid
            rhi, rlo = u_hi, u_lo
            bucket_ovf_d = None
        key_hi, key_lo, _r_slot, r_new, r_origin, probe_ok_d, dd_ovf_d, \
            _rounds_d = base["insert"](key_hi, key_lo, rhi, rlo, rv)

        # ONE combined flag readback (the insert already ran — flags
        # 4/32 therefore cost a plane rebuild on recovery, accepted:
        # rung climbs are rare next to waves, and the good path saves a
        # pre-insert host sync every wave).
        flags = 0
        if np.asarray(local_ovf_d).any():
            flags |= 4
        if bucket_ovf_d is not None and np.asarray(bucket_ovf_d).any():
            flags |= 32
        if np.asarray(stepflag_d).any():
            flags |= 8
        if np.asarray(dd_ovf_d).any():
            flags |= 64
        r_new_h = np.asarray(r_new).reshape(n, -1).astype(bool)
        n_new_h = r_new_h.sum(axis=1).astype(np.int64)
        probe_ok_h = np.asarray(probe_ok_d).reshape(n).astype(bool)
        over = (~probe_ok_h) | (
            (self._ts_hot + n_new_h) * 2 > self._cap_s
        )
        if over.any():
            flags |= 1
            self._ts_flag1_shards = over.copy()

        # Owner-local cold filter: each shard's new keys are checked
        # against ITS OWN cold runs only (ownership routing guarantees
        # a fingerprint can never be cold on another shard).
        cold = None
        fresh_h = r_new_h.copy()
        if flags == 0 and n_new_h.sum():
            queried = hits = shards_touched = 0
            rhi_h = rlo_h = None
            for d in range(n):
                if not n_new_h[d] or not self._colds[d].run_count:
                    continue
                if rhi_h is None:
                    rhi_h = np.asarray(rhi).reshape(n, -1)
                    rlo_h = np.asarray(rlo).reshape(n, -1)
                lanes = np.flatnonzero(r_new_h[d])
                fps = (
                    rhi_h[d, lanes].astype(np.uint64) << np.uint64(32)
                ) | rlo_h[d, lanes].astype(np.uint64)
                hit = self._colds[d].contains(fps)
                if hit.any():
                    fresh_h[d, lanes[hit]] = False
                queried += int(lanes.size)
                hits += int(hit.sum())
                shards_touched += 1
            if shards_touched:
                cold = {
                    "queried": queried,
                    "hits": hits,
                    "shards": shards_touched,
                }
        n_fresh_h = fresh_h.sum(axis=1).astype(np.int64)
        if flags == 0 and bool(
            ((self._ts_tails + n_fresh_h) > self._ts_log_cap).any()
        ):
            flags |= 2

        if flags:
            # The old planes were donated to the insert; the new ones
            # hold the aborted wave's keys — recovery rebuilds them
            # from the committed log segment.  Discoveries revert (the
            # single-chip tiered rule: a kept discovery would change
            # the re-run's awaiting mask and break the bit pin).
            self._ts_planes_dirty = True
            self._t_disc = disc_prev
            self._t_flags = flags
            self._ts_cold_last = None
            return (key_hi, key_lo, rows, parent, ebits)

        tail_ctrl = np.zeros((n, 2), np.uint32)
        tail_ctrl[:, 0] = self._ts_tails
        rows, parent, ebits = progs["append"](
            rows, parent, ebits, rw, rg, reb, r_origin,
            self._ts_up(fresh_h.astype(np.uint32)),
            self._ts_up(tail_ctrl),
        )
        self._ts_hot += n_new_h  # cold hits stay as the negative cache
        self._ts_tails += n_fresh_h
        self._t_unique += int(n_fresh_h.sum())
        self._t_states += int(np.asarray(gen_d).astype(np.int64).sum())
        self._ts_cand += np.asarray(n_cand_d).reshape(n).astype(np.int64)
        self._ts_level_start = self._ts_level_start + counts
        if bool((self._ts_level_start >= self._ts_level_end).all()):
            self._t_depth += 1
            self._ts_level_end = self._ts_tails.copy()
        self._t_disc = disc
        self._t_disc_h = np.asarray(disc)
        if cold is not None:
            if self._journal:
                self._journal.append(
                    "cold_probe",
                    depth=self._t_depth,
                    unique=self._t_unique,
                    **cold,
                )
            self._metrics.inc("cold_probe_queries_total", cold["queried"])
            self._metrics.inc("cold_hits_total", cold["hits"])
        self._t_flags = 0
        self._ts_cold_last = cold
        return (key_hi, key_lo, rows, parent, ebits)

    def _wl_view(self, carry):
        from ..parallel.wave_loop import WaveView

        n = self._n
        props = self._properties
        backlog = self._ts_level_end - self._ts_level_start
        self._update_shard_metrics(backlog, self._ts_tails, self._ts_cand)
        disc = []
        if self._t_disc_h is not None:
            for d in range(n):
                for p, prop in enumerate(props):
                    g = int(self._t_disc_h[d, p])
                    if g != NO_GID:
                        disc.append((prop.name, g))
        extra = {
            "tail": int(self._ts_tails.sum()),
            "hot_entries": int(self._ts_hot.max()),
            "cold_runs": int(sum(c.run_count for c in self._colds)),
        }
        if self._ts_cold_last is not None:
            extra["cold_queried"] = self._ts_cold_last["queried"]
            extra["cold_hits"] = self._ts_cold_last["hits"]
        return WaveView(
            waves_this_call=1,
            remaining=int(backlog.sum()),
            depth=self._t_depth,
            flags=self._t_flags,
            unique=self._t_unique,
            states=self._t_states,
            # Binding constraint: the FULLEST shard's budgeted table.
            occupancy=float(self._ts_hot.max()) / self._cap_s,
            discoveries=tuple(disc),
            extra=extra,
        )

    def _update_shard_metrics(self, frontier, unique_l, cand) -> None:
        super()._update_shard_metrics(frontier, unique_l, cand)
        n = self._n
        cold_entries = np.array(
            [c.entries for c in self._colds], np.int64
        )
        self._metrics.update(
            shard_hot_entries={
                str(d): int(self._ts_hot[d]) for d in range(n)
            },
            shard_cold_entries={
                str(d): int(cold_entries[d]) for d in range(n)
            },
            shard_spills={
                str(d): int(self._ts_spill_counts[d]) for d in range(n)
            },
            cold_skew_max_over_mean=self._skew(cold_entries),
        )

    # --- spill / recovery -----------------------------------------------------

    def _wl_after_commit(self, carry, view):
        """Per-shard eviction on the shared loop's post-commit rung:
        every shard past the threshold spills in one lockstep pass.
        The measured global load factor confirms the host bookkeeping
        (one scalar sync per spill, not per wave)."""
        over = (
            self._ts_hot.astype(np.float64) / self._cap_s
            >= self._spill_threshold
        )
        if not over.any():
            return carry
        from ..parallel.hashset import HashSet

        lf = float(HashSet(carry[0], carry[1]).load_factor())
        self._metrics.update(hot_load_factor=round(lf, 6))
        return self._ts_spill(
            carry, np.flatnonzero(over), reason="threshold",
            clear_planes=True,
        )

    def _ts_spill(self, carry, shards, reason: str, clear_planes: bool):
        """Evict the chosen shards' hot tiers: fingerprints of each
        shard's log segment [spill_tail, tail) become one sorted cold
        run in that shard's own store (computed FROM THE LOG, so keys
        an aborted insert scribbled can never leak cold), watermarks
        advance, and — with ``clear_planes`` (the committed-boundary
        path) — the spilled shards' planes are zeroed on device.  The
        overflow-recovery path passes ``clear_planes=False``: its
        planes are dirty anyway and the full rebuild that follows
        supersedes a clear."""
        key_hi, key_lo, rows, parent, ebits = carry
        n = self._n
        shards = np.asarray(shards, np.int64)
        t0 = time.monotonic()
        progs = self._ts_programs()
        seg = self._ts_pad
        starts = self._ts_spill_tails.copy()
        ends = self._ts_tails.copy()
        spilling = np.zeros(n, bool)
        spilling[shards] = True
        spans = np.where(spilling, ends - starts, 0)
        per_shard = [[] for _ in range(n)]
        off = 0
        max_span = int(spans.max())
        while off < max_span:
            # Lockstep windows: every dispatch slices all shards (idle
            # ones read a zero-count window); the host keeps only the
            # valid prefix of each spilling shard.
            cnts = np.clip(spans - off, 0, seg)
            ctrl_np = np.zeros((n, 2), np.uint32)
            ctrl_np[:, 0] = np.where(spilling, starts + off, 0)
            ctrl_np[:, 1] = cnts
            hi, lo = progs["segfp"](rows, self._ts_up(ctrl_np))
            hi_h = np.asarray(hi).reshape(n, seg)
            lo_h = np.asarray(lo).reshape(n, seg)
            for d in shards:
                c = int(cnts[d])
                if c:
                    per_shard[d].append(
                        (
                            hi_h[d, :c].astype(np.uint64)
                            << np.uint64(32)
                        ) | lo_h[d, :c].astype(np.uint64)
                    )
            off += seg
        spill_sec = round(time.monotonic() - t0, 4)
        for d in shards:
            fps = (
                np.concatenate(per_shard[d])
                if per_shard[d] else np.zeros((0,), np.uint64)
            )
            self._colds[d].add_run(fps)
            self._ts_spill_counts[d] += 1
            if self._journal:
                self._journal.append(
                    "spill",
                    shard=int(d),
                    reason=reason,
                    entries=int(fps.shape[0]),
                    bytes=int(fps.nbytes),
                    start=int(starts[d]),
                    end=int(ends[d]),
                    load_factor=round(
                        float(self._ts_hot[d]) / self._cap_s, 6
                    ),
                    cold_runs=self._colds[d].run_count,
                    cold_entries=self._colds[d].entries,
                    spill_sec=spill_sec,
                )
            self._metrics.inc("spills", 1)
            self._metrics.inc("spill_bytes_total", int(fps.nbytes))
            self._ts_spill_tails[d] = ends[d]
            self._ts_hot[d] = 0
        self._metrics.update(
            cold_runs=int(sum(c.run_count for c in self._colds)),
            cold_entries=int(sum(c.entries for c in self._colds)),
            cold_bytes=int(sum(c.nbytes for c in self._colds)),
        )
        if clear_planes:
            mask_np = np.zeros((n, 1), np.uint32)
            mask_np[shards, 0] = 1
            key_hi, key_lo = progs["clear"](
                key_hi, key_lo, self._ts_up(mask_np)
            )
        return (key_hi, key_lo, rows, parent, ebits)

    def _ts_rebuild_planes(self, rows):
        """Fresh hot planes from the committed log: re-insert every
        shard's [spill_tail, tail) segment in lockstep seg-wide
        windows.  Used at seed, at resume (planes are never persisted),
        and by overflow recovery (erasing an aborted insert's keys)."""
        n = self._n
        progs = self._ts_programs()
        seg = self._ts_pad
        zeros = np.zeros(n * self._cap_s, np.uint32)
        key_hi = self._ts_up(zeros)
        key_lo = self._ts_up(zeros)
        starts = self._ts_spill_tails
        spans = self._ts_tails - starts
        off = 0
        max_span = int(spans.max()) if n else 0
        while off < max_span:
            ctrl_np = np.zeros((n, 2), np.uint32)
            ctrl_np[:, 0] = np.minimum(starts + off, self._ts_tails)
            ctrl_np[:, 1] = np.clip(spans - off, 0, seg)
            key_hi, key_lo, ok = progs["rehash"](
                key_hi, key_lo, rows, self._ts_up(ctrl_np)
            )
            if not np.asarray(ok).all():
                raise RuntimeError(
                    "hot-table rebuild failed a probe bound below the "
                    "50% spill gate — impossible by construction; "
                    "please report"
                )
            off += seg
        return key_hi, key_lo

    def _wl_grow(self, flags: int, carry):
        """In-place recovery for an aborted wave.  Flags 4/32/128 use
        the base knob ladders (_grow_knobs); flag 1 SPILLS the
        overfull shards (the budget pins their capacity) or — if a
        shard's table is already empty — shrinks the chunk until one
        wave's distinct keys fit; flag 2 doubles the row log (gids
        encode log positions, so growth never re-keys anything).  Any
        dirty planes are rebuilt from the committed log at the end."""
        from ..parallel.wave_loop import log_grow

        base_bits = flags & (4 | 32 | 128)
        if base_bits and self._grow_knobs(base_bits) is None:
            return None
        key_hi, key_lo, rows, parent, ebits = carry
        notes = []
        if flags & 1:
            over = self._ts_flag1_shards
            spill_shards = np.flatnonzero(over & (self._ts_hot > 0))
            stuck = over & (self._ts_hot == 0)
            if spill_shards.size:
                carry = self._ts_spill(
                    carry, spill_shards, reason="overflow",
                    clear_planes=False,
                )
                key_hi, key_lo, rows, parent, ebits = carry
                notes.append(
                    f"spill shards={spill_shards.tolist()} (budget "
                    f"pins per-shard capacity={self._cap_s})"
                )
            if stuck.any():
                if self._chunk <= 8:
                    return None
                self._chunk = max(8, self._chunk // 2)
                notes.append(f"chunk_size={self._chunk}")
        if flags & 2:
            new_cap = self._ts_log_cap * 2
            if (new_cap + self._ts_pad) * self._n >= 0xFFFFFFFF:
                return None
            rows, parent, ebits = self._ts_grow_log(
                rows, parent, ebits, new_cap
            )
            self._ts_log_cap = new_cap
            notes.append(f"log_capacity={new_cap}")
        if notes:
            log_grow(
                self, flags & 3, "; ".join(notes),
                self._t_unique, self._t_depth,
            )
        if self._ts_planes_dirty:
            key_hi, key_lo = self._ts_rebuild_planes(rows)
            # The rebuilt tables hold exactly the committed segments —
            # cold-duplicate cache entries are gone (they live in
            # earlier runs), so the bookkeeping must match.
            self._ts_hot = (
                self._ts_tails - self._ts_spill_tails
            ).astype(np.int64)
            self._ts_planes_dirty = False
        return (key_hi, key_lo, rows, parent, ebits)

    def _ts_grow_log(self, rows, parent, ebits, new_cap: int):
        """Double the per-shard row log (host round trip; growth is
        rare and the log is the one buffer that must survive).  gids
        encode positions, not slots, so nothing is re-keyed."""
        n, w = self._n, self._compiled.state_width
        old_lp = self._ts_log_cap + self._ts_pad
        new_lp = new_cap + self._ts_pad
        rows_n = np.zeros((n, new_lp, w), np.uint32)
        rows_n[:, :old_lp] = np.asarray(rows).reshape(n, old_lp, w)
        parent_n = np.full((n, new_lp), NO_GID, np.uint32)
        parent_n[:, :old_lp] = np.asarray(parent).reshape(n, old_lp)
        ebits_n = np.zeros((n, new_lp), np.uint32)
        ebits_n[:, :old_lp] = np.asarray(ebits).reshape(n, old_lp)
        return (
            self._ts_up(rows_n.reshape(n * new_lp, w)),
            self._ts_up(parent_n.reshape(n * new_lp)),
            self._ts_up(ebits_n.reshape(n * new_lp)),
        )

    def _wl_retryable_flags(self) -> int:
        # Unlike the base sharded engine, table (1) and log (2)
        # overflows ARE recoverable here: the budget spills instead of
        # growing, and log growth never re-keys (positional gids).
        return 1 | 2 | 4 | 32 | 128

    def _wl_overflow_message(self, flags: int) -> str:
        if flags & (8 | 64):
            return super()._wl_overflow_message(flags)
        if flags & 1:
            return (
                "a single wave inserted more distinct new keys than a "
                f"shard's budgeted hot table holds (per-shard capacity "
                f"{self._cap_s}) even at the floor chunk; raise "
                "memory_budget_mb"
            )
        return f"tiered-sharded engine overflow flags={flags}"

    # --- run setup / teardown (the host side of _check) -----------------------

    def _check(self) -> None:
        opts = self._options
        deadline = (
            time.monotonic() + opts._timeout
            if opts._timeout is not None else None
        )
        if self._ts_cap_s is not None:
            # Re-pin the budgeted per-shard capacity under the base
            # constructor's 1024-slot floor (see __init__); this thread
            # is the only consumer during the run.
            self._cap_s = self._ts_cap_s
            self._slot_bits = max(1, self._cap_s.bit_length() - 1)
        if self._resume_from is not None:
            carry = self._ts_resume()
        else:
            self._ts_log_cap = self._cap_s
            self._ts_pad = self._chunk
            if (
                (self._ts_log_cap + self._ts_pad) * self._n
                >= 0xFFFFFFFF
            ):
                raise ValueError(
                    "capacity too large for 32-bit global ids"
                )
            carry = self._ts_seed()
        from ..parallel.wave_loop import FusedWaveLoop, finalize_run

        carry, waves_total = FusedWaveLoop(self).run(carry, deadline)
        self._accounting = self._build_accounting(
            waves_total, self._ts_cand.copy(), self._ts_tails.copy()
        )
        self._tables_dev = (carry[3], carry[2])  # parent, rows
        finalize_run(self, self._ts_carry_dict(carry))

    def _ts_seed(self):
        """Host-side seeding: canonical fingerprints + owner routing on
        the host (bit-identical by the pinned host/device fp and mix
        parity), per-shard in-order dedup, one upload, then a device
        plane rebuild over the seeded prefix."""
        cm = self._compiled
        n = self._n
        w = cm.state_width
        from ..ops.fingerprint import fp64_words

        init = cm.init_packed()
        n_init = init.shape[0]
        fpw = cm.fp_words or w
        if self._canon is not None:
            from ..parallel.canon import canon_batch_host

            fp_rows = canon_batch_host(cm, init)
        else:
            fp_rows = init
        fps = np.array(
            [fp64_words(row[:fpw].tolist()) for row in fp_rows],
            np.uint64,
        )
        owner = (
            _owner_mix_host_np(
                (fps >> np.uint64(32)).astype(np.uint32),
                (fps & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            ).astype(np.int64) % n
        )
        lp = self._ts_log_cap + self._ts_pad
        rows_np = np.zeros((n, lp, w), np.uint32)
        parent_np = np.full((n, lp), NO_GID, np.uint32)
        ebits_np = np.zeros((n, lp), np.uint32)
        eb0 = (1 << len(self._ev_indices)) - 1
        tails = np.zeros(n, np.int64)
        for d in range(n):
            seen = set()
            kept = []
            for i in np.flatnonzero(owner == d):
                f = int(fps[i])
                if f not in seen:
                    seen.add(f)
                    kept.append(int(i))
            c = len(kept)
            if c * 2 > self._cap_s:
                raise RuntimeError(
                    "init-state seeding overflowed the budgeted "
                    f"per-shard fingerprint table (shard {d}: {c} "
                    f"distinct seeds vs capacity {self._cap_s}); raise "
                    "memory_budget_mb (or pass capacity=) past the "
                    "init-state count"
                )
            if c:
                rows_np[d, :c] = init[kept]
                ebits_np[d, :c] = eb0
            tails[d] = c
        rows = self._ts_up(rows_np.reshape(n * lp, w))
        parent = self._ts_up(parent_np.reshape(n * lp))
        ebits = self._ts_up(ebits_np.reshape(n * lp))
        self._ts_tails = tails
        self._ts_spill_tails = np.zeros(n, np.int64)
        self._ts_level_start = np.zeros(n, np.int64)
        self._ts_level_end = tails.copy()
        self._ts_hot = tails.copy()
        self._t_depth = 0
        self._t_unique = int(tails.sum())
        self._t_states = n_init
        n_props = len(self._properties)
        self._t_disc = self._ts_up(
            np.full((n, n_props), NO_GID, np.uint32)
        )
        self._t_disc_h = np.asarray(self._t_disc)
        key_hi, key_lo = self._ts_rebuild_planes(rows)
        with self._lock:
            self._state_count = n_init
            self._unique_count = self._t_unique
        return (key_hi, key_lo, rows, parent, ebits)

    def _ts_resume(self):
        n = self._n
        snap = np.load(self._resume_from, allow_pickle=False)
        if "ts_tails" not in snap.files:
            raise ValueError(
                "snapshot was not written by the tiered-sharded engine "
                "(no per-shard tier state); resume it with the engine "
                "that wrote it, or convert a sharded snapshot with the "
                "`reshard` verb (stateright_tpu.tiered.reshard)"
            )
        if "n_shards" in snap.files and int(snap["n_shards"]) != n:
            raise ValueError(
                f"tiered-sharded snapshot was written on a "
                f"{int(snap['n_shards'])}-shard mesh and cannot resume "
                f"on {n} shards directly: global state ids encode the "
                "owner shard; run the `reshard` verb "
                "(stateright_tpu.tiered.reshard.reshard_snapshot) to "
                f"re-key it onto a {n}-shard mesh, or re-run on a "
                f"{int(snap['n_shards'])}-shard mesh"
            )
        if self._memory_budget_mb is not None and (
            capacity_for_budget(self._memory_budget_mb)
            != int(snap["cap_s"])
        ):
            # The budget is authoritative, but a resume must adopt the
            # snapshot's table — both promises hold only when they
            # agree (the single-chip tiered rule).
            raise ValueError(
                f"resume memory_budget_mb={self._memory_budget_mb} "
                f"implies a "
                f"{capacity_for_budget(self._memory_budget_mb)}-slot "
                f"per-shard hot table, but the snapshot was written at "
                f"cap_s={int(snap['cap_s'])}; resume with the "
                "snapshot's original budget (or with capacity kwargs "
                "alone to adopt its geometry)"
            )
        want_key = self._snapshot_key()
        got_key = str(snap["engine_key"])
        if got_key != want_key:
            raise ValueError(
                "snapshot does not match this tiered-sharded checker "
                f"configuration (snapshot {got_key}, expected "
                f"{want_key})"
            )
        self._cap_s = int(snap["cap_s"])
        self._slot_bits = max(1, self._cap_s.bit_length() - 1)
        self._chunk = int(snap["chunk"])
        if "bucket_slack" in snap.files:
            self._bucket_slack = int(snap["bucket_slack"])
        if "sort_lanes" in snap.files and int(snap["sort_lanes"]):
            self._sort_lanes = int(snap["sort_lanes"])
            self._sort_tune = False
        if "sortless" in snap.files:
            self._sortless = bool(int(snap["sortless"]))
        if "step_lanes" in snap.files and int(snap["step_lanes"]):
            self._step_lanes = int(snap["step_lanes"])
            self._step_tune = False
        self._ts_log_cap = int(snap["ts_log_cap"])
        w = self._compiled.state_width
        rows_h = np.asarray(snap["rows"]).reshape(n, -1, w)
        parent_h = np.asarray(snap["parent"]).reshape(n, -1)
        ebits_h = np.asarray(snap["ebits"]).reshape(n, -1)
        lp = rows_h.shape[1]
        pad = lp - self._ts_log_cap
        if pad < self._chunk:
            # Re-establish the mint invariant (pad >= chunk: every
            # dynamic_slice window fits) for snapshots written by a
            # narrower-pad config (e.g. a resharded one).
            new_lp = self._ts_log_cap + self._chunk
            r2 = np.zeros((n, new_lp, w), np.uint32)
            r2[:, :lp] = rows_h
            p2 = np.full((n, new_lp), NO_GID, np.uint32)
            p2[:, :lp] = parent_h
            e2 = np.zeros((n, new_lp), np.uint32)
            e2[:, :lp] = ebits_h
            rows_h, parent_h, ebits_h = r2, p2, e2
            pad = self._chunk
            lp = new_lp
        self._ts_pad = pad
        if lp * n >= 0xFFFFFFFF:
            raise ValueError("capacity too large for 32-bit global ids")
        rows = self._ts_up(rows_h.reshape(n * lp, w))
        parent = self._ts_up(parent_h.reshape(n * lp))
        ebits = self._ts_up(ebits_h.reshape(n * lp))
        self._ts_level_start = np.asarray(
            snap["ts_level_start"], np.int64
        ).copy()
        self._ts_level_end = np.asarray(
            snap["ts_level_end"], np.int64
        ).copy()
        self._ts_tails = np.asarray(snap["ts_tails"], np.int64).copy()
        self._ts_spill_tails = np.asarray(
            snap["ts_spill_tails"], np.int64
        ).copy()
        self._ts_cand = np.asarray(snap["ts_cand"], np.int64).copy()
        self._t_depth = int(snap["ts_depth"])
        self._t_unique = int(snap["ts_unique"])
        self._t_states = int(snap["ts_states"])
        disc_np = np.asarray(snap["disc"]).astype(np.uint32)
        self._t_disc = self._ts_up(disc_np)
        self._t_disc_h = disc_np
        fps = np.asarray(snap["ts_cold_fps"])
        lens = np.asarray(snap["ts_cold_lens"], np.int64)
        runs_per = np.asarray(snap["ts_cold_runs_per_shard"], np.int64)
        self._colds = []
        fp_off = len_off = 0
        for d in range(n):
            k = int(runs_per[d])
            d_lens = lens[len_off:len_off + k]
            cnt = int(d_lens.sum())
            self._colds.append(
                ColdStore.from_arrays(
                    fps[fp_off:fp_off + cnt], d_lens,
                    spill_dir=(
                        None if self._cold_dir is None
                        else os.path.join(self._cold_dir, f"shard_{d}")
                    ),
                    max_runs=self._cold_max_runs,
                )
            )
            fp_off += cnt
            len_off += k
        # Planes are never persisted: rebuild from the committed log
        # (a kill between checkpoint and spill can therefore never
        # resurrect an aborted insert's keys).
        key_hi, key_lo = self._ts_rebuild_planes(rows)
        self._ts_hot = (
            self._ts_tails - self._ts_spill_tails
        ).astype(np.int64)
        with self._lock:
            self._state_count = self._t_states
            self._unique_count = self._t_unique
            self._max_depth = self._t_depth
            for d in range(n):
                for p, prop in enumerate(self._properties):
                    g = int(disc_np[d, p])
                    if g != NO_GID:
                        self._discovery_gids.setdefault(prop.name, g)
        if self._journal:
            self._journal.append(
                "resume",
                path=self._resume_from,
                unique=self._t_unique,
                states=self._t_states,
                depth=self._t_depth,
                cold_runs=int(sum(c.run_count for c in self._colds)),
                cold_entries=int(sum(c.entries for c in self._colds)),
            )
        return (key_hi, key_lo, rows, parent, ebits)

    # --- snapshots ------------------------------------------------------------

    def _snapshot_key(self) -> str:
        return super()._snapshot_key() + "+tiered-sharded-v1"

    def _ts_carry_dict(self, carry) -> dict:
        cold_fps = []
        cold_lens = []
        runs_per = np.zeros(self._n, np.int64)
        for d, c in enumerate(self._colds):
            f, l = c.to_arrays()
            cold_fps.append(f)
            cold_lens.append(l)
            runs_per[d] = l.shape[0]
        n_props = len(self._properties)
        return {
            "rows": carry[2],
            "parent": carry[3],
            "ebits": carry[4],
            "disc": (
                self._t_disc_h if self._t_disc_h is not None
                else np.full((self._n, n_props), NO_GID, np.uint32)
            ),
            "ts_level_start": self._ts_level_start.astype(np.int64),
            "ts_level_end": self._ts_level_end.astype(np.int64),
            "ts_tails": self._ts_tails.astype(np.int64),
            "ts_spill_tails": self._ts_spill_tails.astype(np.int64),
            "ts_cand": self._ts_cand.astype(np.int64),
            "ts_depth": np.int64(self._t_depth),
            "ts_unique": np.int64(self._t_unique),
            "ts_states": np.uint64(self._t_states),
            "ts_log_cap": np.int64(self._ts_log_cap),
            "ts_cold_fps": (
                np.concatenate(cold_fps)
                if cold_fps else np.zeros((0,), np.uint64)
            ),
            "ts_cold_lens": (
                np.concatenate(cold_lens)
                if cold_lens else np.zeros((0,), np.int64)
            ),
            "ts_cold_runs_per_shard": runs_per,
        }

    def _wl_write_checkpoint(self, carry) -> dict:
        self._write_snapshot(
            self._checkpoint_path, self._ts_carry_dict(carry)
        )
        return {
            "tail": int(self._ts_tails.sum()),
            "cold_runs": int(sum(c.run_count for c in self._colds)),
            "cold_entries": int(sum(c.entries for c in self._colds)),
        }

    # --- surface --------------------------------------------------------------

    def discovered_fingerprints(self):
        self.join()
        if self._carry_dev is None:
            raise RuntimeError("no run state to fingerprint")
        from ..parallel.wave_loop import fingerprints_of_rows

        n, w = self._n, self._compiled.state_width
        rows = np.asarray(self._carry_dev["rows"]).reshape(n, -1, w)
        segs = [rows[d, : int(self._ts_tails[d])] for d in range(n)]
        return fingerprints_of_rows(
            self._compiled, np.concatenate(segs, axis=0), self._canon
        )

    def _gid_path(self, gid: int):
        from ..core.path import Path

        with self._lock:
            if self._tables_host is None:
                if self._tables_dev is None:
                    raise RuntimeError(
                        "no run state to reconstruct paths from (the "
                        "checker did not complete cleanly)"
                    )
                parent_dev, rows_dev = self._tables_dev
                n, w = self._n, self._compiled.state_width
                self._tables_host = (
                    np.asarray(parent_dev).reshape(n, -1),
                    np.asarray(rows_dev).reshape(n, -1, w),
                )
            parent, rows = self._tables_host
        n = self._n
        chain = []
        g = gid
        while g != NO_GID:
            chain.append(g)
            g = int(parent[g % n, g // n])
        chain.reverse()
        fps = [
            self._model.fingerprint(
                self._compiled.decode(rows[g % n, g // n])
            )
            for g in chain
        ]
        return Path.from_fingerprints(self._model, fps)

    def _wl_geometry(self) -> dict:
        g = super()._wl_geometry()
        g.update(
            engine="tpu-tiered-sharded",
            memory_budget_mb=self._memory_budget_mb,
            spill_threshold=self._spill_threshold,
            log_capacity=self._ts_log_cap,
            waves_per_call=1,
        )
        return g

    def metrics(self) -> dict:
        out = super().metrics()
        out.update(
            engine="tpu-tiered-sharded",
            memory_budget_mb=self._memory_budget_mb,
            spill_threshold=self._spill_threshold,
            cold_runs=int(sum(c.run_count for c in self._colds)),
            cold_entries=int(sum(c.entries for c in self._colds)),
            cold_bytes=int(sum(c.nbytes for c in self._colds)),
        )
        return out
