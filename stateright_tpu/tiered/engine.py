"""Tiered out-of-core wavefront checker: HBM hot tier + host cold tier.

The in-HBM engines cap exact checking at what one chip's fingerprint
table holds (raft depth-12 ≈ 12.6M states was the practical ceiling,
PARITY.md).  This engine runs the SAME wavefront BFS under a fixed HBM
budget: the device hash set (parallel/hashset.py) is the *hot tier*, and
when its measured load factor (``HashSet.load_factor()``) crosses the
spill threshold, every fingerprint committed since the last spill is
evicted to the host :class:`~stateright_tpu.tiered.cold_store.ColdStore`
as a sorted immutable run and the hot table is reset — the TLC recipe
(Yu–Manolios–Lamport, PAPERS.md) lifted one level: disk→RAM becomes
HBM→host RAM (optionally disk under it).

Each wave then runs exactly the in-HBM pipeline — step kernel,
fingerprint, hot-tier ``insert_batch_compact`` dedup — plus one extra
stage: keys the hot tier reports NEW are merge-joined against the cold
tier by streaming the overlapping windows of each sorted run through the
device in bounded passes (a vmapped branchless binary search per pass,
``cold_chunk`` lanes at a time) BEFORE the append commits.  A key found
cold is a duplicate: its row is not appended, so BFS positions, parent
links, depth semantics, and the discovery set stay bit-identical to an
unconstrained run — pinned by ``discovered_fingerprints()`` equality in
tests/test_tiered.py.  (The hot tier keeps cold-hit keys as entries, so
repeat candidates of an evicted state are answered on-device without
another cold pass — a negative cache the next spill simply carries
along.)

The host loop IS the shared :class:`~stateright_tpu.parallel.wave_loop.
FusedWaveLoop` core: the engine adapts one host-driven wave per
``_wl_call`` (per-wave sync is the documented cost of the mode, like
``trace=True``), spills ride the core's ``_wl_after_commit`` rung, and
overflow flags 2/4 reuse the shared in-place growth rules while flag 1
(table overfull) SPILLS instead of growing — the budget is a hard cap.
``spill`` / ``cold_probe`` events carry bytes and pass counts for the
obs roofline; snapshots embed the whole cold store (checkpoint.npz
container), so a killed deep run resumes mid-search with its tiers
intact under the supervisor.  docs/TIERED.md documents the layout,
eviction policy, pass semantics, and resume format.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Optional

import numpy as np

from ..parallel.wavefront import (
    _PROGRAM_CACHE,
    _PROGRAM_CACHE_MAX,
    NO_SLOT_HOST,
    STAT_FLAGS,
    STAT_UNIQUE,
    TpuChecker,
    _device_owned,
    _OverflowRetry,
    _resize_flat,
)
from .cold_store import ColdStore

# Hot-table slot cost the budget maps onto: 8 B of key planes plus the
# insert's transient 4 B claim plane (hashset.py) — the peak HBM the
# table itself forces per slot.
_BYTES_PER_SLOT = 12
_MIN_CAPACITY = 256


def capacity_for_budget(memory_budget_mb: float) -> int:
    """Largest power-of-two hot-table capacity whose peak table bytes
    (key planes + transient claim plane) fit ``memory_budget_mb``.
    Fractional budgets are allowed — tests and CI force multi-spill runs
    on tiny models with budgets well under 1 MB."""
    import math

    if not math.isfinite(float(memory_budget_mb)) or memory_budget_mb <= 0:
        raise ValueError("memory_budget_mb must be a positive finite size")
    slots = int(float(memory_budget_mb) * (1 << 20)) // _BYTES_PER_SLOT
    if slots < _MIN_CAPACITY:
        # The budget is documented as a hard cap; silently rounding a
        # sub-floor budget UP to the minimum table would exceed it.
        raise ValueError(
            f"memory_budget_mb={memory_budget_mb} cannot hold the "
            f"{_MIN_CAPACITY}-slot minimum hot table "
            f"({_MIN_CAPACITY * _BYTES_PER_SLOT} bytes ≈ "
            f"{_MIN_CAPACITY * _BYTES_PER_SLOT / (1 << 20):.4f} MB)"
        )
    return 1 << (slots.bit_length() - 1)


class TieredTpuChecker(TpuChecker):
    """Budget-bounded wavefront checker behind the standard surface."""

    def __init__(
        self,
        options,
        memory_budget_mb: Optional[float] = None,
        spill_threshold: float = 0.45,
        cold_chunk: int = 1 << 15,
        cold_max_runs: int = 8,
        cold_dir: Optional[str] = None,
        **kwargs,
    ):
        """``memory_budget_mb`` bounds the HOT fingerprint table (the
        component whose size caps the in-HBM engines); when given it
        derives ``capacity``, overriding any explicit one (pass
        ``capacity`` alone to force an exact table size).  The row
        log still holds every unique state's packed row (frontier reads
        and path reconstruction need it) and keeps the base engine's
        ``log_capacity`` + auto-grow behavior — the budget is the dedup
        set's, exactly the TLC split (states on the queue, fingerprints
        in the bounded set).

        ``spill_threshold``: hot-tier load factor at which a committed
        wave triggers eviction (must leave headroom under the insert's
        50% overfull flag).  ``cold_chunk``: lanes per cold-probe pass
        (power of two; each pass streams ``8 * cold_chunk`` bytes of one
        sorted run through the device).  ``cold_max_runs``: run count
        that triggers an LSM merge.  ``cold_dir``: optional directory —
        when set, runs live on disk memory-mapped (the disk tier).

        ``trace=True`` produces per-wave phase breakdowns like the
        in-HBM engine's traced mode — the tiered loop already dispatches
        the traced-mode phase kernels separately, so tracing only adds
        the per-phase sync, not a mode switch.  The phase set gains
        ``cold_probe`` (the pre-commit merge-join; host-classed like
        ``readback``, obs/trace.py).  Visitors stay unsupported (they
        require the base traced readback path)."""
        # Intercepted, NOT forwarded: base trace=True would dispatch
        # _check_once_traced, which knows nothing of the tiers.  The
        # tiered loop does its own phase timing in _wl_call instead.
        self._t_trace = bool(kwargs.pop("trace", False))
        self._t_trace_last = None
        if self._t_trace and kwargs.get("resume_from") is not None:
            raise ValueError(
                "spawn_tpu_tiered(trace=True) does not support "
                "resume_from: tracing is a diagnostic mode; resume the "
                "run untraced and trace a fresh (bounded) run instead"
            )
        if options._visitor is not None:
            raise ValueError(
                "spawn_tpu_tiered() does not support visitors (they "
                "require the traced readback path); use spawn_tpu for "
                "visitor-instrumented runs"
            )
        if not 0.0 < float(spill_threshold) <= 0.5:
            raise ValueError(
                "spill_threshold must be in (0, 0.5]: the insert flags "
                "the table overfull beyond 50% load"
            )
        if cold_chunk < 2 or cold_chunk & (cold_chunk - 1):
            raise ValueError("cold_chunk must be a power of two >= 2")
        if memory_budget_mb is not None:
            # The budget is AUTHORITATIVE: it overrides any capacity
            # riding along in merged kwargs (workload-spec defaults, a
            # warm-started cache entry), so a job that asked for a
            # budget can never silently run un-tiered at a huge table
            # while metrics() reports the budget.  To force an exact
            # table size, pass capacity alone.
            kwargs["capacity"] = capacity_for_budget(memory_budget_mb)
        # Every tiered attribute lands BEFORE super().__init__: the base
        # constructor starts the run thread as its last statement.
        self._memory_budget_mb = (
            None if memory_budget_mb is None else float(memory_budget_mb)
        )
        self._spill_threshold = float(spill_threshold)
        self._cold_chunk = int(cold_chunk)
        self._cold = ColdStore(spill_dir=cold_dir, max_runs=cold_max_runs)
        self._hot_entries = 0  # hot-table entries since the last spill
        self._spill_tail = 0  # row-log positions below this are cold-tiered
        self._t_level_start = 0
        self._t_level_end = 0
        self._t_tail = 0
        self._t_depth = 0
        self._t_unique = 0
        self._t_states = 0
        self._t_flags = 0
        self._t_disc = None  # device uint32[P] discovery slots
        self._t_disc_h = None
        self._t_cold_last = None  # last wave's cold-probe accounting
        self._t_host_spans = []  # in-call host spans for _wl_host_spans
        super().__init__(options, **kwargs)

    # --- budget enforcement ---------------------------------------------------

    def _grow(self, flag: int):
        """Flag 1 (table overfull) never grows in tiered mode — the
        budget is a hard cap and the in-loop recovery spills instead
        (``_wl_grow``); returning None here makes a SEED-time overflow
        (init states alone overfilling the budgeted table) a loud error
        rather than a silent budget violation.  Flags 2/4 keep the base
        rules: the row log and dedup buffers are outside the table
        budget."""
        if flag & 1:
            return None
        return super()._grow(flag)

    # --- the tiered wave (one _wl_call) ---------------------------------------

    def _tiered_programs(self):
        """Cold-filter device programs, cached like every other program
        set.  ``query`` projects the insert's new-key lanes to (hi, lo)
        queries (inactive lanes become the unreachable all-ones
        sentinel) plus the min/max new key for host-side window pruning;
        ``probe`` merge-joins the queries against ONE sorted run chunk —
        a branchless lower-bound binary search, log2(cold_chunk) steps,
        all lanes in lockstep; ``fresh`` folds the accumulated found
        mask out of the append mask."""
        import jax
        import jax.numpy as jnp

        from ..parallel.wave_common import cached_program

        # The query buffers span the live insert compact width.
        u_sz = self._sort_width()
        chunk = self._cold_chunk
        key = ("tiered-cold-v2", u_sz, chunk)

        def build():
            sent = jnp.uint32(0xFFFFFFFF)

            @jax.jit
            def query(hi, lo, u_new, u_origin):
                q_hi = jnp.where(u_new, hi[u_origin], sent)
                q_lo = jnp.where(u_new, lo[u_origin], sent)
                # Lexicographic min/max of the new keys by MASKED
                # two-stage reductions: the sortless claim election
                # (hashset.insert_batch_claim, the default dedup path)
                # returns winners in LANE order, so the sorted-buffer
                # first/last-lane trick no longer applies; the
                # reductions are order-independent and cover the sorted
                # fallback path identically.
                mn_hi = jnp.min(jnp.where(u_new, q_hi, sent))
                mn_lo = jnp.min(
                    jnp.where(u_new & (q_hi == mn_hi), q_lo, sent)
                )
                mx_hi = jnp.max(jnp.where(u_new, q_hi, jnp.uint32(0)))
                mx_lo = jnp.max(
                    jnp.where(
                        u_new & (q_hi == mx_hi), q_lo, jnp.uint32(0)
                    )
                )
                return q_hi, q_lo, mn_hi, mn_lo, mx_hi, mx_lo

            @partial(jax.jit, donate_argnums=(0,))
            def probe(found, q_hi, q_lo, c_hi, c_lo):
                # Branchless lower bound over the sorted chunk: pos ends
                # at min(#elements < q, chunk-1); the equality check at
                # pos decides membership (a present key always has
                # #less < chunk, so the cap never masks a hit).  Chunk
                # tails are padded with the all-ones sentinel, which no
                # real fingerprint can equal (hashset.py).
                pos = jnp.zeros(q_hi.shape, jnp.uint32)
                half = chunk >> 1
                while half:
                    at = pos + jnp.uint32(half - 1)
                    ph = c_hi[at]
                    pl = c_lo[at]
                    less = (ph < q_hi) | ((ph == q_hi) & (pl < q_lo))
                    pos = jnp.where(less, pos + jnp.uint32(half), pos)
                    half >>= 1
                hit = (c_hi[pos] == q_hi) & (c_lo[pos] == q_lo)
                return found | hit

            @jax.jit
            def fresh_of(u_new, found):
                fresh = u_new & ~found
                return fresh, jnp.sum(fresh, dtype=jnp.uint32)

            return {"query": query, "probe": probe, "fresh": fresh_of}

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build,
            label="TieredTpuChecker.cold",
            journal=self._journal,
            provenance={"u_lanes": u_sz, "cold_chunk": chunk},
        )

    def _cold_filter(self, hi, lo, u_new, u_origin, n_new_hot):
        """Merge-join the wave's hot-tier-new keys against every cold
        run: host-side ``searchsorted`` prunes each run to the window
        overlapping [min, max] new key, and the window streams through
        the device ``cold_chunk`` lanes per pass.  Returns ``(fresh,
        n_fresh, accounting)``."""
        import jax.numpy as jnp

        tp = self._tiered_programs()
        q_hi, q_lo, mn_hi, mn_lo, mx_hi, mx_lo = tp["query"](
            hi, lo, u_new, u_origin
        )
        lo_key = (int(np.asarray(mn_hi)) << 32) | int(np.asarray(mn_lo))
        hi_key = (int(np.asarray(mx_hi)) << 32) | int(np.asarray(mx_lo))
        chunk = self._cold_chunk
        found = jnp.zeros(q_hi.shape, jnp.bool_)
        passes = 0
        runs_touched = 0
        window_entries = 0
        for run in self._cold.runs:
            a = int(np.searchsorted(run, np.uint64(lo_key), side="left"))
            b = int(np.searchsorted(run, np.uint64(hi_key), side="right"))
            if a >= b:
                continue
            runs_touched += 1
            window_entries += b - a
            for off in range(a, b, chunk):
                seg = np.asarray(run[off:off + chunk])
                if seg.shape[0] < chunk:
                    seg = np.concatenate([
                        seg,
                        np.full(
                            chunk - seg.shape[0],
                            np.uint64(0xFFFFFFFFFFFFFFFF),
                        ),
                    ])
                c_hi = (seg >> np.uint64(32)).astype(np.uint32)
                c_lo = seg.astype(np.uint32)
                found = tp["probe"](
                    found, q_hi, q_lo, jnp.asarray(c_hi), jnp.asarray(c_lo)
                )
                passes += 1
        fresh, n_fresh_d = tp["fresh"](u_new, found)
        n_fresh = int(np.asarray(n_fresh_d))
        acct = {
            "passes": passes,
            "bytes": passes * chunk * 8,
            "runs_touched": runs_touched,
            "window_entries": window_entries,
            "new": n_new_hot,
            "hits": n_new_hot - n_fresh,
        }
        return fresh, n_fresh, acct

    def _wl_call(self, carry):
        """One tiered wave: the traced-mode phase programs (step /
        fingerprint / hot insert / append — the SAME kernels as the
        fused loop) with the cold merge-join between insert and append.
        Host bookkeeping commits only at flags == 0, exactly like the
        traced loop; an aborted wave leaves every counter and buffer
        (except the hot table, which recovery rebuilds or spills) at
        its pre-wave state."""
        import jax.numpy as jnp

        key_hi, key_lo, rows, parent, ebits = carry
        self._t_trace_last = None  # set per COMMITTED wave below
        td = self._options._target_max_depth or 0
        if (
            self._t_level_end <= self._t_level_start
            or (td and self._t_depth >= td - 1)
        ):
            # Drained level (a completed snapshot being resumed — the
            # fused loop's device wave_cond gates this) or the next wave
            # would expand past the target depth: report a clean no-op
            # and let the shared termination tail stop the loop.  The
            # drained guard matters for correctness: a zero-count wave
            # would still roll the level boundary and bump the depth.
            self._t_flags = 0
            self._t_cold_last = None
            return carry
        progs = self._traced_programs()
        f_eff = self._step_width()  # the live step-geometry rung
        count = min(self._t_level_end - self._t_level_start, f_eff)
        disc_prev = self._t_disc  # t_step does not donate it
        trace = self._t_trace
        if trace:
            import jax

            t = [time.perf_counter()]
        (
            disc, eb, _states, cand_rows, cand_src, cand_act,
            _n_valid_d, v_ovf_d, gen_d, stepflag_d,
        ) = progs["step"](
            rows, ebits, disc_prev,
            jnp.uint32(self._t_level_start), jnp.uint32(self._t_level_end),
        )
        if trace:
            jax.block_until_ready(cand_rows)
            t.append(time.perf_counter())
        hi, lo = progs["fp"](cand_rows)
        if trace:
            jax.block_until_ready(lo)
            t.append(time.perf_counter())
        (
            key_hi, key_lo, u_new, u_origin, n_new_d, probe_ok_d,
            dd_ovf_d, rounds_d,
        ) = progs["insert"](key_hi, key_lo, hi, lo, cand_act)
        if trace:
            jax.block_until_ready(key_lo)
            t.append(time.perf_counter())
        n_new_hot = int(np.asarray(n_new_d))
        flags = 0
        if (
            not bool(np.asarray(probe_ok_d))
            or (self._hot_entries + n_new_hot) * 2 > self._capacity
        ):
            flags |= 1
        if bool(np.asarray(dd_ovf_d)) or bool(np.asarray(v_ovf_d)):
            flags |= 4
        if bool(np.asarray(stepflag_d)):
            flags |= 8
        if (
            f_eff < self._max_frontier
            and self._t_level_end - self._t_level_start > f_eff
        ):
            # Step-rung clamp (flag 128, non-committing): climb one
            # chunk rung and re-run — the base engine's contract.
            flags |= 128

        if trace:
            t.append(time.perf_counter())  # readback: the scalar syncs

        cold = None
        fresh, n_fresh = u_new, n_new_hot
        if flags == 0 and n_new_hot and self._cold.run_count:
            t_cp = time.monotonic()
            fresh, n_fresh, cold = self._cold_filter(
                hi, lo, u_new, u_origin, n_new_hot
            )
            # Host-side cold windowing inside the call window: handed to
            # the shared loop's SpanRecorder via _wl_host_spans so the
            # timeline decomposes it without a second timer pass.
            self._t_host_spans.append(
                ("cold_probe", t_cp, time.monotonic() - t_cp)
            )
        if trace:
            t.append(time.perf_counter())
        if flags == 0 and self._t_tail + n_fresh > self._log_capacity:
            flags |= 2

        if flags == 0:
            rows, parent, ebits = progs["append"](
                rows, parent, ebits, cand_rows, cand_src, eb, fresh,
                u_origin, jnp.uint32(self._t_level_start),
                jnp.uint32(self._t_tail),
            )
            if trace:
                jax.block_until_ready(ebits)
                t.append(time.perf_counter())
                from ..parallel.wave_common import two_phase_capable

                phases = {
                    "step": t[1] - t[0],
                    "canon": t[2] - t[1],
                    "dedup": t[3] - t[2],
                    "readback": t[4] - t[3],
                    "cold_probe": t[5] - t[4],
                    "append": t[6] - t[5],
                }
                # Modeled device bytes: the base phase model (these ARE
                # the base phase kernels) — cold_probe bytes stay out of
                # the HBM model (host-classed, obs/trace.py) and ride
                # the cold accounting instead.
                self._t_trace_last = self._tracer.record_wave(
                    phases,
                    self._traced_wave_bytes(
                        int(np.asarray(rounds_d)),
                        two_phase_capable(self._compiled),
                    ),
                )
            self._hot_entries += n_new_hot
            self._t_tail += n_fresh
            self._t_unique += n_fresh
            self._t_states += int(np.asarray(gen_d))
            self._t_level_start += count
            if self._t_level_start >= self._t_level_end:
                self._t_depth += 1
                self._t_level_end = self._t_tail
            if cold is not None:
                if self._journal:
                    self._journal.append(
                        "cold_probe",
                        depth=self._t_depth,
                        unique=self._t_unique,
                        **cold,
                    )
                self._metrics.inc("cold_probe_passes_total", cold["passes"])
                self._metrics.inc("cold_probe_bytes_total", cold["bytes"])
                self._metrics.inc("cold_hits_total", cold["hits"])
        # An aborted wave's discoveries REVERT, like the fused loop's
        # on-device `disc = where(commit, disc, disc_prev)`: a kept
        # discovery would change the re-run's awaiting mask (wave_eval
        # prunes expansion of states that contribute nothing once a
        # property is discovered), generating different successors than
        # a committed execution — breaking the bit-identical pin.
        # Decided HERE, after every flag (incl. the late row-log check
        # above) is final, so a flag-2 abort cannot leak discoveries.
        self._t_disc = disc if flags == 0 else disc_prev
        self._t_disc_h = np.asarray(self._t_disc)
        self._t_flags = flags
        self._t_cold_last = cold
        return (key_hi, key_lo, rows, parent, ebits)

    def _wl_view(self, carry):
        from ..parallel.wave_loop import WaveView

        disc = []
        for p, prop in enumerate(self._properties):
            s = int(self._t_disc_h[p])
            if s != NO_SLOT_HOST:
                disc.append((prop.name, s))
        extra = {
            "tail": self._t_tail,
            "hot_entries": self._hot_entries,
            "cold_runs": self._cold.run_count,
        }
        if self._t_cold_last is not None:
            extra["cold_passes"] = self._t_cold_last["passes"]
            extra["cold_bytes"] = self._t_cold_last["bytes"]
        if self._t_trace_last is not None:
            # Traced runs: the wave's phase breakdown rides the shared
            # loop's journal "wave" event, like the base traced loop.
            extra.update(self._t_trace_last)
        return WaveView(
            waves_this_call=1,
            remaining=self._t_level_end - self._t_level_start,
            depth=self._t_depth,
            flags=self._t_flags,
            unique=self._t_unique,
            states=self._t_states,
            occupancy=self._hot_entries / self._capacity,
            discoveries=tuple(disc),
            extra=extra,
        )

    def _wl_host_spans(self):
        """Fused-loop hook (obs/timeline.py ``SpanRecorder.collect``):
        drain the in-call host spans ``_wl_call`` measured itself —
        the cold-run windowing (``cold_probe``), which runs on the host
        INSIDE the device-call window and would otherwise vanish into
        the opaque ``call_sec``."""
        spans = self._t_host_spans
        if spans:
            self._t_host_spans = []
        return spans

    # --- spill / recovery -----------------------------------------------------

    def _wl_after_commit(self, carry, view):
        """The eviction trigger, on the shared loop's post-commit rung.
        The per-wave decision uses the host-tracked occupancy
        (``view.occupancy`` = hot entries / capacity, exact by
        bookkeeping: inserts add ``n_new_hot``, spills reset, recovery
        rehashes set the segment count) — no device traffic on the
        common path.  At the spill decision point the MEASURED
        ``HashSet.load_factor()`` readback confirms against the key
        planes themselves (one scalar sync per SPILL, not per wave) and
        is what the ``spill`` journal event and ``hot_load_factor``
        metric record."""
        if view.occupancy >= self._spill_threshold:
            from ..parallel.hashset import HashSet

            lf = HashSet(carry[0], carry[1]).load_factor()
            self._metrics.update(hot_load_factor=round(lf, 6))
            carry = self._spill(carry, reason="threshold", load_factor=lf)
        return carry

    def _spill(self, carry, reason: str, load_factor: float):
        """Evict the hot tier: fingerprints of row-log positions
        ``[spill_tail, tail)`` become one sorted immutable cold run
        (computed FROM THE LOG, so keys an aborted insert scribbled
        into the table can never leak into the cold tier), the hot
        table resets to empty, and the watermark advances.  Hot-tier
        cold-hit cache entries are simply dropped — they are in an
        earlier run already."""
        key_hi, key_lo, rows, parent, ebits = carry
        from ..parallel.hashset import make_hashset

        start, end = self._spill_tail, self._t_tail
        t0 = time.monotonic()
        fps = self._segment_fingerprints(rows, start, end)
        self._cold.add_run(fps)
        self._hot_entries = 0
        self._spill_tail = end
        if self._journal:
            self._journal.append(
                "spill",
                reason=reason,
                entries=int(fps.shape[0]),
                bytes=int(fps.nbytes),
                start=start,
                end=end,
                load_factor=round(float(load_factor), 6),
                cold_runs=self._cold.run_count,
                cold_entries=self._cold.entries,
                spill_sec=round(time.monotonic() - t0, 4),
            )
        self._metrics.inc("spills", 1)
        self._metrics.inc("spill_bytes_total", int(fps.nbytes))
        self._metrics.update(
            cold_runs=self._cold.run_count,
            cold_entries=self._cold.entries,
            cold_bytes=self._cold.nbytes,
        )
        t = make_hashset(self._capacity)
        return (t.key_hi, t.key_lo, rows, parent, ebits)

    def _segment_fp_program(self):
        """Device program fingerprinting one row-log chunk — the spill
        readback (O(segment) through the device fp kernel, canonical
        keys when symmetry is on, exactly what the hot tier stored)."""
        import jax
        import jax.numpy as jnp

        from ..ops.device_fp import device_fp64
        from ..parallel.wave_common import cached_program

        cm = self._compiled
        w = cm.state_width
        fpw = cm.fp_words or w
        r = self._max_frontier
        canon = self._canon
        key = ("tiered-segfp", w, fpw, r, canon is not None,
               cm.cache_key() if canon is not None else None)

        def build():
            @jax.jit
            def seg_fp(rows, start):
                states = jax.lax.dynamic_slice(
                    rows, (start * jnp.uint32(w),), (r * w,)
                ).reshape(r, w)
                states_c = (
                    states if canon is None else jax.vmap(canon)(states)
                )
                return device_fp64(states_c[:, :fpw])

            return seg_fp

        return cached_program(
            _PROGRAM_CACHE, _PROGRAM_CACHE_MAX, key, build,
            label="TieredTpuChecker.segfp",
            journal=self._journal,
            provenance={"max_frontier": r},
        )

    def _segment_fingerprints(self, rows, start: int, end: int):
        """uint64 dedup-key fingerprints of row-log positions
        ``[start, end)``, in log order (the cold store sorts)."""
        import jax.numpy as jnp

        if end <= start:
            return np.zeros((0,), np.uint64)
        prog = self._segment_fp_program()
        r = self._max_frontier
        out = []
        for off in range(start, end, r):
            hi, lo = prog(rows, jnp.uint32(off))
            n = min(r, end - off)
            hi = np.asarray(hi)[:n].astype(np.uint64)
            lo = np.asarray(lo)[:n].astype(np.uint64)
            out.append((hi << np.uint64(32)) | lo)
        return np.concatenate(out)

    def _wl_grow(self, flags: int, carry):
        """In-place recovery for an aborted tiered wave.  Flags 2/4 use
        the base growth rules (row log ×2, dedup relax toward 1); flag 1
        SPILLS — the memory budget pins the table capacity, and after
        eviction the empty hot tier re-runs the same chunk (its states
        now answered by the cold tier).  Either way the hot table is
        rebuilt from scratch, erasing any keys the aborted insert
        wrote: a spill re-derives the run from the row log, a non-spill
        recovery rehashes the committed ``[spill_tail, tail)`` segment."""
        from ..parallel.wave_loop import log_grow

        key_hi, key_lo, rows, parent, ebits = carry
        notes = []
        spill = False
        for bit in (2, 4, 128):
            if flags & bit:
                g = self._grow(bit) if self._auto_tune else None
                if g is None:
                    return None
                notes.append(g)
        if flags & 1:
            if self._hot_entries:
                spill = True
                notes.append(
                    f"spill (budget pins capacity={self._capacity})"
                )
            else:
                # The table is already empty (the previous recovery just
                # spilled): this ONE wave's distinct new keys overflow
                # the budgeted table, so eviction cannot converge —
                # shrink the chunk until each wave inserts less than the
                # table holds.  The floor is deliberately tiny: at a
                # pathological budget, crawling 8 states a wave is still
                # correct, and a loud refusal only remains for chunks
                # that cannot shrink further.
                if self._max_frontier <= 8:
                    return None
                self._max_frontier //= 2
                notes.append(f"max_frontier={self._max_frontier}")
        log_grow(
            self, flags, "; ".join(notes), self._t_unique, self._t_depth
        )
        new_qcap = self._log_capacity
        new_pad = self._block_pad()
        if (new_qcap + new_pad) != (self._loop_qcap + self._loop_pad):
            n_len = new_qcap + new_pad
            rows = _resize_flat(
                rows, n_len * self._compiled.state_width, 0
            )
            parent = _resize_flat(parent, n_len, NO_SLOT_HOST)
            ebits = _resize_flat(ebits, n_len, 0)
        self._loop_qcap, self._loop_pad = new_qcap, new_pad
        carry = (key_hi, key_lo, rows, parent, ebits)
        if spill:
            return self._spill(
                carry, reason="overflow",
                load_factor=self._hot_entries / self._capacity,
            )
        kh, kl = self._rehash(rows, self._t_tail, self._spill_tail)
        # The rebuilt table holds exactly the committed segment — any
        # cold-duplicate cache entries the old table carried are gone
        # (they live in earlier runs), so the occupancy bookkeeping must
        # match or the flag-1 gate and journal occupancy would
        # overestimate until the next spill.
        self._hot_entries = self._t_tail - self._spill_tail
        return (kh, kl, rows, parent, ebits)

    def _wl_overflow_message(self, flags: int) -> str:
        if flags & 8:
            return super()._wl_overflow_message(flags)
        return f"tiered engine overflow flags={flags}"

    def _wl_abort_cleanup(self, carry):
        """The keep-partial-break analog of the base hook, scoped to
        the tiers: rebuild the hot table from the committed
        ``[spill_tail, tail)`` segment so a persisted carry never
        carries an aborted wave's keys (a resume would otherwise drop
        that wave's states as hot-tier duplicates)."""
        kh, kl = self._rehash(carry[2], self._t_tail, self._spill_tail)
        self._hot_entries = self._t_tail - self._spill_tail
        return (kh, kl, carry[2], carry[3], carry[4])

    # --- run setup / teardown (the host side of _check_once) ------------------

    def _check_once(self, deadline=None) -> None:
        import jax
        import jax.numpy as jnp

        cm = self._compiled
        props = self._properties

        def sized(arr_np, n):
            if arr_np.shape[0] < n:
                return np.concatenate(
                    [arr_np, np.zeros(n - arr_np.shape[0], arr_np.dtype)]
                )
            return arr_np[:n]

        if self._resume_from is not None:
            snap = np.load(self._resume_from, allow_pickle=False)
            if "tiered_spill_tail" not in snap.files:
                raise ValueError(
                    "snapshot was not written by the tiered engine (no "
                    "persisted cold tier); resume it with spawn_tpu, or "
                    "re-run the tiered check to produce a tiered snapshot"
                )
            if self._memory_budget_mb is not None and (
                capacity_for_budget(self._memory_budget_mb)
                != int(snap["capacity"])
            ):
                # The budget is authoritative (never silently overridden
                # while metrics() reports it), but a resume must adopt
                # the snapshot's table — the two promises can only both
                # hold when they agree, so a mismatch is a loud error
                # naming both sides, like the engine-key check below.
                raise ValueError(
                    f"resume memory_budget_mb={self._memory_budget_mb} "
                    f"implies a "
                    f"{capacity_for_budget(self._memory_budget_mb)}-slot "
                    f"hot table, but the snapshot was written at "
                    f"capacity={int(snap['capacity'])}; resume with the "
                    "snapshot's original budget (or with capacity "
                    "kwargs alone to adopt its geometry)"
                )
            # Adopt the snapshot's geometry, like the base engine.
            self._capacity = int(snap["capacity"])
            self._log_capacity = int(snap["log_capacity"])

        f = self._max_frontier
        qcap = self._log_capacity
        pad = self._block_pad()

        with jax.default_device(self._device):
            seed, _run = self._programs()
            if self._resume_from is not None:
                want_key = self._snapshot_key()
                got_key = str(snap["engine_key"])
                if got_key != want_key:
                    raise ValueError(
                        "snapshot does not match this checker configuration"
                        f" (snapshot {got_key}, expected {want_key})"
                    )
                key_hi = _device_owned(jnp.asarray(snap["key_hi"]))
                key_lo = _device_owned(jnp.asarray(snap["key_lo"]))
                rows = _device_owned(jnp.asarray(sized(
                    np.asarray(snap["rows"]), (qcap + pad) * cm.state_width
                )))
                parent = _device_owned(jnp.asarray(
                    sized(np.asarray(snap["parent"]), qcap + pad)
                ))
                ebits = _device_owned(jnp.asarray(
                    sized(np.asarray(snap["ebits"]), qcap + pad)
                ))
                disc_np = np.asarray(snap["disc"]).astype(np.uint32)
                self._t_disc = _device_owned(jnp.asarray(disc_np))
                self._t_disc_h = disc_np
                self._t_level_start = int(snap["level_start"])
                self._t_level_end = int(snap["level_end"])
                self._t_tail = int(snap["tail"])
                self._t_depth = int(snap["depth"])
                self._t_unique = int(snap["unique_count"])
                self._t_states = (
                    int(snap["sc_hi"]) << 32
                ) | int(snap["sc_lo"])
                self._spill_tail = int(snap["tiered_spill_tail"])
                self._hot_entries = int(snap["tiered_hot_entries"])
                self._cold = ColdStore.from_arrays(
                    np.asarray(snap["tiered_cold_fps"]),
                    np.asarray(snap["tiered_cold_lens"]),
                    spill_dir=self._cold.spill_dir,
                    max_runs=self._cold.max_runs,
                )
                with self._lock:
                    self._state_count = self._t_states
                    self._unique_count = self._t_unique
                    self._max_depth = self._t_depth
                    for p, prop in enumerate(props):
                        if int(disc_np[p]) != NO_SLOT_HOST:
                            self._discovery_slots[prop.name] = int(disc_np[p])
                if self._journal:
                    self._journal.append(
                        "resume",
                        path=self._resume_from,
                        unique=self._t_unique,
                        states=self._t_states,
                        depth=self._t_depth,
                        cold_runs=self._cold.run_count,
                        cold_entries=self._cold.entries,
                        spill_tail=self._spill_tail,
                    )
            else:
                init = cm.init_packed()
                n_init = init.shape[0]
                if n_init > f:
                    raise ValueError(
                        f"{n_init} init states exceed the chunk size "
                        f"({f}); raise max_frontier to at least the "
                        "init-state count (interior levels are unbounded)"
                    )
                key_hi, key_lo, rows, parent, ebits, stats = seed(
                    jnp.asarray(init.astype(np.uint32)), jnp.uint32(n_init)
                )
                stats_h = np.asarray(stats)
                if int(stats_h[STAT_FLAGS]):
                    raise _OverflowRetry(
                        1,
                        "init-state seeding overflowed the budgeted "
                        "fingerprint table; raise memory_budget_mb (or "
                        "pass capacity=) past the init-state count",
                    )
                fcount = int(stats_h[STAT_UNIQUE])
                self._t_level_start = 0
                self._t_level_end = fcount
                self._t_tail = fcount
                self._t_depth = 0
                self._t_unique = fcount
                self._t_states = n_init
                self._hot_entries = fcount
                self._spill_tail = 0
                self._t_disc = _device_owned(jnp.asarray(
                    np.full((len(props),), NO_SLOT_HOST, np.uint32)
                ))
                self._t_disc_h = np.asarray(self._t_disc)
                with self._lock:
                    self._state_count = n_init
                    self._unique_count = fcount

            from ..parallel.wave_loop import FusedWaveLoop, finalize_run

            if self._t_trace:
                from ..obs.trace import WaveTracer

                self._tracer = WaveTracer(self._device, "tpu-tiered")
            self._loop_qcap, self._loop_pad = qcap, pad
            carry = (key_hi, key_lo, rows, parent, ebits)
            carry, _waves = FusedWaveLoop(self).run(carry, deadline)
            key_hi, key_lo, rows, parent, ebits = carry
            self._tables_dev = (parent, rows)
            if self._tracer is not None and self._journal:
                self._journal.append(
                    "trace_summary", **self._tracer.summary()
                )
            finalize_run(self, self._carry_from(
                key_hi, key_lo, rows, parent, ebits, self._stats_np()
            ))

    def _stats_np(self) -> np.ndarray:
        """Host bookkeeping in the base engine's stats-vector layout, so
        ``_carry_from`` / snapshots share one npz schema."""
        return np.concatenate([
            np.array(
                [
                    self._t_level_start,
                    self._t_level_end,
                    self._t_tail,
                    self._t_states & 0xFFFFFFFF,
                    (self._t_states >> 32) & 0xFFFFFFFF,
                    self._t_unique,
                    self._t_depth,
                    0,
                ],
                np.uint32,
            ),
            np.asarray(self._t_disc_h, np.uint32),
        ])

    def _wl_write_checkpoint(self, carry) -> dict:
        self._write_snapshot(
            self._checkpoint_path,
            self._carry_from(
                carry[0], carry[1], carry[2], carry[3], carry[4],
                self._stats_np(),
            ),
        )
        return {
            "tail": self._t_tail,
            "cold_runs": self._cold.run_count,
            "cold_entries": self._cold.entries,
        }

    def _snapshot_key(self) -> str:
        # Tiered snapshots are NOT plain-engine resumable (the hot table
        # holds only the post-spill suffix), and vice versa.
        return super()._snapshot_key() + "+tiered-v1"

    def _snapshot_extra(self) -> dict:
        """The tier state beside the base snapshot fields: the cold
        store's runs (concatenated + per-run lengths, so a resume
        restores the exact run shape), the spill watermark, and the
        hot-entry count — all inside the one checkpoint.npz container
        the supervisor already rotates atomically (the atomic-write
        body itself lives once, in the base ``_write_snapshot``)."""
        cold_fps, cold_lens = self._cold.to_arrays()
        return {
            "tiered_cold_fps": cold_fps,
            "tiered_cold_lens": cold_lens,
            "tiered_spill_tail": self._spill_tail,
            "tiered_hot_entries": self._hot_entries,
        }

    # --- surface --------------------------------------------------------------

    def tuned_kwargs(self) -> dict:
        """Right-sized kwargs for a repeat run — with ``capacity``
        PINNED at this run's budgeted size (the base rule of ≥2× the
        unique count would silently un-tier the workload)."""
        out = super().tuned_kwargs()
        out["capacity"] = self._capacity
        return out

    def _wl_geometry(self) -> dict:
        out = super()._wl_geometry()
        out["engine"] = "tpu-tiered"
        out["spill_threshold"] = self._spill_threshold
        if self._memory_budget_mb is not None:
            out["memory_budget_mb"] = self._memory_budget_mb
        return out

    def metrics(self) -> dict:
        out = super().metrics()
        out.update(
            engine="tpu-tiered",
            spill_threshold=self._spill_threshold,
            cold_chunk=self._cold_chunk,
            cold_runs=self._cold.run_count,
            cold_entries=self._cold.entries,
            cold_bytes=self._cold.nbytes,
            hot_entries=self._hot_entries,
            spill_tail=self._spill_tail,
        )
        if self._memory_budget_mb is not None:
            out["memory_budget_mb"] = self._memory_budget_mb
        return out
