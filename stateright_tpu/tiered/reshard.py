"""Elastic resharding: re-key a sharded snapshot onto a different mesh.

Sharded global state ids encode the owner shard, so a snapshot written
on an n-shard mesh cannot simply resume on m != n shards — every parent
pointer and discovery gid would point at the wrong row.  This module is
the offline translation: it reads a snapshot written by EITHER sharded
engine (``ShardedTpuChecker`` slot-layout or ``TieredShardedTpuChecker``
positional-log layout), re-routes every row to its owner under the new
mesh width with the SAME host owner mix the engines use
(``_owner_mix_host_np`` — host/device parity is pinned by tests), and
writes a **tiered-sharded** snapshot for the new width:

- each new shard's log keeps BFS segment order (visited rows, then the
  current frontier, then the accumulating next level — within a segment,
  old shards in index order, old log order within a shard), so a resume
  continues the same level structure the old run was mid-way through;
- parent gids and per-shard discovery gids are remapped through the full
  old-gid → new-gid table;
- the hot tier restarts EMPTY: every row's fingerprint becomes one
  sorted cold run per new shard (the log is the source of truth for
  rows; cold runs only need fingerprints), so the resumed run's first
  waves rebuild hot occupancy organically and correctness never depends
  on re-splitting the old hot/cold watermarks.

The output is always a tiered-sharded snapshot — resume it with
``spawn_tpu_tiered_sharded`` (or ``check-tpu --tiered --sharded=M
--resume``).  The discovery-set bit-equality pin holds across the
conversion: dedup is exact and the level structure is preserved, so the
continued run visits exactly the states the uninterrupted run would
(tests/test_tiered_sharded.py pins 8→4 and 4→8 against the
unconstrained engine).

Everything here runs on the host (single-device fingerprint evaluation,
no mesh), so a snapshot can be resharded on a coordinator node — or any
CPU — without claiming the target mesh.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

import numpy as np

from ..parallel.sharded import NO_GID, S_DISC, S_LEVEL_START, \
    S_LEVEL_END, S_TAIL, S_SC_LO, S_SC_HI, S_UNIQUE_G, S_DEPTH, \
    S_CAND_LO, S_CAND_HI, _owner_mix_host_np

_TS_SUFFIX = "+tiered-sharded-v1"


def _rekey_engine_key(old_key: str, new_shards: int) -> str:
    """The snapshot engine key with its shard-count element replaced.

    Both sharded engine keys are ``repr()`` of a tuple whose index 6 is
    the mesh width (parallel/sharded.py _snapshot_key), the tiered
    variant with a version suffix appended; parse, substitute, re-repr."""
    base = old_key
    if base.endswith(_TS_SUFFIX):
        base = base[: -len(_TS_SUFFIX)]
    parts = list(ast.literal_eval(base))
    parts[6] = new_shards
    return repr(tuple(parts)) + _TS_SUFFIX


def _segments_from_plain(snap, compiled, canon):
    """Per-old-shard (rows, parent_gids, ebits, seg boundaries) in BFS
    log order from a plain ShardedTpuChecker snapshot (slot-layout
    store + insertion-order queue), plus an old-gid decoder."""
    n = int(snap["n_shards"])
    cap_s = int(snap["cap_s"])
    slot_bits = cap_s.bit_length() - 1
    w = compiled.state_width
    store = np.asarray(snap["store"]).reshape(n, cap_s, w)
    parent = np.asarray(snap["parent"]).reshape(n, cap_s)
    ebits = np.asarray(snap["ebits"]).reshape(n, cap_s)
    queue = np.asarray(snap["queue"]).reshape(n, -1)
    stats = np.asarray(snap["stats"]).astype(np.int64).reshape(n, -1)
    shards = []
    # slot -> log position inverse, for decoding parent gids.
    inv = np.zeros((n, cap_s), np.int64)
    for d in range(n):
        tail = int(stats[d, S_TAIL])
        slots = queue[d, :tail].astype(np.int64)
        inv[d, slots] = np.arange(tail)
        shards.append({
            "rows": store[d, slots],
            "parent": parent[d, slots],
            "ebits": ebits[d, slots],
            "level_start": int(stats[d, S_LEVEL_START]),
            "level_end": int(stats[d, S_LEVEL_END]),
            "tail": tail,
        })

    def decode(g):
        d = g >> slot_bits
        return d, int(inv[d, g & (cap_s - 1)])

    meta = {
        "n": n,
        "depth": int(stats[0, S_DEPTH]),
        "unique": int(stats[0, S_UNIQUE_G]),
        "states": (int(stats[0, S_SC_HI]) << 32) | int(stats[0, S_SC_LO]),
        "cand": int(
            (
                (stats[:, S_CAND_HI] << 32) | stats[:, S_CAND_LO]
            ).sum()
        ),
        "disc": stats[:, S_DISC:].astype(np.uint32),
    }
    return shards, decode, meta


def _segments_from_tiered(snap, compiled):
    """Same, from a TieredShardedTpuChecker snapshot (positional log:
    gid = pos * n + shard, rows already in BFS order)."""
    n = int(snap["n_shards"])
    w = compiled.state_width
    rows = np.asarray(snap["rows"]).reshape(n, -1, w)
    parent = np.asarray(snap["parent"]).reshape(n, -1)
    ebits = np.asarray(snap["ebits"]).reshape(n, -1)
    starts = np.asarray(snap["ts_level_start"], np.int64)
    ends = np.asarray(snap["ts_level_end"], np.int64)
    tails = np.asarray(snap["ts_tails"], np.int64)
    shards = []
    for d in range(n):
        tail = int(tails[d])
        shards.append({
            "rows": rows[d, :tail],
            "parent": parent[d, :tail],
            "ebits": ebits[d, :tail],
            "level_start": int(starts[d]),
            "level_end": int(ends[d]),
            "tail": tail,
        })

    def decode(g):
        return g % n, g // n

    meta = {
        "n": n,
        "depth": int(snap["ts_depth"]),
        "unique": int(snap["ts_unique"]),
        "states": int(snap["ts_states"]),
        "cand": int(np.asarray(snap["ts_cand"], np.int64).sum()),
        "disc": np.asarray(snap["disc"]).astype(np.uint32),
    }
    return shards, decode, meta


def reshard_snapshot(
    model,
    in_path: str,
    out_path: str,
    new_shards: int,
    compiled=None,
    journal=None,
) -> dict:
    """Re-key the sharded snapshot at ``in_path`` onto a ``new_shards``
    mesh, writing a tiered-sharded snapshot at ``out_path``.

    ``model`` identifies the checked system (the canonical fingerprints
    and — under symmetry — the canonicalizer come from its compiled
    form, exactly as the engines derive them).  Returns a summary dict
    (per-new-shard tails, the re-keyed engine key, counters) and, when
    ``journal`` is given, appends one ``reshard`` event to it."""
    from ..parallel.compiled import compiled_model_for
    from ..parallel.wave_loop import fingerprints_of_rows

    if new_shards < 1:
        raise ValueError("new_shards must be >= 1")
    cm = compiled if compiled is not None else compiled_model_for(model)
    snap = np.load(in_path, allow_pickle=False)
    required = {"engine_key", "n_shards", "cap_s", "chunk"}
    if not required.issubset(set(snap.files)):
        raise ValueError(
            f"{in_path} is not a sharded engine snapshot (missing "
            f"{sorted(required - set(snap.files))})"
        )
    tiered_in = "ts_tails" in snap.files
    # Canonical-fp snapshots carry a ("sym",) tail on the engine-key
    # tuple; their ownership routing ran on canonical fingerprints, so
    # the re-key must too.
    key_str = str(snap["engine_key"])
    key_tuple = ast.literal_eval(
        key_str[: -len(_TS_SUFFIX)]
        if key_str.endswith(_TS_SUFFIX) else key_str
    )
    canon = None
    if "sym" in key_tuple[7:]:
        from ..parallel.canon import make_canon

        canon = make_canon(cm)
        if canon is None:
            raise ValueError(
                "snapshot was written with symmetry canonicalization "
                f"but {type(cm).__name__} declares no canonicalization"
            )
    if tiered_in:
        old, decode, meta = _segments_from_tiered(snap, cm)
    else:
        old, decode, meta = _segments_from_plain(snap, cm, canon)
    n = meta["n"]
    m = int(new_shards)
    w = cm.state_width

    # Route every old row to its new owner (the engines' host owner
    # mix on the canonical fingerprint — host/device parity pinned).
    owners = []
    fps_all = []
    for seg in old:
        if seg["tail"]:
            fps = fingerprints_of_rows(cm, seg["rows"], canon, sort=False)
        else:
            fps = np.zeros((0,), np.uint64)
        fps_all.append(fps)
        owners.append(
            _owner_mix_host_np(
                (fps >> np.uint64(32)).astype(np.uint32),
                (fps & np.uint64(0xFFFFFFFF)).astype(np.uint32),
            ).astype(np.int64) % m
        )

    # New logs keep the BFS segment order: visited ++ frontier ++ next,
    # old shards in index order within each segment — the (d, pos) ->
    # (e, new_pos) table doubles as the gid remap.
    new_pos = [np.zeros(seg["tail"], np.int64) for seg in old]
    new_owner = owners
    counts = np.zeros(m, np.int64)
    bounds = np.zeros((m, 2), np.int64)  # (level_start, level_end)
    for bound_idx, lo_key, hi_key in (
        (None, None, "level_start"),
        (0, "level_start", "level_end"),
        (1, "level_end", "tail"),
    ):
        if bound_idx is not None:
            bounds[:, bound_idx] = counts
        for d, seg in enumerate(old):
            lo = 0 if lo_key is None else seg[lo_key]
            hi = seg[hi_key]
            for p in range(lo, hi):
                e = int(new_owner[d][p])
                new_pos[d][p] = counts[e]
                counts[e] += 1

    tails_new = counts
    max_tail = int(tails_new.max()) if m else 0
    log_cap = 1 << max(max_tail, 1).bit_length()  # >= 2x headroom
    chunk = int(snap["chunk"])
    pad = chunk
    lp = log_cap + pad
    if lp * m >= 0xFFFFFFFF:
        raise ValueError(
            f"resharding onto {m} shards needs {lp * m} global ids, "
            "past the 32-bit gid space; use fewer, larger shards"
        )

    def remap_gid(g: int) -> int:
        if g == NO_GID:
            return NO_GID
        d, p = decode(g)
        return int(new_pos[d][p]) * m + int(new_owner[d][p])

    rows_new = np.zeros((m, lp, w), np.uint32)
    parent_new = np.full((m, lp), NO_GID, np.uint32)
    ebits_new = np.zeros((m, lp), np.uint32)
    cold_fps = [[] for _ in range(m)]
    for d, seg in enumerate(old):
        if not seg["tail"]:
            continue
        e = new_owner[d]
        p = new_pos[d]
        rows_new[e, p] = seg["rows"]
        ebits_new[e, p] = seg["ebits"]
        par = seg["parent"].astype(np.int64)
        parent_new[e, p] = np.array(
            [remap_gid(int(g)) for g in par], np.uint32
        )
        for j in range(m):
            sel = e == j
            if sel.any():
                cold_fps[j].append(fps_all[d][sel])

    n_props = meta["disc"].shape[1]
    disc_new = np.full((m, n_props), NO_GID, np.uint32)
    for d in range(n):
        for p in range(n_props):
            g = int(meta["disc"][d, p])
            if g == NO_GID:
                continue
            g2 = remap_gid(g)
            e = g2 % m
            if disc_new[e, p] == NO_GID:
                disc_new[e, p] = g2

    # The whole log spills: one sorted cold run per new shard, hot tier
    # empty (spill_tail == tail).  Run lengths are pre-sort counts; the
    # store contract only needs each run internally sorted.
    runs_per = np.zeros(m, np.int64)
    flat_fps = []
    flat_lens = []
    for j in range(m):
        fps = (
            np.sort(np.concatenate(cold_fps[j]))
            if cold_fps[j] else np.zeros((0,), np.uint64)
        )
        if fps.size:
            flat_fps.append(fps)
            flat_lens.append(fps.size)
            runs_per[j] = 1
    zeros_m = np.zeros(m, np.int64)
    out = {
        "engine_key": _rekey_engine_key(str(snap["engine_key"]), m),
        "n_shards": np.int64(m),
        "cap_s": np.int64(int(snap["cap_s"])),
        "chunk": np.int64(chunk),
        "rows": rows_new.reshape(m * lp, w),
        "parent": parent_new.reshape(m * lp),
        "ebits": ebits_new.reshape(m * lp),
        "disc": disc_new,
        "ts_level_start": bounds[:, 0],
        "ts_level_end": bounds[:, 1],
        "ts_tails": tails_new,
        "ts_spill_tails": tails_new.copy(),
        # Candidate accounting is global-true but per-shard-unknowable
        # after a re-key; spread evenly so the sum survives.
        "ts_cand": np.full(m, meta["cand"] // m, np.int64)
        + (np.arange(m) < meta["cand"] % m),
        "ts_depth": np.int64(meta["depth"]),
        "ts_unique": np.int64(meta["unique"]),
        "ts_states": np.uint64(meta["states"]),
        "ts_log_cap": np.int64(log_cap),
        "ts_cold_fps": (
            np.concatenate(flat_fps)
            if flat_fps else np.zeros((0,), np.uint64)
        ),
        "ts_cold_lens": np.asarray(flat_lens, np.int64),
        "ts_cold_runs_per_shard": runs_per,
        "ts_spill_counts": zeros_m,
    }
    for k in ("bucket_slack", "sort_lanes", "sortless", "step_lanes"):
        if k in snap.files:
            out[k] = np.asarray(snap[k])
    tmp = out_path + ".tmp"
    np.savez_compressed(tmp, **out)
    # np.savez appends .npz to a suffix-less temp name.
    tmp_written = tmp if os.path.exists(tmp) else tmp + ".npz"
    os.replace(tmp_written, out_path)
    summary = {
        "in_path": in_path,
        "out_path": out_path,
        "old_shards": n,
        "new_shards": m,
        "unique": meta["unique"],
        "depth": meta["depth"],
        "tails": tails_new.tolist(),
        "log_capacity": log_cap,
        "engine_key": out["engine_key"],
    }
    if journal is not None:
        # Accept a Journal or a path, like the engines' journal kwarg.
        if isinstance(journal, (str, os.PathLike)):
            from ..runtime.journal import Journal

            j = Journal(os.fspath(journal))
            try:
                j.append("reshard", **{
                    k: v for k, v in summary.items() if k != "engine_key"
                })
            finally:
                j.close()
        else:
            journal.append("reshard", **{
                k: v for k, v in summary.items() if k != "engine_key"
            })
    return summary
