"""Last-write-wins register: a state-based CRDT driven by random choices.

Reference: examples/lww-register.rs — each node nondeterministically (via
``choose_random``) sets a value or skews its local clock, broadcasting its
register; receivers merge by (timestamp, updater_id).  The "eventually
consistent" property is CRDT-style: states must agree whenever the network
is empty (transient agreement before a terminal state does not count,
examples/lww-register.rs:166-182).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Any, Optional, Tuple

from ..actor import Actor, ActorModel, Id, Network, Out
from ..core.model import Expectation

VALUES = ("A", "B", "C")


@dataclass(frozen=True)
class LwwRegister:
    value: Any
    timestamp: int
    updater_id: int

    @staticmethod
    def merge(a: "LwwRegister", b: "LwwRegister") -> "LwwRegister":
        return a if (a.timestamp, a.updater_id) > (b.timestamp, b.updater_id) else b


@dataclass(frozen=True)
class SetValue:
    value: Any


@dataclass(frozen=True)
class SetTime:
    time: int


@dataclass(frozen=True)
class LwwActorState:
    register: Optional[LwwRegister]
    local_clock: int
    maximum_used_clock: int


class LwwActor(Actor):
    def __init__(self, peers: Tuple[Id, ...]):
        self.peers = tuple(peers)

    def name(self) -> str:
        return "LWW Node"

    def _populate_choices(self, o: Out, time: int) -> None:
        o.choose_random(
            "node_action",
            [SetValue(v) for v in VALUES]
            + [SetTime(time + 1), SetTime(max(time - 1, 0))],
        )

    def on_start(self, id: Id, storage, o: Out) -> LwwActorState:
        state = LwwActorState(
            register=None, local_clock=1000, maximum_used_clock=1000
        )
        self._populate_choices(o, state.local_clock)
        return state

    def on_random(self, id: Id, state: LwwActorState, random, o: Out):
        if isinstance(random, SetValue):
            if state.register is not None:
                # Clock values stay unique per node.
                clock_value = max(
                    state.local_clock, state.maximum_used_clock + 1
                )
                state = replace(
                    state,
                    register=LwwRegister(random.value, clock_value, int(id)),
                    maximum_used_clock=clock_value,
                )
            else:
                state = replace(
                    state,
                    register=LwwRegister(
                        random.value, state.local_clock, int(id)
                    ),
                )
            o.broadcast(self.peers, state.register)
        elif isinstance(random, SetTime):
            state = replace(state, local_clock=random.time)
        self._populate_choices(o, state.local_clock)
        return state

    def on_msg(self, id: Id, state: LwwActorState, src: Id, msg, o: Out):
        if state.register is not None:
            return replace(state, register=LwwRegister.merge(state.register, msg))
        return replace(state, register=msg)


def build_model(num_actors: int = 2) -> ActorModel:
    """examples/lww-register.rs:153-185; checked with target_max_depth."""
    nodes = tuple(Id(i) for i in range(num_actors))

    def eventually_consistent(_m, state):
        if len(state.network) == 0:
            regs = [s.register for s in state.actor_states]
            return all(r == regs[0] for r in regs)
        return True

    model = ActorModel(cfg=None)
    model.add_actors(LwwActor(nodes) for _ in range(num_actors))

    def _compiled():
        from .lww_compiled import LwwCompiled

        return LwwCompiled(model)

    model.compiled = _compiled
    return model.init_network_(
        Network.new_unordered_nonduplicating()
    ).property(
        Expectation.ALWAYS, "eventually consistent", eventually_consistent
    )


def cli_spec():
    """This module's CLI/workload spec (resolved by serve/workloads.py)."""
    from ..cli import CliSpec

    return CliSpec(
        name="LWW-register CRDT",
        build=lambda n: build_model(num_actors=n),
        default_n=2,
        n_meta="ACTOR_COUNT",
        # The CRDT walk is unbounded (clocks skew forever); the
        # reference's check bounds depth at 8 by default
        # (examples/lww-register.rs:194-196).  The device run bounds
        # tighter to fit its default table capacity.
        target_max_depth=8,
        tpu=True,
        tpu_kwargs=dict(capacity=1 << 16, max_frontier=1 << 9),
        tpu_target_max_depth=6,
    )


def main(argv=None) -> int:
    """CLI mirroring examples/lww-register.rs."""
    from ..cli import example_main

    return example_main(cli_spec(), argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
