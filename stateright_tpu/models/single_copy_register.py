"""Unreplicated single-copy register — linearizable iff one server.

Reference: examples/single-copy-register.rs.  Golden: 93 unique states with
2 clients / 1 server (nonduplicating network); linearizability violated
with 2 servers (20 unique states).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..actor import Actor, ActorModel, Network, Out
from ..actor.register import (
    Get,
    GetOk,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

NULL_VALUE = "\x00"  # the analog of Rust's char::default()


class SingleCopyActor(Actor):
    def on_start(self, id, storage, o: Out):
        return NULL_VALUE

    def on_msg(self, id, state, src, msg, o: Out):
        if isinstance(msg, Put):
            o.send(src, PutOk(msg.request_id))
            return msg.value
        if isinstance(msg, Get):
            o.send(src, GetOk(msg.request_id, state))
            return None
        return None


@dataclass
class SingleCopyModelCfg:
    client_count: int
    server_count: int
    network: Network

    def into_model(self) -> ActorModel:
        def value_chosen(_m, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != NULL_VALUE:
                    return True
            return False

        model = ActorModel(
            cfg=self, init_history=LinearizabilityTester(Register(NULL_VALUE))
        )
        model.add_actors(
            RegisterServer(SingleCopyActor()) for _ in range(self.server_count)
        )
        model.add_actors(
            RegisterClient(put_count=1, server_count=self.server_count)
            for _ in range(self.client_count)
        )
        model = (
            model.init_network_(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _m, s: s.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )

        def _compiled():
            from .single_copy_compiled import SingleCopyCompiled

            return SingleCopyCompiled(model)

        model.compiled = _compiled
        return model


def cli_spec():
    """This module's CLI/workload spec (resolved by serve/workloads.py)."""
    from ..cli import CliSpec, spawn_register_system

    def spawn_servers():
        from ..actor.register import (
            Get, GetOk, Put, PutOk, RegisterServer,
        )
        from ..actor.wire import register_wire_types

        register_wire_types(Put, Get, PutOk, GetOk)
        spawn_register_system(
            lambda ids: [RegisterServer(SingleCopyActor())],
            1,
            "single-copy register",
        )

    return CliSpec(
        name="single-copy register",
        build=lambda n, net: SingleCopyModelCfg(
            client_count=n, server_count=1, network=net
        ).into_model(),
        default_n=2,
        n_meta="CLIENT_COUNT",
        default_network="unordered_nonduplicating",
        tpu=True,
        tpu_kwargs=dict(capacity=1 << 12, max_frontier=1 << 7),
        spawn=spawn_servers,
    )


def main(argv=None) -> int:
    """CLI mirroring examples/single-copy-register.rs."""
    from ..cli import example_main

    return example_main(cli_spec(), argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
