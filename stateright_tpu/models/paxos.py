"""Single Decree Paxos on the register harness, linearizability-checked.

Reference: examples/paxos.rs.  Golden: 16,668 unique states at 2 clients /
3 servers on a nonduplicating network (BFS and DFS).  This model is also
the flagship workload for the TPU wavefront backend (see
stateright_tpu.models.paxos_compiled and BASELINE.md).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, FrozenSet, Optional, Tuple

from ..actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

NULL_VALUE = "\x00"

# Ballot = (round, Id); Proposal = (request_id, requester Id, value)


@dataclass(frozen=True)
class Prepare:
    ballot: Tuple[int, Id]


@dataclass(frozen=True)
class Prepared:
    ballot: Tuple[int, Id]
    last_accepted: Optional[Tuple[Tuple[int, Id], Tuple[int, Id, Any]]]


@dataclass(frozen=True)
class Accept:
    ballot: Tuple[int, Id]
    proposal: Tuple[int, Id, Any]


@dataclass(frozen=True)
class Accepted:
    ballot: Tuple[int, Id]


@dataclass(frozen=True)
class Decided:
    ballot: Tuple[int, Id]
    proposal: Tuple[int, Id, Any]


@dataclass(frozen=True)
class PaxosState:
    # shared state
    ballot: Tuple[int, Id]
    # leader state
    proposal: Optional[Tuple[int, Id, Any]]
    prepares: Tuple[Tuple[Id, Optional[Tuple]], ...]  # sorted by id
    accepts: FrozenSet[Id]
    # acceptor state
    accepted: Optional[Tuple[Tuple[int, Id], Tuple[int, Id, Any]]]
    is_decided: bool


def _prepared_sort_key(last_accepted):
    """Rust's Option ordering: None < Some(inner)."""
    return (0,) if last_accepted is None else (1, last_accepted)


class PaxosActor(Actor):
    def __init__(self, peer_ids):
        self.peer_ids = list(peer_ids)

    def name(self) -> str:
        return "Paxos Server"

    def on_start(self, id, storage, o: Out):
        return PaxosState(
            ballot=(0, Id(0)),
            proposal=None,
            prepares=(),
            accepts=frozenset(),
            accepted=None,
            is_decided=False,
        )

    def on_msg(self, id, state: PaxosState, src, msg, o: Out):
        if state.is_decided:
            if isinstance(msg, Get):
                _b, (_req_id, _src, value) = state.accepted
                o.send(src, GetOk(msg.request_id, value))
            return None

        if isinstance(msg, Put) and state.proposal is None:
            ballot = (state.ballot[0] + 1, id)
            o.broadcast(self.peer_ids, Internal(Prepare(ballot)))
            return self._replace(
                state,
                proposal=(msg.request_id, src, msg.value),
                ballot=ballot,
                # Simulate Prepare+Prepared self-sends.
                prepares=((id, state.accepted),),
                accepts=frozenset(),
            )

        if isinstance(msg, Internal) and isinstance(msg.msg, Prepare):
            if state.ballot < msg.msg.ballot:
                o.send(
                    src,
                    Internal(Prepared(msg.msg.ballot, state.accepted)),
                )
                return self._replace(state, ballot=msg.msg.ballot)
            return None

        if isinstance(msg, Internal) and isinstance(msg.msg, Prepared):
            if msg.msg.ballot != state.ballot:
                return None
            prepares = dict(state.prepares)
            prepares[src] = msg.msg.last_accepted
            if len(prepares) == majority(len(self.peer_ids) + 1):
                best = max(prepares.values(), key=_prepared_sort_key)
                proposal = best[1] if best is not None else state.proposal
                ballot = state.ballot
                o.broadcast(self.peer_ids, Internal(Accept(ballot, proposal)))
                return self._replace(
                    state,
                    proposal=proposal,
                    prepares=tuple(sorted(prepares.items())),
                    # Simulate Accept+Accepted self-sends.
                    accepted=(ballot, proposal),
                    accepts=frozenset([id]),
                )
            return self._replace(state, prepares=tuple(sorted(prepares.items())))

        if isinstance(msg, Internal) and isinstance(msg.msg, Accept):
            if state.ballot <= msg.msg.ballot:
                o.send(src, Internal(Accepted(msg.msg.ballot)))
                return self._replace(
                    state,
                    ballot=msg.msg.ballot,
                    accepted=(msg.msg.ballot, msg.msg.proposal),
                )
            return None

        if isinstance(msg, Internal) and isinstance(msg.msg, Accepted):
            if msg.msg.ballot != state.ballot:
                return None
            accepts = state.accepts | {src}
            if len(accepts) == majority(len(self.peer_ids) + 1):
                proposal = state.proposal
                o.broadcast(
                    self.peer_ids, Internal(Decided(msg.msg.ballot, proposal))
                )
                request_id, requester_id, _ = proposal
                o.send(requester_id, PutOk(request_id))
                return self._replace(state, accepts=accepts, is_decided=True)
            return self._replace(state, accepts=accepts)

        if isinstance(msg, Internal) and isinstance(msg.msg, Decided):
            return self._replace(
                state,
                ballot=msg.msg.ballot,
                accepted=(msg.msg.ballot, msg.msg.proposal),
                is_decided=True,
            )

        return None

    @staticmethod
    def _replace(state: PaxosState, **changes) -> PaxosState:
        import dataclasses

        return dataclasses.replace(state, **changes)


@dataclass
class PaxosModelCfg:
    client_count: int
    server_count: int
    network: Network
    # Adds an (intentionally false) always-property "never decided" — the
    # property-violating variant BASELINE.md's time-to-first-violation
    # metric is measured on.
    never_decided: bool = False
    # Ballot-round boundary: states where any server's ballot round
    # exceeds this are pruned (None = bounded only by the packed
    # encoding's MAX_ROUND cap, paxos_compiled.py).  Raising it is a
    # monotone reachable-set widening — every in-bound state keeps its
    # transitions and the boundary admits a superset — which the
    # compiled codec declares to the incremental store
    # (PaxosCompiled.spec_widens, docs/INCREMENTAL.md).
    max_round: Optional[int] = None

    def into_model(self) -> ActorModel:
        def value_chosen(_m, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != NULL_VALUE:
                    return True
            return False

        model = ActorModel(
            cfg=self, init_history=LinearizabilityTester(Register(NULL_VALUE))
        )
        model.add_actors(
            RegisterServer(PaxosActor(model_peers(i, self.server_count)))
            for i in range(self.server_count)
        )
        model.add_actors(
            RegisterClient(put_count=1, server_count=self.server_count)
            for _ in range(self.client_count)
        )
        model = (
            model.init_network_(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _m, s: s.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )
        if self.never_decided:
            model.property(
                Expectation.ALWAYS,
                "never decided",
                lambda _m, s: not any(
                    getattr(a, "is_decided", False) for a in s.actor_states
                ),
            )
        if self.max_round is not None:
            # Host half of the round boundary; the device half is
            # PaxosCompiled.boundary, which reads the same per-server
            # ballot rounds from the packed record so host BFS and the
            # TPU engine prune identically.
            model.within_boundary_(
                lambda cfg, s: all(
                    a.ballot[0] <= cfg.max_round
                    for a in s.actor_states
                    if hasattr(a, "ballot")
                )
            )

        def _compiled():
            from .paxos_compiled import PaxosCompiled

            return PaxosCompiled(model)

        model.compiled = _compiled
        return model


def cli_spec():
    """This module's CLI/workload spec (resolved by serve/workloads.py)."""
    from ..cli import CliSpec, spawn_register_system

    def spawn_servers():
        from ..actor.register import (
            Get, GetOk, Internal, Put, PutOk, RegisterServer,
        )
        from ..actor.wire import register_wire_types

        register_wire_types(
            Put, Get, PutOk, GetOk, Internal,
            Prepare, Prepared, Accept, Accepted, Decided,
        )
        spawn_register_system(
            lambda ids: [
                RegisterServer(
                    PaxosActor([p for p in ids if p != me])
                )
                for me in ids
            ],
            3,
            "Single Decree Paxos",
        )

    return CliSpec(
        name="Single Decree Paxos",
        build=lambda n, net: PaxosModelCfg(
            client_count=n, server_count=3, network=net
        ).into_model(),
        default_n=2,
        n_meta="CLIENT_COUNT",
        default_network="unordered_nonduplicating",
        tpu=True,
        tpu_kwargs=dict(capacity=1 << 23, max_frontier=1 << 13),
        spawn=spawn_servers,
    )


def main(argv=None) -> int:
    """CLI mirroring examples/paxos.rs:355-513."""
    from ..cli import example_main

    return example_main(cli_spec(), argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
