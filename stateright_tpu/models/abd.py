"""ABD quorum register (Attiya, Bar-Noy, Dolev — "Sharing Memory Robustly
in Message-Passing Systems").

Reference: examples/linearizable-register.rs.  Golden: 544 unique states at
2 clients / 2 servers on a nonduplicating network.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, FrozenSet, Optional, Tuple

from ..actor import Actor, ActorModel, Id, Network, Out, majority, model_peers
from ..actor.register import (
    Get,
    GetOk,
    Internal,
    Put,
    PutOk,
    RegisterClient,
    RegisterServer,
    record_invocations,
    record_returns,
)
from ..core.model import Expectation
from ..semantics import LinearizabilityTester, Register

NULL_VALUE = "\x00"

# Seq = (logical clock, id)


@dataclass(frozen=True)
class Query:
    request_id: int


@dataclass(frozen=True)
class AckQuery:
    request_id: int
    seq: Tuple[int, Id]
    value: Any


@dataclass(frozen=True)
class Record:
    request_id: int
    seq: Tuple[int, Id]
    value: Any


@dataclass(frozen=True)
class AckRecord:
    request_id: int


@dataclass(frozen=True)
class Phase1:
    request_id: int
    requester_id: Id
    write: Optional[Any]
    responses: Tuple[Tuple[Id, Tuple[Tuple[int, Id], Any]], ...]  # sorted by id


@dataclass(frozen=True)
class Phase2:
    request_id: int
    requester_id: Id
    read: Optional[Any]
    acks: FrozenSet[Id]


@dataclass(frozen=True)
class AbdState:
    seq: Tuple[int, Id]
    val: Any
    phase: Optional[Any]


class AbdActor(Actor):
    """The ABD quorum register replica.

    ``fault`` injects a deliberate protocol bug for chaos-audit testing
    (never used when model checking): ``"skip_ack"`` makes the replica
    acknowledge client operations immediately from local state, skipping
    both quorum phases — the classic linearizability violation (a read on
    another replica misses a completed write) that the live auditor must
    catch (tests/test_actor_chaos.py).
    """

    def __init__(self, peers, fault=None):
        self.peers = list(peers)
        if fault not in (None, "skip_ack"):
            raise ValueError(f"unknown AbdActor fault: {fault!r}")
        self.fault = fault

    def name(self) -> str:
        return "ABD Server"

    def on_start(self, id, storage, o: Out):
        return AbdState(seq=(0, id), val=NULL_VALUE, phase=None)

    def on_msg(self, id, state: AbdState, src, msg, o: Out):
        if self.fault == "skip_ack" and isinstance(msg, (Put, Get)):
            # Broken replica: answer from local state without consulting a
            # quorum (no Query/Record round, no acks awaited).
            if isinstance(msg, Put):
                o.send(src, PutOk(msg.request_id))
                return AbdState(
                    seq=(state.seq[0] + 1, id), val=msg.value, phase=state.phase
                )
            o.send(src, GetOk(msg.request_id, state.val))
            return None

        if isinstance(msg, (Put, Get)) and state.phase is None:
            write = msg.value if isinstance(msg, Put) else None
            o.broadcast(self.peers, Internal(Query(msg.request_id)))
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=msg.request_id,
                    requester_id=src,
                    write=write,
                    responses=((id, (state.seq, state.val)),),
                ),
            )

        if isinstance(msg, Internal) and isinstance(msg.msg, Query):
            o.send(src, Internal(AckQuery(msg.msg.request_id, state.seq, state.val)))
            return None

        if (
            isinstance(msg, Internal)
            and isinstance(msg.msg, AckQuery)
            and isinstance(state.phase, Phase1)
            and state.phase.request_id == msg.msg.request_id
        ):
            ph = state.phase
            responses = dict(ph.responses)
            responses[src] = (msg.msg.seq, msg.msg.value)
            if len(responses) == majority(len(self.peers) + 1):
                # Quorum reached; pick the max-sequencer response and move to
                # phase 2 (sequencers are distinct, so max is unambiguous).
                seq, val = max(responses.values(), key=lambda sv: sv[0])
                read = None
                if ph.write is not None:
                    seq = (seq[0] + 1, id)
                    val = ph.write
                else:
                    read = val
                o.broadcast(self.peers, Internal(Record(ph.request_id, seq, val)))
                # Self-send Record.
                new_seq, new_val = state.seq, state.val
                if seq > state.seq:
                    new_seq, new_val = seq, val
                # Self-send AckRecord.
                return AbdState(
                    seq=new_seq,
                    val=new_val,
                    phase=Phase2(
                        request_id=ph.request_id,
                        requester_id=ph.requester_id,
                        read=read,
                        acks=frozenset([id]),
                    ),
                )
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase1(
                    request_id=ph.request_id,
                    requester_id=ph.requester_id,
                    write=ph.write,
                    responses=tuple(sorted(responses.items())),
                ),
            )

        if isinstance(msg, Internal) and isinstance(msg.msg, Record):
            o.send(src, Internal(AckRecord(msg.msg.request_id)))
            if msg.msg.seq > state.seq:
                return AbdState(seq=msg.msg.seq, val=msg.msg.value, phase=state.phase)
            return None

        if (
            isinstance(msg, Internal)
            and isinstance(msg.msg, AckRecord)
            and isinstance(state.phase, Phase2)
            and state.phase.request_id == msg.msg.request_id
            and src not in state.phase.acks
        ):
            ph = state.phase
            acks = ph.acks | {src}
            if len(acks) == majority(len(self.peers) + 1):
                if ph.read is not None:
                    o.send(ph.requester_id, GetOk(ph.request_id, ph.read))
                else:
                    o.send(ph.requester_id, PutOk(ph.request_id))
                return AbdState(seq=state.seq, val=state.val, phase=None)
            return AbdState(
                seq=state.seq,
                val=state.val,
                phase=Phase2(
                    request_id=ph.request_id,
                    requester_id=ph.requester_id,
                    read=ph.read,
                    acks=acks,
                ),
            )

        return None


@dataclass
class AbdModelCfg:
    """``fault`` forwards to every replica's :class:`AbdActor` —
    ``"skip_ack"`` builds the deliberately-broken cluster the chaos
    ensemble (``stateright_tpu.ensemble``) sweeps for failing fault
    schedules; the compiled codec mirrors the same hook on device."""

    client_count: int
    server_count: int
    network: Network
    fault: Optional[str] = None

    def into_model(self) -> ActorModel:
        def value_chosen(_m, state):
            for env in state.network.iter_deliverable():
                if isinstance(env.msg, GetOk) and env.msg.value != NULL_VALUE:
                    return True
            return False

        model = ActorModel(
            cfg=self, init_history=LinearizabilityTester(Register(NULL_VALUE))
        )
        model.add_actors(
            RegisterServer(
                AbdActor(model_peers(i, self.server_count), fault=self.fault)
            )
            for i in range(self.server_count)
        )
        model.add_actors(
            RegisterClient(put_count=1, server_count=self.server_count)
            for _ in range(self.client_count)
        )
        model = (
            model.init_network_(self.network)
            .property(
                Expectation.ALWAYS,
                "linearizable",
                lambda _m, s: s.history.serialized_history() is not None,
            )
            .property(Expectation.SOMETIMES, "value chosen", value_chosen)
            .record_msg_in(record_returns)
            .record_msg_out(record_invocations)
        )

        def _compiled():
            from .abd_compiled import AbdCompiled

            return AbdCompiled(model)

        model.compiled = _compiled
        return model


def run_chaos_audit(chaos, fault=None, client_count=2, put_count=2) -> dict:
    """A hermetic ABD cluster under chaos with live linearizability
    auditing (the `spawn --chaos ... --audit` flow; see docs/ACTORS.md).
    ``fault`` forwards to :class:`AbdActor` — ``"skip_ack"`` builds the
    deliberately-broken replica the audit must reject.  The chaos
    options' observability knobs ride along (``getattr``, so bare
    option objects from older callers keep working): ``trace`` turns on
    the causal trace envelope, ``metrics_port`` serves and self-scrapes
    the live ``/.metrics`` surface (docs/OBSERVABILITY.md
    "Actor-runtime observability")."""
    from ..actor.register import RegisterServer
    from ..runtime.chaos import run_chaos_register_system
    from ..semantics import LinearizabilityTester, Register

    return run_chaos_register_system(
        lambda peers: RegisterServer(AbdActor(peers, fault=fault)),
        server_count=3,
        client_count=client_count,
        put_count=put_count,
        spec=chaos.spec,
        seed=chaos.seed,
        tester_factory=lambda: LinearizabilityTester(Register(NULL_VALUE)),
        wire_types=(Internal, Query, AckQuery, Record, AckRecord),
        journal=chaos.journal,
        deadline_sec=chaos.duration,
        trace=bool(getattr(chaos, "trace", False)),
        metrics_port=getattr(chaos, "metrics_port", None),
    )


def cli_spec():
    """This module's CLI/workload spec (resolved by serve/workloads.py)."""
    from ..cli import CliSpec, spawn_register_system

    def spawn_servers(chaos=None):
        import json as _json

        from ..actor.register import (
            Get, GetOk, Internal, Put, PutOk, RegisterServer,
        )
        from ..actor.wire import register_wire_types

        register_wire_types(
            Put, Get, PutOk, GetOk, Internal,
            Query, AckQuery, Record, AckRecord,
        )
        if chaos is not None and chaos.audit:
            result = run_chaos_audit(chaos)
            print(_json.dumps(result, sort_keys=True, default=str))
            # Exit 0 only for a meaningful pass: a linearizable history
            # with no crashed actor threads and at least one completed
            # operation (a cluster that did nothing, or died early with a
            # trivially-consistent prefix, must not go green).
            ok = (
                result["consistent"]
                and not result["errors"]
                and result["returned"] >= 1
            )
            return 0 if ok else 1
        make_transport = None
        if chaos is not None:
            from ..actor.transport import UdpTransport
            from ..runtime.chaos import FaultyTransport

            def make_transport(ids):
                # Spec links/partitions are written with model indices;
                # remap them onto the real socket-addr ids.
                spec = chaos.spec.remap_ids(
                    {i: int(a) for i, a in enumerate(ids)}
                )
                return FaultyTransport(
                    UdpTransport(), spec, seed=chaos.seed,
                    journal=chaos.journal,
                )

            print(
                f"Chaos transport active: seed={chaos.seed} "
                f"spec={_json.dumps(chaos.spec.to_dict(), sort_keys=True)}"
            )
        spawn_register_system(
            lambda ids: [
                RegisterServer(AbdActor([p for p in ids if p != me]))
                for me in ids
            ],
            3,
            "ABD replicas",
            make_transport=make_transport,
            metrics_port=(
                getattr(chaos, "metrics_port", None)
                if chaos is not None else None
            ),
            trace=bool(getattr(chaos, "trace", False)) if chaos else False,
            journal=chaos.journal if chaos is not None else None,
        )

    return CliSpec(
        name="ABD linearizable register",
        build=lambda n, net: AbdModelCfg(
            client_count=n, server_count=2, network=net
        ).into_model(),
        default_n=2,
        n_meta="CLIENT_COUNT",
        default_network="unordered_nonduplicating",
        tpu=True,
        tpu_kwargs=dict(capacity=1 << 13, max_frontier=1 << 8),
        spawn=spawn_servers,
        ensemble=True,
    )


def main(argv=None) -> int:
    """CLI mirroring examples/linearizable-register.rs."""
    from ..cli import example_main

    return example_main(cli_spec(), argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
