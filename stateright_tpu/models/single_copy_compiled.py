"""Compiled single-copy register — the *violation* workload for the device
linearizability DP.

Host model: models/single_copy_register.py (reference
examples/single-copy-register.rs): an unreplicated register that is
linearizable with one server (golden 93 unique states at 2 clients) and
demonstrably NOT with two — clients round-robin their Put and Get to
different servers, and 20 of the 62 reachable states at 2 clients / 2
servers carry non-linearizable histories.  That makes this the one model
family whose reachable exploration actually *discovers* the
"linearizable" counterexample, exercising the shared DP
(register_compiled_common) on reachable — not just synthetic — violations.

Layout (C ≤ 7 clients, S ≤ 2 servers): word 0 packs the server values
(vb bits each, vb = max(2, ⌈log2(C+1)⌉)); then the shared client word,
network slots (4 for C ≤ 2, else 8 — each client has at most one message
in flight), and tester words.  The widths scale with the client count so
the reference's bench workload `single-copy-register check 4`
(bench.sh:29: 4 clients, 1 server) compiles.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..actor import Envelope, Id, Network
from ..actor.model import ActorModelState
from ..actor.register import Get, GetOk, Put, PutOk
from ..parallel.compiled import CompiledModel
from ..semantics import LinearizabilityTester, Register
from .register_compiled_common import (
    RegisterClientCodec,
    decode_slot_counts,
    representative_slot_code,
)
from .single_copy_register import NULL_VALUE

_T_PUT, _T_GET, _T_PUTOK, _T_GETOK = 0, 1, 2, 3


class SingleCopyCompiled(CompiledModel):
    """Codec + device step kernel for ``SingleCopyModelCfg.into_model()``."""

    step_flags = True

    def __init__(self, model):
        self.model = model
        cfg = model.cfg
        if cfg.server_count > 2 or cfg.client_count > 7:
            # Client cap from the shared harness (tester word width); the
            # server cap covers both reference configs (1 server for the
            # linearizable goldens, 2 for the violation case).
            raise ValueError(
                "packed single-copy supports at most 2 servers / 7 clients"
            )
        if model.lossy_network or model.max_crashes:
            raise ValueError(
                "packed single-copy supports lossless, crash-free "
                "configurations"
            )
        if model.init_network.kind != "unordered_nonduplicating":
            raise ValueError(
                "packed single-copy supports the unordered_nonduplicating "
                "network"
            )
        self.s = cfg.server_count
        self.c = cfg.client_count
        # Each client has at most one message in flight, so c slots always
        # suffice; 4/8 keeps the golden-config shapes stable.
        self.m = 4 if self.c <= 2 else 8
        self.state_width = 1 + 1 + self.m + self.c
        self.max_actions = self.m
        self.rc = RegisterClientCodec(
            server_count=self.s,
            client_count=self.c,
            cli_word=1,
            tst0=2 + self.m,
        )
        self.vb = self.rc.vb  # server-value field width in word 0
        self.values = self.rc.values

    def cache_key(self):
        return (type(self).__qualname__, self.s, self.c)

    # --- envelope codes -------------------------------------------------------

    def _env_code(self, env: Envelope) -> int:
        s, rc = self.s, self.rc
        msg = env.msg
        src, dst = int(env.src), int(env.dst)
        if isinstance(msg, Put):
            ci = src - s
            assert msg == Put(s + ci, self.values[ci]) and dst == (s + ci) % s
            code = (_T_PUT, ci, 0)
        elif isinstance(msg, Get):
            ci = src - s
            assert msg.request_id == 2 * (s + ci) and dst == (s + ci + 1) % s
            code = (_T_GET, ci, 0)
        elif isinstance(msg, PutOk):
            ci = dst - s
            assert msg.request_id == s + ci
            code = (_T_PUTOK, src * 8 + ci, 0)
        elif isinstance(msg, GetOk):
            ci = dst - s
            assert msg.request_id == 2 * (s + ci)
            code = (_T_GETOK, src * 8 + ci, rc.value_code(msg.value, NULL_VALUE))
        else:
            raise ValueError(f"unknown message {msg!r}")
        tag, addr, payload = code
        assert addr < 32 and payload < (1 << 14)
        return 1 + ((tag << 19) | (addr << 14) | payload)

    def _env_of(self, code: int) -> Envelope:
        s, rc = self.s, self.rc
        code -= 1
        tag = code >> 19
        addr = (code >> 14) & 0x1F
        payload = code & 0x3FFF
        if tag == _T_PUT:
            ci = addr
            return Envelope(
                Id(s + ci), Id((s + ci) % s), Put(s + ci, self.values[ci])
            )
        if tag == _T_GET:
            ci = addr
            return Envelope(Id(s + ci), Id((s + ci + 1) % s), Get(2 * (s + ci)))
        if tag == _T_PUTOK:
            src, ci = addr // 8, addr % 8
            return Envelope(Id(src), Id(s + ci), PutOk(s + ci))
        if tag == _T_GETOK:
            src, ci = addr // 8, addr % 8
            return Envelope(
                Id(src),
                Id(s + ci),
                GetOk(2 * (s + ci), rc.value_of(payload, NULL_VALUE)),
            )
        raise ValueError(f"bad envelope code {code}")

    # --- full state -----------------------------------------------------------

    def encode(self, st: ActorModelState) -> np.ndarray:
        words = np.zeros(self.state_width, dtype=np.uint32)
        bits = 0
        for i in range(self.s):
            bits |= self.rc.value_code(st.actor_states[i], NULL_VALUE) << (
                self.vb * i
            )
        words[0] = bits
        words[1] = self.rc.encode_clients(st.actor_states)
        env_codes = []
        for env, count in sorted(
            st.network.counts, key=lambda ec: self._env_code(ec[0])
        ):
            # Multiset counts > 1 are repeated codes, like the raft codec
            # — a duplicate in-flight send is data, not an engine error.
            env_codes.extend([self._env_code(env)] * count)
        if len(env_codes) > self.m:
            raise ValueError(
                f"{len(env_codes)} in-flight envelopes exceed {self.m} slots"
            )
        for k, code in enumerate(env_codes):
            words[2 + k] = code
        for i in range(self.c):
            words[2 + self.m + i] = self.rc.encode_tester(
                st.history, i, NULL_VALUE
            )
        return words

    def decode(self, words: Sequence[int]) -> ActorModelState:
        bits = int(words[0])
        servers = tuple(
            self.rc.value_of(
                (bits >> (self.vb * i)) & ((1 << self.vb) - 1), NULL_VALUE
            )
            for i in range(self.s)
        )
        clients = self.rc.decode_clients(int(words[1]))
        network = Network(
            kind="unordered_nonduplicating",
            counts=decode_slot_counts(words, 2, self.m, self._env_of),
        )
        tester = LinearizabilityTester(Register(NULL_VALUE))
        for i in range(self.c):
            self.rc.decode_tester_into(
                tester, int(words[2 + self.m + i]), i, NULL_VALUE
            )
        n = self.s + self.c
        return ActorModelState(
            actor_states=servers + tuple(clients),
            network=network,
            timers_set=(frozenset(),) * n,
            random_choices=((),) * n,
            crashed=(False,) * n,
            history=tester,
            actor_storages=(None,) * n,
        )

    # --- device side ----------------------------------------------------------

    def step(self, state):
        import jax
        import jax.numpy as jnp

        ks = jnp.arange(self.m, dtype=jnp.uint32)
        nexts, valid, flags = jax.vmap(lambda k: self._deliver_lane(state, k))(ks)
        return nexts, valid, jnp.any(flags)

    def _deliver_lane(self, state, k):
        import jax.numpy as jnp

        u = jnp.uint32
        c = self.c
        s = self.s
        m = self.m
        net0 = 2
        tst0 = net0 + m

        code, occupied = representative_slot_code(state, net0, m, k)
        lane_sel = jnp.arange(m, dtype=u) == k
        e = code - u(1)
        tag = e >> u(19)
        addr = (e >> u(14)) & u(0x1F)
        payload = e & u(0x3FFF)
        i_dst = addr & u(7)
        vb = u(self.vb)
        vmask = u((1 << self.vb) - 1)

        # Put goes to (s+ci) % s, Get to (s+ci+1) % s (actor/register.py).
        dsrv = jnp.where(
            tag == u(_T_PUT),
            (addr + u(s)) % u(s),
            (addr + u(s) + u(1)) % u(s),
        )
        srv_bits = state[0]
        sval = (srv_bits >> (vb * dsrv)) & vmask

        def mk(t, a, p):
            return u(1) + ((u(t) << u(19)) | (a << u(14)) | p)

        # Put: store the value, reply PutOk (models/single_copy_register.py:33-35).
        put_ci = addr
        put_bits = (srv_bits & ~(vmask << (vb * dsrv))) | (
            (put_ci + u(1)) << (vb * dsrv)
        )
        put_s0 = mk(_T_PUTOK, dsrv * u(8) + put_ci, u(0))

        # Get: reply with the current value, state unchanged (:36-38).
        get_s0 = mk(_T_GETOK, dsrv * u(8) + addr, sval)

        # PutOk / GetOk to a client (shared harness transitions).
        ci, cli, ckind, _opc = self.rc.client_record(state, i_dst)
        tw = self.rc.tester_word(state, ci)
        putok_guard = (ckind == u(1)) & (i_dst < u(c))
        cli_putok, tw_putok = self.rc.putok_transition(state, ci, cli, tw)
        putok_s0 = mk(_T_GET, ci, u(0))
        getok_guard = (ckind == u(2)) & (i_dst < u(c))
        cli_getok, tw_getok = self.rc.getok_transition(ci, cli, tw, payload)

        def sel(pairs, default):
            out = default
            for t, v in pairs:
                out = jnp.where(tag == u(t), v, out)
            return out

        valid = occupied & sel(
            [
                (_T_PUT, jnp.ones((), jnp.bool_)),
                (_T_GET, jnp.ones((), jnp.bool_)),
                (_T_PUTOK, putok_guard),
                (_T_GETOK, getok_guard),
            ],
            jnp.zeros((), jnp.bool_),
        )
        srv_f = sel([(_T_PUT, put_bits)], srv_bits)
        cli_f = sel([(_T_PUTOK, cli_putok), (_T_GETOK, cli_getok)], cli)
        tw_f = sel([(_T_PUTOK, tw_putok), (_T_GETOK, tw_getok)], tw)
        s0 = sel(
            [
                (_T_PUT, put_s0),
                (_T_GET, get_s0),
                (_T_PUTOK, putok_s0),
            ],
            u(0),
        )
        s0 = jnp.where(valid, s0, u(0))

        slots = jnp.where(lane_sel, u(0), state[net0 : net0 + m])
        cand = jnp.concatenate([slots, s0[None]])
        ones = u(0xFFFFFFFF)
        cand = jnp.where(cand == u(0), ones, cand)
        cand = jnp.sort(cand)
        slot_overflow = valid & jnp.any(cand[m:] != ones)
        # Duplicate sends are repeated codes (host multiset count > 1) —
        # data, not an engine error, exactly like the raft codec.
        new_slots = jnp.where(cand[:m] == ones, u(0), cand[:m])
        flag = slot_overflow

        head = [srv_f, cli_f]
        tail = [
            jnp.where(ci == u(j), tw_f, state[tst0 + j]) for j in range(c)
        ]
        ns = jnp.concatenate(
            [jnp.stack(head), new_slots, jnp.stack(tail)]
        ).astype(u)
        return ns, valid, flag

    def property_conds(self, state):
        import jax.numpy as jnp

        u = jnp.uint32
        lin = self.rc.device_linearizable(state)
        slots = state[2 : 2 + self.m]
        e = slots - u(1)
        getok = (slots != u(0)) & ((e >> u(19)) == u(_T_GETOK))
        chosen = jnp.any(getok & ((e & u(0x3FFF)) != u(0)))
        return jnp.stack([lin, chosen])


def compiled_single_copy(model) -> SingleCopyCompiled:
    return SingleCopyCompiled(model)
