"""Fixture models for tests (and docs).

Reference: src/test_util.rs — binary_clock, dgraph, linear_equation_solver,
and panicker, reproduced with the same state spaces so the reference's
golden counts (e.g. 65,536 states for full LinearEquation enumeration) pin
this implementation too.  ``TrapCounter`` (+ its compiled form) is this
package's own fixture for the device engines: the smallest model
exercising the full eventually-property pipeline, and — via its identity
canonicalization — the symmetry plumbing on a model with no symmetric
structure.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

import numpy as np

from ..core.model import Model, Property
from ..parallel.compiled import CompiledModel


class BinaryClock(Model):
    """2-state cycle; the smallest possible model (src/test_util.rs:4-47)."""

    class Action(enum.Enum):
        GO_LOW = "GoLow"
        GO_HIGH = "GoHigh"

        def __repr__(self) -> str:
            return self.value

    def init_states(self):
        return [0, 1]

    def actions(self, state, actions):
        if state == 0:
            actions.append(BinaryClock.Action.GO_HIGH)
        else:
            actions.append(BinaryClock.Action.GO_LOW)

    def next_state(self, state, action):
        return 1 if action is BinaryClock.Action.GO_HIGH else 0

    def properties(self):
        return [Property.always("in [0, 1]", lambda _m, s: 0 <= s <= 1)]


@dataclass
class DGraph(Model):
    """A directed graph specified via paths from initial states; the harness
    for eventually-property semantics tests (src/test_util.rs:50-116)."""

    inits: Set[int] = field(default_factory=set)
    edges: Dict[int, Set[int]] = field(default_factory=dict)
    props: List[Property] = field(default_factory=list)

    @staticmethod
    def with_property(prop: Property) -> "DGraph":
        return DGraph(props=[prop])

    def with_path(self, path: List[int]) -> "DGraph":
        src = path[0]
        self.inits.add(src)
        for dst in path[1:]:
            self.edges.setdefault(src, set()).add(dst)
            src = dst
        return self

    def check(self):
        return self.checker().spawn_bfs().join()

    def init_states(self):
        return sorted(self.inits)

    def actions(self, state, actions):
        actions.extend(sorted(self.edges.get(state, ())))

    def next_state(self, state, action):
        return action

    def properties(self):
        return list(self.props)


@dataclass
class LinearEquation(Model):
    """Finds x, y with a*x + b*y = c (mod 256); the standard checker test —
    full enumeration is 65,536 states (src/test_util.rs:140-192)."""

    a: int
    b: int
    c: int

    class Guess(enum.Enum):
        INCREASE_X = "IncreaseX"
        INCREASE_Y = "IncreaseY"

        def __repr__(self) -> str:
            return self.value

    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions):
        actions.append(LinearEquation.Guess.INCREASE_X)
        actions.append(LinearEquation.Guess.INCREASE_Y)

    def next_state(self, state, action):
        x, y = state
        if action is LinearEquation.Guess.INCREASE_X:
            return ((x + 1) % 256, y)
        return (x, (y + 1) % 256)

    def properties(self):
        def solvable(model, solution):
            x, y = solution
            return (model.a * x + model.b * y) % 256 == model.c

        return [Property.sometimes("solvable", solvable)]


class Panicker(Model):
    """Raises mid-exploration to test clean thread shutdown
    (src/test_util.rs:195-228)."""

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        actions.append(1)

    def next_state(self, last_state, action):
        if last_state == 5:
            raise RuntimeError("reached panic state")
        return last_state + action

    def properties(self):
        return [Property.always("true", lambda _m, _s: True)]


class TrapCounter(Model):
    """0 →inc→ 1 → … → limit, with a dead-end trap edge at ``trap_at``.

    Exercises the full eventually pipeline: "reaches one" is satisfied
    along every path (bit cleared mid-path, never reported); "reaches
    limit" has a genuine counterexample ending in the trap terminal state.
    States are plain ints with no symmetric structure, so the compiled
    form's canonicalization is the identity — the fixture for pinning
    that symmetry-on changes nothing when there is nothing to reduce
    (``checker().symmetry_fn(lambda s: s)`` on the host side).
    """

    def __init__(self, limit=5, trap_at=2):
        self.limit = limit
        self.trap_at = trap_at
        self.trap_state = limit + 1

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        if state < self.limit:
            actions.append("inc")
        if state == self.trap_at:
            actions.append("trap")

    def next_state(self, state, action):
        return state + 1 if action == "inc" else self.trap_state

    def properties(self):
        return [
            Property.eventually("reaches one", lambda _m, s: s >= 1),
            Property.eventually(
                "reaches limit", lambda _m, s: s == self.limit
            ),
            Property.sometimes(
                "trapped", lambda _m, s: s == self.trap_state
            ),
        ]

    def compiled(self):
        return TrapCounterCompiled(self)


class TrapCounterCompiled(CompiledModel):
    state_width = 1
    max_actions = 2

    def __init__(self, model):
        self.model = model

    def encode(self, state):
        return np.array([state], np.uint32)

    def decode(self, words):
        return int(words[0])

    def step(self, state):
        import jax.numpy as jnp

        n = state[0]
        limit = jnp.uint32(self.model.limit)
        inc = jnp.stack([n + jnp.uint32(1)])
        trap = jnp.stack([jnp.uint32(self.model.trap_state)])
        nexts = jnp.stack([inc, trap])
        valid = jnp.stack(
            [n < limit, n == jnp.uint32(self.model.trap_at)]
        )
        return nexts, valid

    def property_conds(self, state):
        import jax.numpy as jnp

        n = state[0]
        return jnp.stack(
            [
                n >= jnp.uint32(1),
                n == jnp.uint32(self.model.limit),
                n == jnp.uint32(self.model.trap_state),
            ]
        )

    def canon_spec(self):
        """No symmetric records: the canonical form is the row itself —
        an empty spec, so symmetry-enabled runs must match plain runs
        bit-for-bit (pinned in tests/test_tpu_symmetry.py)."""
        from ..parallel.canon import CanonSpec

        return CanonSpec(n=0)

    def spec_constants(self):
        """TrapCounter is not a dataclass, so the incremental store's
        default constants derivation (parallel/compiled.py) cannot see
        ``limit``/``trap_at`` — declared explicitly so trap-counter
        entries participate in the store instead of degrading to
        "no stable constants"."""
        return {
            "limit": repr(self.model.limit),
            "trap_at": repr(self.model.trap_at),
        }


def cli_spec():
    """CLI/workload spec for :class:`TrapCounter` — the smallest
    KNOWN-VIOLATING workload with a compiled device form ("reaches
    limit" has a genuine counterexample ending in the trap terminal).
    Registered so the checking service (serve/workloads.py), its CI
    smoke, and the violation-exit-code CLI test all have a fast
    violating job to submit."""
    from ..cli import CliSpec

    return CliSpec(
        name="trap counter",
        # limit must clear trap_at=2 or the trap edge is unreachable
        # and the fixture stops violating.
        build=lambda n: TrapCounter(limit=max(n, 3)),
        default_n=5,
        n_meta="LIMIT",
        tpu=True,
        tpu_kwargs=dict(capacity=1 << 10, max_frontier=1 << 6),
    )


def main(argv=None) -> int:
    from ..cli import example_main

    return example_main(cli_spec(), argv)


@dataclass(frozen=True)
class GridWalk(Model):
    """Monotone walk on the integer grid ``[0, bound]²`` — the fixture
    for the incremental store's CONSTANT-WIDENING mode (incr/,
    docs/INCREMENTAL.md): the packed encoding is bound-independent
    (x and y each ride a 16-bit lane), the transition function emits
    the same candidate successors at every bound, and ``bound`` only
    prunes via the boundary — so raising it is a declared monotone
    reachable-set widening (``spec_widens``), exactly the "one constant
    bumped" re-check the store seeds from the prior reachable set.
    ``(bound+1)²`` unique states at depth ``2·bound``.  The always
    property never violates, so a completed run is exhaustive (every
    state stays awaited — the store's row-reuse witness)."""

    bound: int = 4

    def init_states(self):
        return [(0, 0)]

    def actions(self, state, actions):
        actions.append("right")
        actions.append("up")

    def next_state(self, state, action):
        x, y = state
        return (x + 1, y) if action == "right" else (x, y + 1)

    def within_boundary(self, state):
        x, y = state
        return x <= self.bound and y <= self.bound

    def properties(self):
        return [
            Property.always(
                "in bounds",
                lambda m, s: 0 <= s[0] <= m.bound and 0 <= s[1] <= m.bound,
            ),
            Property.sometimes(
                "reaches corner",
                lambda m, s: s[0] == m.bound and s[1] == m.bound,
            ),
        ]

    def compiled(self):
        return GridWalkCompiled(self)


class GridWalkCompiled(CompiledModel):
    state_width = 1
    max_actions = 2

    def __init__(self, model: GridWalk):
        if not 0 <= model.bound < (1 << 15):
            raise ValueError("GridWalk bound must fit a 16-bit lane")
        self.model = model

    def encode(self, state):
        x, y = state
        return np.array([x | (y << 16)], np.uint32)

    def decode(self, words):
        w = int(words[0])
        return (w & 0xFFFF, w >> 16)

    def step(self, state):
        import jax.numpy as jnp

        w = state[0]
        right = jnp.stack([w + jnp.uint32(1)])
        up = jnp.stack([w + jnp.uint32(1 << 16)])
        nexts = jnp.stack([right, up])
        valid = jnp.ones((2,), jnp.bool_)
        return nexts, valid

    def boundary(self, state):
        import jax.numpy as jnp

        w = state[0]
        b = jnp.uint32(self.model.bound)
        return ((w & jnp.uint32(0xFFFF)) <= b) & ((w >> jnp.uint32(16)) <= b)

    def property_conds(self, state):
        import jax.numpy as jnp

        w = state[0]
        b = jnp.uint32(self.model.bound)
        x = w & jnp.uint32(0xFFFF)
        y = w >> jnp.uint32(16)
        return jnp.stack([(x <= b) & (y <= b), (x == b) & (y == b)])

    def spec_widens(self, old_constants: dict) -> bool:
        """Raising ``bound`` only ever ADDS reachable states: every old
        state keeps its packed row, its candidate successors, and its
        in-old-bounds successors, and the boundary admits a superset —
        the store's constant-widening contract."""
        try:
            old_bound = int(str(old_constants["bound"]))
        except (KeyError, TypeError, ValueError):
            return False
        return set(old_constants) == {"bound"} and (
            old_bound <= self.model.bound
        )

    # --- gang batching (fleet/gang.py): the canonical gang family —
    # the codec is bound-independent, so differently-bounded walks
    # share one program with ``bound`` riding the consts lane.

    def gang_key(self):
        return ("GridWalk", self.state_width, self.max_actions, 2)

    def gang_constants(self):
        return np.array([self.model.bound], np.uint32)

    def gang_step(self, state, consts):
        del consts  # successors are bound-independent; boundary prunes
        return self.step(state)

    def gang_boundary(self, state, consts):
        import jax.numpy as jnp

        w = state[0]
        b = consts[0]
        return ((w & jnp.uint32(0xFFFF)) <= b) & ((w >> jnp.uint32(16)) <= b)

    def gang_property_conds(self, state, consts):
        import jax.numpy as jnp

        w = state[0]
        b = consts[0]
        x = w & jnp.uint32(0xFFFF)
        y = w >> jnp.uint32(16)
        return jnp.stack([(x <= b) & (y <= b), (x == b) & (y == b)])


@dataclass(frozen=True)
class CapCounter(Model):
    """Counter 0 → 1 → … → ``limit`` with an ALWAYS cap property — the
    gang-batch VIOLATION fixture (fleet/gang.py): "within cap" violates
    exactly when ``limit > cap``, so one gang can mix violating and
    clean members and each must report its own verdict (the per-job
    ``VIOLATION_RC`` parity gate).  The "counts up" ALWAYS property
    never violates, so — like GridWalk's "in bounds" — every state
    stays awaited and a completed run is EXHAUSTIVE whether or not the
    cap property discovered, which is what makes gang-vs-solo
    fingerprint parity independent of discovery timing."""

    limit: int = 6
    cap: int = 10

    def init_states(self):
        return [0]

    def actions(self, state, actions):
        if state < self.limit:
            actions.append("inc")

    def next_state(self, state, action):
        return state + 1

    def properties(self):
        return [
            Property.always("counts up", lambda _m, s: s >= 0),
            Property.always("within cap", lambda m, s: s <= m.cap),
            Property.sometimes("reaches limit", lambda m, s: s == m.limit),
        ]

    def compiled(self):
        return CapCounterCompiled(self)


class CapCounterCompiled(CompiledModel):
    state_width = 1
    max_actions = 1

    def __init__(self, model: CapCounter):
        self.model = model

    def encode(self, state):
        return np.array([state], np.uint32)

    def decode(self, words):
        return int(words[0])

    def step(self, state):
        import jax.numpy as jnp

        n = state[0]
        nexts = jnp.stack([jnp.stack([n + jnp.uint32(1)])])
        valid = jnp.stack([n < jnp.uint32(self.model.limit)])
        return nexts, valid

    def property_conds(self, state):
        import jax.numpy as jnp

        n = state[0]
        return jnp.stack([
            n >= jnp.uint32(0),
            n <= jnp.uint32(self.model.cap),
            n == jnp.uint32(self.model.limit),
        ])

    # consts = [limit, cap]: the step's enable mask and the cap
    # property both become data, so every CapCounter shares one traced
    # gang program regardless of parameters.

    def gang_key(self):
        return ("CapCounter", self.state_width, self.max_actions, 3)

    def gang_constants(self):
        return np.array([self.model.limit, self.model.cap], np.uint32)

    def gang_step(self, state, consts):
        import jax.numpy as jnp

        n = state[0]
        nexts = jnp.stack([jnp.stack([n + jnp.uint32(1)])])
        valid = jnp.stack([n < consts[0]])
        return nexts, valid

    def gang_property_conds(self, state, consts):
        import jax.numpy as jnp

        n = state[0]
        return jnp.stack([
            n >= jnp.uint32(0), n <= consts[1], n == consts[0],
        ])


class TwoPhaseEdited:
    """The "one-line model edit" fixture for the incremental store's
    PROPERTY-ONLY mode: two-phase commit with one property appended —
    codec, constants, and symmetry hash identical to the stock model
    (the subclasses below inherit ``encode``/``step`` unchanged, so the
    code digests match), only the property component differs.  Used by
    tests/test_incr.py, the CI incremental smoke, and bench.py's
    ``recheck`` phase as the canonical near-identical resubmission."""

    @staticmethod
    def build(rm_count: int) -> Model:
        from dataclasses import dataclass as _dc

        from .twophase import PREPARED, TwoPhaseSys
        from .twophase_compiled import TwoPhaseCompiled, _U32

        class _EditedCompiled(TwoPhaseCompiled):
            def property_conds(self, state):
                import jax.numpy as jnp

                base = TwoPhaseCompiled.property_conds(self, state)
                n = self.n
                w0 = state[0]
                some_prepared = jnp.zeros((), jnp.bool_)
                for rm in range(n):
                    rs = (w0 >> _U32(2 * rm)) & _U32(3)
                    some_prepared |= rs == _U32(PREPARED)
                return jnp.concatenate([base, some_prepared[None]])

        @_dc(frozen=True)
        class _Edited(TwoPhaseSys):
            def properties(self):
                return TwoPhaseSys.properties(self) + [
                    Property.sometimes(
                        "some rm prepared",
                        lambda _m, s: any(
                            r == PREPARED for r in s.rm_state
                        ),
                    ),
                ]

            def compiled(self):
                return _EditedCompiled(self)

        return _Edited(rm_count=rm_count)


class FnModel(Model):
    """A model defined by a function ``fn(prev_state_or_None, out_list)`` —
    the analog of the reference's blanket Model impl for functions
    (src/test_util.rs:119-137)."""

    def __init__(self, fn):
        self._fn = fn

    def init_states(self):
        out: list = []
        self._fn(None, out)
        return out

    def actions(self, state, actions):
        self._fn(state, actions)

    def next_state(self, state, action):
        return action


if __name__ == "__main__":
    import sys

    sys.exit(main())
