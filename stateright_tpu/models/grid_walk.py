"""Servable grid-walk workload: the monotone walk on ``[0, bound]²``
(models/fixtures.GridWalk), promoted from test fixture to registered
workload so the fleet's gang batcher (fleet/gang.py) has a real
allowlisted family to batch — differently-bounded walks share one
compiled gang program, which is exactly the "many small jobs, one
dispatch" case ROADMAP #3 names.  ``(bound+1)²`` unique states at depth
``2·bound``; the ALWAYS property never violates, so every completed
check is exhaustive.
"""

from __future__ import annotations

from .fixtures import GridWalk


def cli_spec():
    from ..cli import CliSpec

    return CliSpec(
        name="grid walk",
        build=lambda n: GridWalk(bound=n),
        default_n=8,
        n_meta="BOUND",
        tpu=True,
        tpu_kwargs=dict(capacity=1 << 12, max_frontier=1 << 7),
    )


def main(argv=None) -> int:
    from ..cli import example_main

    return example_main(cli_spec(), argv)


if __name__ == "__main__":
    import sys

    sys.exit(main())
