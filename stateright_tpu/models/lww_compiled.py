"""Bit-packed codec + device step kernel for the LWW-register CRDT.

Closes the last reference action family on device: **SelectRandom**
(src/actor/model.rs:320-333).  With raft covering Timeout/Crash/Recover
and ping_pong covering Drop, every family the reference enumerates now has
a compiled form.

Host model: models/lww_register.py (reference examples/lww-register.rs) —
each node nondeterministically sets a value or skews its clock via
``choose_random``; broadcasts merge by (timestamp, updater_id).

The random-choice *menu* needs no encoding: it is always exactly
``_populate_choices(local_clock)`` (repopulated by every on_random, and
on_msg never changes the clock), so the five SelectRandom lanes per node
are derivable from the packed clock — the host's ``random_choices`` dict
round-trips through ``decode`` by reconstruction.

Layout (N ≤ 3 nodes): one word per node — register present(1) value(2)
ts(6, offset-coded) updater(2), local_clock(6), maximum_used_clock(6) —
then M single-word envelope codes (src 2 | dst 2 | value 2 | ts 6 |
updater 2, +1 so 0 = empty).  Clocks are offset-coded around the model's
starting clock of 1000 with a ±31 budget; exhaustion flags loudly, and
the reference checks this model depth-bounded (examples/lww-register.rs:
190-196) so the budget covers any practical bound.
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import numpy as np

from ..actor import Envelope, Id, Network
from ..actor.model import ActorModelState
from ..parallel.compiled import CompiledModel
from .lww_register import (
    LwwActorState,
    LwwRegister,
    SetTime,
    SetValue,
    VALUES,
)

CLOCK_BASE = 1000 - 31  # offset code 0..63 covers clocks 969..1032
NET_SLOTS = 12
N_CHOICES = 5  # SetValue(A/B/C), SetTime(+1), SetTime(-1)


class LwwCompiled(CompiledModel):
    """Codec + device step kernel for ``lww_register.build_model()``."""

    step_flags = True

    def __init__(self, model):
        self.model = model
        self.n = len(model.actors)
        if self.n > 3:
            raise ValueError("packed lww supports at most 3 nodes")
        if model.lossy_network or model.max_crashes:
            raise ValueError("packed lww supports lossless, crash-free runs")
        if model.init_network.kind != "unordered_nonduplicating":
            raise ValueError(
                "packed lww supports the unordered_nonduplicating network"
            )
        self.m = NET_SLOTS
        self.state_width = self.n + self.m
        self.max_actions = self.m + N_CHOICES * self.n

    def cache_key(self):
        return (type(self).__qualname__, self.n)

    # --- small codes ----------------------------------------------------------

    @staticmethod
    def _clock_code(c: int) -> int:
        off = c - CLOCK_BASE
        if not 0 <= off < 64:
            raise ValueError(f"clock {c} outside the offset budget")
        return off

    @staticmethod
    def _val_code(v) -> int:
        return VALUES.index(v)

    def _encode_node(self, s: LwwActorState) -> int:
        bits = 0
        if s.register is not None:
            bits |= 1
            bits |= self._val_code(s.register.value) << 1
            bits |= self._clock_code(s.register.timestamp) << 3
            bits |= s.register.updater_id << 9
        bits |= self._clock_code(s.local_clock) << 11
        bits |= self._clock_code(s.maximum_used_clock) << 17
        return bits

    def _decode_node(self, bits: int) -> LwwActorState:
        reg = None
        if bits & 1:
            reg = LwwRegister(
                VALUES[(bits >> 1) & 3],
                CLOCK_BASE + ((bits >> 3) & 63),
                (bits >> 9) & 3,
            )
        return LwwActorState(
            register=reg,
            local_clock=CLOCK_BASE + ((bits >> 11) & 63),
            maximum_used_clock=CLOCK_BASE + ((bits >> 17) & 63),
        )

    def _env_code(self, env: Envelope) -> int:
        msg = env.msg
        assert isinstance(msg, LwwRegister), msg
        return 1 + (
            int(env.src)
            | (int(env.dst) << 2)
            | (self._val_code(msg.value) << 4)
            | (self._clock_code(msg.timestamp) << 6)
            | (msg.updater_id << 12)
        )

    def _env_of(self, code: int) -> Envelope:
        code -= 1
        return Envelope(
            Id(code & 3),
            Id((code >> 2) & 3),
            LwwRegister(
                VALUES[(code >> 4) & 3],
                CLOCK_BASE + ((code >> 6) & 63),
                (code >> 12) & 3,
            ),
        )

    # --- full state -----------------------------------------------------------

    def _choices_for(self, clock: int) -> Tuple[Tuple[str, tuple], ...]:
        menu = tuple(
            [SetValue(v) for v in VALUES]
            + [SetTime(clock + 1), SetTime(max(clock - 1, 0))]
        )
        return (("node_action", menu),)

    def encode(self, st: ActorModelState) -> np.ndarray:
        words = np.zeros(self.state_width, dtype=np.uint32)
        for i in range(self.n):
            words[i] = self._encode_node(st.actor_states[i])
            # The menu must be the derivable one, or decode cannot
            # reconstruct it.
            assert st.random_choices[i] == self._choices_for(
                st.actor_states[i].local_clock
            ), st.random_choices[i]
        # Duplicate envelopes are REACHABLE here (a register-less SetValue
        # stamps local_clock without bumping maximum_used_clock, so an
        # identical broadcast can be re-sent while the first is still in
        # flight) — the multiset is encoded as repeated sorted codes, like
        # raft's.
        codes: List[int] = []
        for env, count in st.network.counts:
            codes.extend([self._env_code(env)] * count)
        if len(codes) > self.m:
            raise ValueError(
                f"{len(codes)} in-flight envelopes exceed {self.m} slots"
            )
        codes.sort()
        for k, c in enumerate(codes):
            words[self.n + k] = c
        return words

    def decode(self, words: Sequence[int]) -> ActorModelState:
        nodes = tuple(
            self._decode_node(int(words[i])) for i in range(self.n)
        )
        counts: dict = {}
        for k in range(self.m):
            code = int(words[self.n + k])
            if code:
                env = self._env_of(code)
                counts[env] = counts.get(env, 0) + 1
        network = Network(
            kind="unordered_nonduplicating", counts=frozenset(counts.items())
        )
        return ActorModelState(
            actor_states=nodes,
            network=network,
            timers_set=(frozenset(),) * self.n,
            random_choices=tuple(
                self._choices_for(s.local_clock) for s in nodes
            ),
            crashed=(False,) * self.n,
            history=self.model.init_history,
            actor_storages=(None,) * self.n,
        )

    # --- device side ----------------------------------------------------------

    def step(self, state):
        import jax
        import jax.numpy as jnp

        ks = jnp.arange(self.m, dtype=jnp.uint32)
        dn, dv, df = jax.vmap(lambda k: self._deliver_lane(state, k))(ks)
        outs = [(dn, dv, df)]
        for i in range(self.n):
            for c in range(N_CHOICES):
                ns, valid, flag = self._random_lane(state, i, c)
                outs.append((ns[None], valid[None], flag[None]))
        nexts = jnp.concatenate([o[0] for o in outs])
        valid = jnp.concatenate([o[1] for o in outs])
        flags = jnp.concatenate([o[2] for o in outs])
        return nexts, valid, jnp.any(flags & valid)

    @staticmethod
    def _merge(p_a, v_a, t_a, u_a, v_b, t_b, u_b):
        """LwwRegister.merge: keep a iff (t_a, u_a) > (t_b, u_b) — with no
        register (p_a == 0) the incoming value always wins."""
        import jax.numpy as jnp

        u = jnp.uint32
        a_wins = (p_a == u(1)) & (
            (t_a > t_b) | ((t_a == t_b) & (u_a > u_b))
        )
        return (
            jnp.where(a_wins, v_a, v_b),
            jnp.where(a_wins, t_a, t_b),
            jnp.where(a_wins, u_a, u_b),
        )

    def _node_fields(self, word):
        import jax.numpy as jnp

        u = jnp.uint32
        return dict(
            present=word & u(1),
            val=(word >> u(1)) & u(3),
            ts=(word >> u(3)) & u(63),
            up=(word >> u(9)) & u(3),
            clock=(word >> u(11)) & u(63),
            max_used=(word >> u(17)) & u(63),
        )

    @staticmethod
    def _node_word(present, val, ts, up, clock, max_used):
        import jax.numpy as jnp

        u = jnp.uint32
        return (
            present.astype(u)
            | (val << u(1))
            | (ts << u(3))
            | (up << u(9))
            | (clock << u(11))
            | (max_used << u(17))
        )

    def _deliver_lane(self, state, k):
        import jax.numpy as jnp

        u = jnp.uint32
        n, m = self.n, self.m
        code = u(0)
        for j in range(m):
            code = jnp.where(k == u(j), state[n + j], code)
        occupied = code != u(0)
        # One Deliver per DISTINCT envelope: slots are sorted, so only the
        # first of an equal run is a valid lane (host iter_deliverable
        # enumerates multiset keys once).
        prev = u(0)
        for j in range(1, m):
            prev = jnp.where(k == u(j), state[n + j - 1], prev)
        first = (k == u(0)) | (prev != code)
        e = code - u(1)
        dst = (e >> u(2)) & u(3)
        mv = (e >> u(4)) & u(3)
        mt = (e >> u(6)) & u(63)
        mu = (e >> u(12)) & u(3)

        word = u(0)
        for i in range(n):
            word = jnp.where(dst == u(i), state[i], word)
        f = self._node_fields(word)
        nv, nt, nu = self._merge(
            f["present"], f["val"], f["ts"], f["up"], mv, mt, mu
        )
        new_word = self._node_word(
            jnp.ones((), jnp.bool_), nv, nt, nu, f["clock"], f["max_used"]
        )
        # Remove one copy of slot k; re-sort (no sends on deliver).
        slots = [
            jnp.where(k == u(j), u(0), state[n + j]) for j in range(m)
        ]
        cand = jnp.stack(slots)
        ones = u(0xFFFFFFFF)
        cand = jnp.where(cand == u(0), ones, cand)
        cand = jnp.sort(cand)
        new_slots = jnp.where(cand == ones, u(0), cand)
        head = [
            jnp.where(dst == u(i), new_word, state[i]) for i in range(n)
        ]
        ns = jnp.concatenate([jnp.stack(head), new_slots]).astype(u)
        return ns, occupied & first, jnp.zeros((), jnp.bool_)

    def _random_lane(self, state, i: int, c: int):
        """SelectRandom(node i, choice c): c in 0..2 = SetValue(VALUES[c]),
        c == 3 = SetTime(clock+1), c == 4 = SetTime(clock-1).  Always a
        successor (the host applies on_random unconditionally and the
        handler repopulates the same menu, actor/model.py:348-358)."""
        import jax.numpy as jnp

        u = jnp.uint32
        n, m = self.n, self.m
        f = self._node_fields(state[i])
        flag = jnp.zeros((), jnp.bool_)
        if c < 3:
            # SetValue: clock_value = local if no register else
            # max(local, max_used + 1); broadcast to peers.
            cv = jnp.where(
                f["present"] == u(1),
                jnp.maximum(f["clock"], f["max_used"] + u(1)),
                f["clock"],
            )
            flag = flag | (cv > u(63))
            new_word = self._node_word(
                jnp.ones((), jnp.bool_),
                u(c),
                cv,
                u(i),
                f["clock"],
                jnp.where(f["present"] == u(1), cv, f["max_used"]),
            )
            # The model's peer list includes the sender itself
            # (build_model passes every id to every actor), so the
            # broadcast goes to ALL nodes.
            sends = [
                u(1)
                + (
                    u(i)
                    | (u(p) << u(2))
                    | (u(c) << u(4))
                    | (cv << u(6))
                    | (u(i) << u(12))
                )
                for p in range(n)
            ]
        else:
            if c == 4:  # SetTime(max(clock - 1, 0))
                nclock = f["clock"] - u(1)
                flag = flag | (f["clock"] == u(0))  # offset floor, not 0
            else:  # SetTime(clock + 1)
                nclock = f["clock"] + u(1)
                flag = flag | (nclock > u(63))
            new_word = self._node_word(
                f["present"] == u(1), f["val"], f["ts"], f["up"],
                nclock, f["max_used"],
            )
            sends = []

        slots = [state[n + j] for j in range(m)]
        cand = jnp.stack(slots + sends) if sends else jnp.stack(slots)
        ones = u(0xFFFFFFFF)
        cand = jnp.where(cand == u(0), ones, cand)
        cand = jnp.sort(cand)
        overflow = jnp.any(cand[m:] != ones) if sends else jnp.zeros(
            (), jnp.bool_
        )
        new_slots = jnp.where(cand[:m] == ones, u(0), cand[:m])
        head = [
            new_word if j == i else state[j] for j in range(n)
        ]
        ns = jnp.concatenate([jnp.stack(head), new_slots]).astype(u)
        valid = jnp.ones((), jnp.bool_)
        return ns, valid, flag | overflow

    def property_conds(self, state):
        import jax.numpy as jnp

        u = jnp.uint32
        n, m = self.n, self.m
        net_empty = jnp.ones((), jnp.bool_)
        for j in range(m):
            net_empty = net_empty & (state[n + j] == u(0))
        regs = [self._node_fields(state[i]) for i in range(n)]
        agree = jnp.ones((), jnp.bool_)
        for i in range(1, n):
            same = (
                (regs[i]["present"] == regs[0]["present"])
                & (regs[i]["val"] == regs[0]["val"])
                & (regs[i]["ts"] == regs[0]["ts"])
                & (regs[i]["up"] == regs[0]["up"])
            )
            none_both = (regs[i]["present"] == u(0)) & (
                regs[0]["present"] == u(0)
            )
            agree = agree & (same | none_both)
        return jnp.stack([~net_empty | agree])


def compiled_lww(model) -> LwwCompiled:
    return LwwCompiled(model)
