"""Racy shared-counter models ("threads" as a direct Model).

Reference: examples/increment.rs (no lock — the "fin" invariant is
violated; 13 unique states at 2 threads, 8 with symmetry reduction per the
worked example in its module docs) and examples/increment_lock.rs (with a
lock — both invariants hold).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


from ..core.model import Model, Property


@dataclass(frozen=True)
class IncrementState:
    i: int
    # each thread: (t, pc)
    s: Tuple[Tuple[int, int], ...]

    def representative(self) -> "IncrementState":
        # Reference: examples/increment.rs:142-151 — just sort thread states.
        return IncrementState(self.i, tuple(sorted(self.s)))


@dataclass(frozen=True)
class Increment(Model):
    """SHARED = 0; N threads each do: 1: local = SHARED; 2: SHARED = local+1."""

    thread_count: int

    def init_states(self):
        return [IncrementState(0, ((0, 1),) * self.thread_count)]

    def actions(self, state, actions):
        for tid in range(self.thread_count):
            pc = state.s[tid][1]
            if pc == 1:
                actions.append(("Read", tid))
            elif pc == 2:
                actions.append(("Write", tid))

    def next_state(self, st, action):
        kind, n = action
        s = list(st.s)
        if kind == "Read":
            s[n] = (st.i, 2)
            return IncrementState(st.i, tuple(s))
        else:  # Write
            t = st.s[n][0]
            s[n] = (t, 3)
            return IncrementState(t + 1, tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda _m, st: sum(1 for (_t, pc) in st.s if pc == 3) == st.i,
            )
        ]


@dataclass(frozen=True)
class IncrementLockState:
    i: int
    lock: bool
    s: Tuple[Tuple[int, int], ...]

    def representative(self) -> "IncrementLockState":
        return IncrementLockState(self.i, self.lock, tuple(sorted(self.s)))


@dataclass(frozen=True)
class IncrementLock(Model):
    """Same counter with a lock; the invariants hold.
    Reference: examples/increment_lock.rs."""

    thread_count: int

    def init_states(self):
        return [IncrementLockState(0, False, ((0, 0),) * self.thread_count)]

    def actions(self, state, actions):
        for tid in range(self.thread_count):
            pc = state.s[tid][1]
            if pc == 0 and not state.lock:
                actions.append(("Lock", tid))
            elif pc == 1:
                actions.append(("Read", tid))
            elif pc == 2:
                actions.append(("Write", tid))
            elif pc == 3 and state.lock:
                actions.append(("Release", tid))

    def next_state(self, st, action):
        kind, n = action
        s = list(st.s)
        t, _pc = st.s[n]
        if kind == "Lock":
            s[n] = (t, 1)
            return IncrementLockState(st.i, True, tuple(s))
        if kind == "Read":
            s[n] = (st.i, 2)
            return IncrementLockState(st.i, st.lock, tuple(s))
        if kind == "Write":
            s[n] = (t, 3)
            return IncrementLockState(t + 1, st.lock, tuple(s))
        # Release
        s[n] = (t, 4)
        return IncrementLockState(st.i, False, tuple(s))

    def properties(self):
        return [
            Property.always(
                "fin",
                lambda _m, st: sum(1 for (_t, pc) in st.s if pc >= 3) == st.i,
            ),
            Property.always(
                "mutex",
                lambda _m, st: sum(1 for (_t, pc) in st.s if 1 <= pc < 4) <= 1,
            ),
        ]


def cli_spec(lock: bool = False):
    """This module's CLI/workload spec (resolved by serve/workloads.py);
    the unlocked variant genuinely violates its "fin" invariant."""
    from ..cli import CliSpec

    return CliSpec(
        name="increment-lock" if lock else "increment",
        build=lambda n: (IncrementLock if lock else Increment)(
            thread_count=n
        ),
        default_n=2,
        n_meta="THREAD_COUNT",
        symmetry=True,
    )


def main(argv=None) -> int:
    """CLI mirroring examples/increment.rs and examples/increment_lock.rs;
    pass ``lock`` as the first argument for the locked variant."""
    import sys as _sys

    from ..cli import example_main

    args = list(_sys.argv[1:] if argv is None else argv)
    lock = bool(args) and args[0] == "lock"
    if lock:
        args = args[1:]
    return example_main(cli_spec(lock), args)


if __name__ == "__main__":
    import sys

    sys.exit(main())
