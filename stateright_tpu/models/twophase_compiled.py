"""Bit-packed TPU form of the two-phase-commit model.

Host model: stateright_tpu.models.twophase (reference: examples/2pc.rs).
State packs into W=2 uint32 words for up to 12 RMs:

- word0: RM states, 2 bits each at bit 2*i (WORKING/PREPARED/COMMITTED/
  ABORTED); TM state (2 bits) at bit 24.
- word1: tm_prepared bitmap at bits [0, N); message-set bitmap — the
  reference's message *set* is finite (N ``Prepared(rm)`` + ``Commit`` +
  ``Abort``), so it packs exactly as N+2 presence bits: ``Prepared(i)`` at
  bit N+i, ``Commit`` at bit 2N, ``Abort`` at bit 2N+1.

Static action arity A = 2 + 5N, mirroring the host enumeration
(TmCommit, TmAbort, then per-RM TmRcvPrepared / RmPrepare /
RmChooseToAbort / RmRcvCommitMsg / RmRcvAbortMsg).  2pc's ``next_state``
never returns None, so a lane is valid iff its action guard holds.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..parallel.compiled import CompiledModel
from .twophase import (
    ABORTED,
    COMMITTED,
    MSG_ABORT,
    MSG_COMMIT,
    PREPARED,
    TM_ABORTED,
    TM_COMMITTED,
    TM_INIT,
    TwoPhaseState,
    TwoPhaseSys,
    WORKING,
    msg_prepared,
)

_U32 = jnp.uint32
_TM_SHIFT = 24


class TwoPhaseCompiled(CompiledModel):
    state_width = 2

    def __init__(self, model: TwoPhaseSys):
        n = model.rm_count
        if n > 12:
            raise ValueError("packed 2pc encoding supports at most 12 RMs")
        self.model = model
        self.n = n
        self.max_actions = 2 + 5 * n

    # --- host side -----------------------------------------------------------

    def encode(self, s: TwoPhaseState) -> np.ndarray:
        n = self.n
        w0 = 0
        for i, rs in enumerate(s.rm_state):
            w0 |= rs << (2 * i)
        w0 |= s.tm_state << _TM_SHIFT
        w1 = 0
        for i, p in enumerate(s.tm_prepared):
            w1 |= int(p) << i
        for m in s.msgs:
            if m == MSG_COMMIT:
                w1 |= 1 << (2 * n)
            elif m == MSG_ABORT:
                w1 |= 1 << (2 * n + 1)
            else:  # ("prepared", rm)
                w1 |= 1 << (n + m[1])
        return np.array([w0, w1], dtype=np.uint32)

    def decode(self, words: Sequence[int]) -> TwoPhaseState:
        n = self.n
        w0, w1 = int(words[0]), int(words[1])
        rm_state = tuple((w0 >> (2 * i)) & 3 for i in range(n))
        tm_state = (w0 >> _TM_SHIFT) & 3
        tm_prepared = tuple(bool((w1 >> i) & 1) for i in range(n))
        msgs = set()
        for i in range(n):
            if (w1 >> (n + i)) & 1:
                msgs.add(msg_prepared(i))
        if (w1 >> (2 * n)) & 1:
            msgs.add(MSG_COMMIT)
        if (w1 >> (2 * n + 1)) & 1:
            msgs.add(MSG_ABORT)
        return TwoPhaseState(rm_state, tm_state, tm_prepared, frozenset(msgs))

    # --- device side ---------------------------------------------------------

    def step(self, state):
        n = self.n
        w0, w1 = state[0], state[1]
        tm = (w0 >> _U32(_TM_SHIFT)) & _U32(3)
        tm_init = tm == _U32(TM_INIT)
        prepared_mask = _U32((1 << n) - 1)
        all_prepared = (w1 & prepared_mask) == prepared_mask
        commit_msg = ((w1 >> _U32(2 * n)) & _U32(1)) == _U32(1)
        abort_msg = ((w1 >> _U32(2 * n + 1)) & _U32(1)) == _U32(1)

        w0_tm_cleared = w0 & _U32(~(3 << _TM_SHIFT) & 0xFFFFFFFF)

        nexts0, nexts1, valids = [], [], []

        def emit(valid, nw0, nw1):
            valids.append(valid)
            nexts0.append(nw0)
            nexts1.append(nw1)

        # TmCommit (examples/2pc.rs:100-102)
        emit(
            tm_init & all_prepared,
            w0_tm_cleared | _U32(TM_COMMITTED << _TM_SHIFT),
            w1 | _U32(1 << (2 * n)),
        )
        # TmAbort
        emit(
            tm_init,
            w0_tm_cleared | _U32(TM_ABORTED << _TM_SHIFT),
            w1 | _U32(1 << (2 * n + 1)),
        )
        for rm in range(n):
            rm_bits = (w0 >> _U32(2 * rm)) & _U32(3)
            rm_working = rm_bits == _U32(WORKING)
            prep_msg = ((w1 >> _U32(n + rm)) & _U32(1)) == _U32(1)
            w0_rm_cleared = w0 & _U32(~(3 << (2 * rm)) & 0xFFFFFFFF)
            # TmRcvPrepared(rm)
            emit(tm_init & prep_msg, w0, w1 | _U32(1 << rm))
            # RmPrepare(rm)
            emit(
                rm_working,
                w0_rm_cleared | _U32(PREPARED << (2 * rm)),
                w1 | _U32(1 << (n + rm)),
            )
            # RmChooseToAbort(rm)
            emit(rm_working, w0_rm_cleared | _U32(ABORTED << (2 * rm)), w1)
            # RmRcvCommitMsg(rm)
            emit(commit_msg, w0_rm_cleared | _U32(COMMITTED << (2 * rm)), w1)
            # RmRcvAbortMsg(rm)
            emit(abort_msg, w0_rm_cleared | _U32(ABORTED << (2 * rm)), w1)

        nexts = jnp.stack(
            [jnp.stack(nexts0), jnp.stack(nexts1)], axis=-1
        )  # [A, W]
        return nexts.astype(_U32), jnp.stack(valids)

    def canon_spec(self):
        """RM records are fully described by three bit fields — state
        (word0, 2 bits at 2i), tm_prepared (word1, bit i), and the
        Prepared(i) message presence (word1, bit n+i) — so sorting whole
        records canonicalizes exactly the orbit (the reference's
        representative sorts by rm_state alone and tie-breaks by index,
        examples/2pc.rs:203-223, which is traversal-order-dependent; see
        parallel/canon.py's module docstring).  The TM state and the
        Commit/Abort message bits are permutation-invariant and stay
        untouched."""
        from ..parallel.canon import CanonSpec, field

        n = self.n
        return CanonSpec(
            n=n,
            fields=(
                field(word=0, shift=0, width=2),   # rm_state
                field(word=1, shift=0, width=1),   # tm_prepared
                field(word=1, shift=n, width=1),   # Prepared(i) in msgs
            ),
        )

    def property_conds(self, state):
        n = self.n
        w0 = state[0]
        committed = jnp.zeros((), jnp.bool_)
        aborted = jnp.zeros((), jnp.bool_)
        all_committed = jnp.ones((), jnp.bool_)
        all_aborted = jnp.ones((), jnp.bool_)
        for rm in range(n):
            rs = (w0 >> _U32(2 * rm)) & _U32(3)
            committed |= rs == _U32(COMMITTED)
            aborted |= rs == _U32(ABORTED)
            all_committed &= rs == _U32(COMMITTED)
            all_aborted &= rs == _U32(ABORTED)
        # Order matches TwoPhaseSys.properties():
        #   sometimes "abort agreement", sometimes "commit agreement",
        #   always "consistent".
        return jnp.stack([all_aborted, all_committed, ~(aborted & committed)])


