"""Bit-packed TPU form of the ping-pong actor model.

This is the actor-layer compilation proof: unlike 2pc (a direct model),
ping_pong is an ``ActorModel`` whose state embeds the *network* — here the
unordered **duplicating** fabric: a set of envelopes that persist across
deliveries plus the last-delivered marker (src/actor/network.rs:52-57,
224-228) — and whose actions are the model-generated Deliver/Drop families
(src/actor/model.rs:269-333) with unordered no-op suppression
(src/actor/model.rs:360-366).

Packing (host model: models/ping_pong.py, maintains_history=False; the
constant history/timers/crashed/storages fields need no bits):

- bits 0-3:  actor 0 counter; bits 4-7: actor 1 counter (values can
  transiently reach max_nat+1 before the boundary filter removes them).
- bits 8..8+E: envelope presence bitmap, E = 2*(max_nat+2) possible
  envelopes — ``Ping(v)`` (always 0→1) at id v, ``Pong(v)`` (always 1→0)
  at id (max_nat+2)+v, for v in [0, max_nat+1].
- next 5 bits: last-delivered marker (0 = none, else 1+envelope id).

Static action arity A = 2E: Deliver(e) then Drop(e) per possible envelope;
Drop lanes are valid only on a lossy network.
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..actor import Envelope, Id, Network
from ..actor.model import ActorModelState
from ..parallel.compiled import CompiledModel
from .ping_pong import Ping, Pong

_U32 = jnp.uint32
_C0_SHIFT, _C1_SHIFT, _ENV_SHIFT = 0, 4, 8


class PingPongCompiled(CompiledModel):
    state_width = 2

    def __init__(self, model):
        self.model = model
        cfg = model.cfg
        if cfg.maintains_history:
            raise ValueError(
                "packed ping_pong supports maintains_history=False (the "
                "golden configurations)"
            )
        if cfg.max_nat > 13:
            raise ValueError("packed ping_pong encoding supports max_nat <= 13")
        self.max_nat = cfg.max_nat
        self.lossy = model.lossy_network
        self.e = 2 * (cfg.max_nat + 2)  # possible envelopes
        self.last_shift = _ENV_SHIFT + self.e
        # Drop lanes exist only on lossy networks; a lossless model's step
        # emits just the Deliver family.
        self.max_actions = 2 * self.e if self.lossy else self.e

    def cache_key(self):
        return (
            type(self).__qualname__,
            self.max_nat,
            self.lossy,
        )

    # --- envelope numbering --------------------------------------------------

    def _env_id(self, env: Envelope) -> int:
        if isinstance(env.msg, Ping):
            assert (int(env.src), int(env.dst)) == (0, 1)
            return env.msg.value
        assert (int(env.src), int(env.dst)) == (1, 0)
        return (self.max_nat + 2) + env.msg.value

    def _env_of(self, env_id: int) -> Envelope:
        half = self.max_nat + 2
        if env_id < half:
            return Envelope(Id(0), Id(1), Ping(env_id))
        return Envelope(Id(1), Id(0), Pong(env_id - half))

    # --- host side -----------------------------------------------------------

    def encode(self, s: ActorModelState) -> np.ndarray:
        bits = int(s.actor_states[0]) << _C0_SHIFT
        bits |= int(s.actor_states[1]) << _C1_SHIFT
        for env in s.network.envelopes:
            bits |= 1 << (_ENV_SHIFT + self._env_id(env))
        last = s.network.last_msg
        bits |= (
            (1 + self._env_id(last)) if last is not None else 0
        ) << self.last_shift
        return np.array([bits & 0xFFFFFFFF, bits >> 32], dtype=np.uint32)

    def decode(self, words: Sequence[int]) -> ActorModelState:
        bits = int(words[0]) | (int(words[1]) << 32)
        c0 = (bits >> _C0_SHIFT) & 0xF
        c1 = (bits >> _C1_SHIFT) & 0xF
        envs = frozenset(
            self._env_of(e)
            for e in range(self.e)
            if (bits >> (_ENV_SHIFT + e)) & 1
        )
        last_code = (bits >> self.last_shift) & 0x1F
        if last_code:
            network = Network.new_unordered_duplicating_with_last_msg(
                envs, self._env_of(last_code - 1)
            )
        else:
            network = Network.new_unordered_duplicating(envs)
        return ActorModelState(
            actor_states=(c0, c1),
            network=network,
            timers_set=(frozenset(), frozenset()),
            random_choices=((), ()),
            crashed=(False, False),
            history=(0, 0),
            actor_storages=(None, None),
        )

    # --- device side ---------------------------------------------------------

    def _unpack(self, state):
        bits_lo = state[0]
        bits_hi = state[1]
        c0 = (bits_lo >> _U32(_C0_SHIFT)) & _U32(0xF)
        c1 = (bits_lo >> _U32(_C1_SHIFT)) & _U32(0xF)
        return bits_lo, bits_hi, c0, c1

    def _bit(self, pos: int):
        """(lo_mask, hi_mask) for absolute bit position ``pos``."""
        if pos < 32:
            return _U32(1 << pos), _U32(0)
        return _U32(0), _U32(1 << (pos - 32))

    def step(self, state):
        half = self.max_nat + 2
        lo, hi, c0, c1 = self._unpack(state)
        nexts_lo, nexts_hi, valids = [], [], []

        def emit(valid, nlo, nhi):
            valids.append(valid)
            nexts_lo.append(nlo)
            nexts_hi.append(nhi)

        last_clear_lo, last_clear_hi = _U32(0xFFFFFFFF), _U32(0xFFFFFFFF)
        for b in range(5):
            pos = self.last_shift + b
            blo, bhi = self._bit(pos)
            last_clear_lo &= ~blo
            last_clear_hi &= ~bhi

        for e in range(self.e):
            plo, phi = self._bit(_ENV_SHIFT + e)
            present = ((lo & plo) | (hi & phi)) != 0
            is_ping = e < half
            v = e if is_ping else e - half

            # Deliver(e): guard = receiver counter == msg value (else the
            # handler is a no-op, suppressed on unordered networks).
            if is_ping:
                guard = c1 == _U32(v)
                # c1 += 1; send Pong(v); last = e
                nlo = (lo & _U32(~(0xF << _C1_SHIFT) & 0xFFFFFFFF)) | (
                    (c1 + _U32(1)) << _U32(_C1_SHIFT)
                )
                nhi = hi
                slo, shi = self._bit(_ENV_SHIFT + half + v)
            else:
                guard = c0 == _U32(v)
                # c0 += 1; send Ping(v+1); last = e
                nlo = (lo & _U32(~0xF & 0xFFFFFFFF)) | (c0 + _U32(1))
                nhi = hi
                slo, shi = self._bit(_ENV_SHIFT + v + 1)
            nlo = nlo | slo
            nhi = nhi | shi
            # last-delivered marker := 1 + e
            llo, lhi = self._last_code_bits(1 + e)
            nlo = (nlo & last_clear_lo) | llo
            nhi = (nhi & last_clear_hi) | lhi
            emit(present & guard, nlo, nhi)

        if self.lossy:
            for e in range(self.e):
                plo, phi = self._bit(_ENV_SHIFT + e)
                present = ((lo & plo) | (hi & phi)) != 0
                # Drop(e): remove the envelope; marker unchanged.
                emit(present, lo & ~plo, hi & ~phi)

        nexts = jnp.stack(
            [jnp.stack(nexts_lo), jnp.stack(nexts_hi)], axis=-1
        ).astype(_U32)
        return nexts, jnp.stack(valids)

    def _last_code_bits(self, code: int):
        lo = hi = 0
        for b in range(5):
            if (code >> b) & 1:
                pos = self.last_shift + b
                if pos < 32:
                    lo |= 1 << pos
                else:
                    hi |= 1 << (pos - 32)
        return _U32(lo), _U32(hi)

    def boundary(self, state):
        _lo, _hi, c0, c1 = self._unpack(state)
        m = _U32(self.max_nat)
        return (c0 <= m) & (c1 <= m)

    def property_conds(self, state):
        _lo, _hi, c0, c1 = self._unpack(state)
        max_nat = _U32(self.max_nat)
        delta_ok = jnp.where(c0 > c1, c0 - c1, c1 - c0) <= _U32(1)
        at_max = (c0 == max_nat) | (c1 == max_nat)
        over_max = (c0 == max_nat + _U32(1)) | (c1 == max_nat + _U32(1))
        true_ = jnp.ones((), jnp.bool_)
        # Order matches PingPongCfg.into_model() properties:
        #   always "delta within 1", sometimes "can reach max",
        #   eventually "must reach max", eventually "must exceed max",
        #   always "#in <= #out", eventually "#out <= #in + 1"
        # (history is constant (0, 0) when not maintained).
        return jnp.stack(
            [delta_ok, at_max, at_max, over_max, true_, true_]
        )


def compiled_ping_pong(model) -> PingPongCompiled:
    """Compiled form for a ``PingPongCfg(...).into_model()`` model on a
    (possibly lossy) unordered duplicating network."""
    return PingPongCompiled(model)
