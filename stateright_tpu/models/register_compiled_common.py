"""Shared device machinery for register-harness workloads.

Every model built on the register test harness (actor/register.py — paxos,
the ABD register, …) shares the same client-side structure: scripted
clients that Put once then Get (``RegisterClient(put_count=1)``), a
``LinearizabilityTester`` history recorded through the Get/Put ↔
GetOk/PutOk hooks, and therefore the same packed client/tester layout and
the same exact on-device linearizability decision.  This module carries
that shared half so each protocol's compiled model only implements its
server records and message kinds.

Layout owned here (C clients, S servers):

- one *client word* of 4-bit records: awaiting kind (0 none / 1 put /
  2 get) + op_count, per client;
- C *tester words*: phase (3b), write-invocation snapshot (2b per other
  client), read-invocation snapshot (same), read value (2b) — an injective
  encoding of the ``LinearizabilityTester`` state for this client
  (consistency.py:198-239; clients invoke their Put at ``on_start``, so
  the write snapshot is always empty in reachable states).

The linearizability decision is a Wing&Gong-style subset-reachability DP
over the ≤ 2C register operations — see ``device_linearizable`` and the
exhaustive differential (including violations) in tests/test_paxos_tpu.py.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..actor.ids import Id
from ..actor.register import ClientState
from ..semantics.register import READ, ReadOk, WRITE_OK, WriteOp


def representative_slot_code(state, net0: int, m: int, k):
    """(code, occupied) for unordered-multiset Deliver lane ``k``.

    Slots hold SORTED envelope codes with duplicates as repeated codes
    (host multiset count > 1, like the raft codec).  The host enumerates
    one Deliver per DISTINCT envelope (network.iter_deliverable), so only
    the first slot of an equal-code run is the representative lane —
    later copies of a duplicated send stay in flight.  Shared by the
    paxos/ABD/single-copy codecs so the rule cannot drift."""
    import jax.numpy as jnp

    u = jnp.uint32
    sel = jnp.arange(m, dtype=u)
    slots = state[net0 : net0 + m]
    code = jnp.sum(jnp.where(sel == k, slots, u(0)))
    prev = jnp.sum(jnp.where(sel == k - u(1), slots, u(0)))
    occupied = (code != u(0)) & ((k == u(0)) | (prev != code))
    return code, occupied


def decode_slot_counts(words, net0: int, m: int, env_of):
    """Host decode of the slot section back to multiset (env, count)
    pairs, counting repeated codes.  Shared across the register codecs."""
    env_counts: dict = {}
    for k in range(m):
        code = int(words[net0 + k])
        if code:
            env = env_of(code)
            env_counts[env] = env_counts.get(env, 0) + 1
    return frozenset(env_counts.items())


class RegisterClientCodec:
    """Codec + device predicates for the harness's client/tester section.

    ``cli_word``: index of the packed client-record word; ``tst0``: index
    of the first tester word.  ``values[i]`` is client i's put value
    (actor/register.py:126).
    """

    def __init__(self, server_count: int, client_count: int, cli_word: int,
                 tst0: int):
        self.s = server_count
        self.c = client_count
        self.cli_word = cli_word
        self.tst0 = tst0
        self.lcb = 2 * (client_count - 1)
        # Value codes 0..C (0 = NULL): width derived, not hard-coded, so
        # bench-scale configs (paxos check 6, single-copy check 4 —
        # reference bench.sh:27-34) pack correctly.
        self.vb = max(2, client_count.bit_length())
        # Tester word: phase(3) + two snapshots(lcb each) + value(vb); the
        # client word holds 4 bits per client.  Both must fit one u32.
        if 3 + 2 * self.lcb + self.vb > 32 or 4 * client_count > 32:
            raise ValueError(
                f"register harness supports at most 7 clients "
                f"(got {client_count}: tester word needs "
                f"{3 + 2 * self.lcb + self.vb} bits)"
            )
        self.values = tuple(
            chr(ord("A") + i) for i in range(client_count)
        )

    # --- host side -----------------------------------------------------------

    def value_code(self, v, null_value) -> int:
        """0 = NULL, 1+i = client i's value."""
        if v == null_value:
            return 0
        return 1 + self.values.index(v)

    def value_of(self, code: int, null_value):
        return null_value if code == 0 else self.values[code - 1]

    def encode_clients(self, actor_states) -> int:
        bits = 0
        for i in range(self.c):
            cs: ClientState = actor_states[self.s + i]
            if cs.awaiting is None:
                kind = 0
            elif cs.awaiting == self.s + i:
                kind = 1  # awaiting the put
            else:
                assert cs.awaiting == 2 * (self.s + i)
                kind = 2  # awaiting the get
            assert cs.op_count <= 3
            bits |= (kind | (cs.op_count << 2)) << (4 * i)
        return bits

    def decode_clients(self, bits: int) -> List[ClientState]:
        out = []
        for i in range(self.c):
            nib = (bits >> (4 * i)) & 0xF
            kind, op_count = nib & 0x3, nib >> 2
            awaiting = {0: None, 1: self.s + i, 2: 2 * (self.s + i)}[kind]
            out.append(ClientState(awaiting=awaiting, op_count=op_count))
        return out

    def _lc_code(self, last_completed, me: int) -> int:
        """Snapshot tuple -> 2 bits per other client (0 absent, else idx+1)."""
        lc = dict(last_completed)
        bits = 0
        slot = 0
        for j in range(self.c):
            if j == me:
                continue
            v = lc.get(Id(self.s + j))
            bits |= (0 if v is None else v + 1) << (2 * slot)
            slot += 1
        return bits

    def _lc_of(self, bits: int, me: int):
        out = []
        slot = 0
        for j in range(self.c):
            if j == me:
                continue
            v = (bits >> (2 * slot)) & 0x3
            if v:
                out.append((Id(self.s + j), v - 1))
            slot += 1
        return tuple(sorted(out))

    def encode_tester(self, h, me: int, null_value) -> int:
        tid = Id(self.s + me)
        hist = h.history_by_thread.get(tid)
        inflight = h.in_flight_by_thread.get(tid)
        lcb = self.lcb
        if hist is None and inflight is None:
            return 0  # phase 0
        if inflight is not None and not hist:
            lc, op = inflight
            assert op == WriteOp(self.values[me])
            return 1 | (self._lc_code(lc, me) << 3)
        assert hist[0][1] == WriteOp(self.values[me]) and hist[0][2] == WRITE_OK
        lc_w = self._lc_code(hist[0][0], me)
        if len(hist) == 1 and inflight is None:
            return 2 | (lc_w << 3)
        if len(hist) == 1:
            lc, op = inflight
            assert op == READ
            return 3 | (lc_w << 3) | (self._lc_code(lc, me) << (3 + lcb))
        assert len(hist) == 2 and inflight is None and hist[1][1] == READ
        lc_r = self._lc_code(hist[1][0], me)
        vcode = self.value_code(hist[1][2].value, null_value)
        return 4 | (lc_w << 3) | (lc_r << (3 + lcb)) | (vcode << (3 + 2 * lcb))

    def decode_tester_into(self, h, bits: int, me: int, null_value) -> None:
        tid = Id(self.s + me)
        phase = bits & 0x7
        if phase == 0:
            return
        lcb = self.lcb
        lc_w = self._lc_of((bits >> 3) & ((1 << lcb) - 1), me)
        if phase == 1:
            h.in_flight_by_thread[tid] = (lc_w, WriteOp(self.values[me]))
            h.history_by_thread[tid] = ()
            return
        entry_w = (lc_w, WriteOp(self.values[me]), WRITE_OK)
        if phase == 2:
            h.history_by_thread[tid] = (entry_w,)
            return
        lc_r = self._lc_of((bits >> (3 + lcb)) & ((1 << lcb) - 1), me)
        if phase == 3:
            h.history_by_thread[tid] = (entry_w,)
            h.in_flight_by_thread[tid] = (lc_r, READ)
            return
        vcode = (bits >> (3 + 2 * lcb)) & ((1 << self.vb) - 1)
        h.history_by_thread[tid] = (
            entry_w,
            (lc_r, READ, ReadOk(self.value_of(vcode, null_value))),
        )

    # --- device side ----------------------------------------------------------

    def client_record(self, state, ci):
        """(kind, op_count) of (possibly clamped) client ``ci``; plus the
        clamped index usable for in-bounds tester-word selects."""
        import jax.numpy as jnp

        u = jnp.uint32
        ci = jnp.minimum(ci, u(self.c - 1))
        cli = state[self.cli_word]
        nib = (cli >> (u(4) * ci)) & u(0xF)
        return ci, cli, nib & u(3), nib >> u(2)

    def tester_word(self, state, ci):
        import jax.numpy as jnp

        u = jnp.uint32
        tw = u(0)
        for j in range(self.c):
            tw = jnp.where(ci == u(j), state[self.tst0 + j], tw)
        return tw

    def putok_transition(self, state, ci, cli, tw):
        """Client ``ci`` receives its PutOk: nibble -> (get, 2); tester
        phase 1 -> 3, snapshotting the other clients' completed counts at
        the Get invocation (consistency.py:215)."""
        import jax.numpy as jnp

        u = jnp.uint32
        cli_new = (cli & ~(u(0xF) << (u(4) * ci))) | (u(10) << (u(4) * ci))
        phases = [state[self.tst0 + j] & u(0x7) for j in range(self.c)]
        counts = [
            (phases[j] >= u(2)).astype(u) + (phases[j] == u(4)).astype(u)
            for j in range(self.c)
        ]
        lc_opts = []
        for me in range(self.c):
            bits = u(0)
            slot = 0
            for j in range(self.c):
                if j == me:
                    continue
                bits = bits | (counts[j] << u(2 * slot))
                slot += 1
            lc_opts.append(bits)
        lc_r = u(0)
        for me in range(self.c):
            lc_r = jnp.where(ci == u(me), lc_opts[me], lc_r)
        lc_w_old = (tw >> u(3)) & u((1 << self.lcb) - 1)
        tw_new = u(3) | (lc_w_old << u(3)) | (lc_r << u(3 + self.lcb))
        return cli_new, tw_new

    def getok_transition(self, ci, cli, tw, value_code):
        """Client ``ci`` receives its GetOk(value): nibble -> (done, 3);
        tester phase 3 -> 4 recording the read value."""
        import jax.numpy as jnp

        u = jnp.uint32
        cli_new = (cli & ~(u(0xF) << (u(4) * ci))) | (u(12) << (u(4) * ci))
        tw_new = (tw & ~u(7)) | u(4) | (value_code << u(3 + 2 * self.lcb))
        return cli_new, tw_new

    def device_linearizable(self, state):
        """Exact linearizability of the recorded register history.

        The host property runs ``LinearizabilityTester.serialized_history()``
        — an exponential interleaving search with real-time pruning
        (semantics/consistency.py:241-295).  On device the same decision is
        a reachability DP over Wing&Gong-style configurations: subsets of
        the ≤ 2C register operations crossed with the register value, where
        an op may be appended iff its real-time prerequisites (from the
        tester's last-completed snapshots) are already in the subset and,
        for a read, the register holds the value it returned.  The history
        is linearizable iff a configuration containing every *completed*
        op is reachable (in-flight writes are optional; in-flight reads are
        always droppable).  Exactness is pinned by tests/test_paxos_tpu.py
        against the host tester over both full reachable state spaces and
        an exhaustive synthetic tester-state enumeration (including
        violations).

        The subset dimension is BIT-PACKED into u32 lanes (bit k of word w
        = subset 32w+k), so the DP state is ``[nv, nsub/32]`` u32 instead
        of ``[nsub, nv]`` bool and every transition is word-parallel:

        - appending op o maps subset ``sub^bit`` -> ``sub``, which for
          o < 5 is an in-word shift by 2^o masked to lanes with bit o set,
          and for o >= 5 a static word-level butterfly (low half -> high
          half at stride 2^(o-5));
        - the real-time gate ``pm[o] ⊆ sub`` is a *superset indicator*,
          built by ANDing the static has-bit masks of pm's set bits — no
          per-subset arithmetic at all (pm never contains o itself: writes
          have empty masks and a read's mask holds only other ops, so the
          gate over ``sub`` equals the gate over ``sub^bit``).

        At C=6 this turns 28,672 bool cells/state into 896 u32 words/state
        (the difference between `paxos check 6` lowering and running —
        VERDICT r3 #1; cost table in docs/TPU_PAXOS_DESIGN.md).
        """
        import jax.numpy as jnp

        u = jnp.uint32
        c = self.c
        n_ops = 2 * c  # op i = W_i (client i's put), op c+i = R_i (its get)
        nsub = 1 << n_ops
        nv = c + 1  # register values: 0 = NULL, 1+i = client i's value
        nwords = max(1, nsub // 32)
        lcb = self.lcb
        tst0 = self.tst0

        tw = [state[tst0 + i] for i in range(c)]
        phase = [w & u(7) for w in tw]
        lc_r = [(w >> u(3 + lcb)) & u((1 << lcb) - 1) for w in tw]
        v_read = [
            (w >> u(3 + 2 * lcb)) & u((1 << self.vb) - 1) for w in tw
        ]

        w_completed = [phase[i] >= u(2) for i in range(c)]
        w_present = [phase[i] >= u(1) for i in range(c)]
        r_present = [phase[i] == u(4) for i in range(c)]  # completed reads

        # Real-time prerequisite masks.  A snapshot code about thread j
        # constrains only j's *completed* ops (consistency.py:252-261).
        pm = []
        for i in range(c):
            pm.append(u(0))  # writes invoke at init: empty snapshot
        for i in range(c):
            mask = u(1 << i)  # program order: W_i before R_i
            slot = 0
            for j in range(c):
                if j == i:
                    continue
                cj = (lc_r[i] >> u(2 * slot)) & u(3)
                mask = mask | jnp.where(
                    (cj >= u(1)) & w_completed[j], u(1 << j), u(0)
                )
                mask = mask | jnp.where(
                    (cj >= u(2)) & r_present[j], u(1 << (c + j)), u(0)
                )
                slot += 1
            pm.append(mask)
        present = w_present + r_present

        # Static has-bit masks: HAS[b] bit k of word w <=> subset 32w+k
        # contains op b.  Only real subsets get bits, so for nsub < 32 the
        # unused high lanes of the single word can never light up.
        sub_np = np.arange(nsub, dtype=np.uint64)
        weights = (np.uint64(1) << np.arange(32, dtype=np.uint64))
        has_np = np.empty((n_ops, nwords), np.uint32)
        for b in range(n_ops):
            bits = ((sub_np >> np.uint64(b)) & np.uint64(1)).astype(np.uint64)
            pad = np.zeros(nwords * 32 - nsub, np.uint64)
            bits = np.concatenate([bits, pad]).reshape(nwords, 32)
            has_np[b] = (bits * weights[None, :]).sum(axis=1).astype(np.uint32)
        HAS = jnp.asarray(has_np)  # [n_ops, nwords]
        ones = jnp.full((nwords,), 0xFFFFFFFF, u)

        def superset_indicator(mask_scalar):
            """Packed indicator of {sub : mask ⊆ sub} via AND of HAS rows."""
            out = ones
            for b in range(n_ops):
                bit_set = (mask_scalar >> u(b)) & u(1)
                out = out & jnp.where(bit_set == u(1), HAS[b], ones)
            return out

        # Per-op gates, hoisted out of the sweep (pm is sweep-invariant).
        gates = []
        for o in range(n_ops):
            g = superset_indicator(pm[o]) & HAS[o]
            gates.append(jnp.where(present[o], g, jnp.zeros((), u)))
        v_arange = jnp.arange(nv, dtype=u)
        # Read-op value-column mask, also sweep-invariant: [n_ops, nv].
        colmask = []
        for o in range(n_ops):
            if o < c:
                colmask.append((v_arange == u(1 + o)).astype(u) * u(0xFFFFFFFF))
            else:
                colmask.append(
                    (v_arange == v_read[o - c]).astype(u) * u(0xFFFFFFFF)
                )

        def shift_src(dp, o):
            """dp word-image of sub^bit(o) at lanes with bit o set."""
            if o < 5:
                return (dp << u(1 << o)) & HAS[o][None, :]
            stride = 1 << (o - 5)
            r = dp.reshape(nv, nwords // (2 * stride), 2, stride)
            lowhalf = r[:, :, 0:1, :]
            shifted = jnp.concatenate(
                [jnp.zeros_like(lowhalf), lowhalf], axis=2
            )
            return shifted.reshape(nv, nwords)

        def sweep(dp):
            for o in range(n_ops):
                shifted = shift_src(dp, o)
                if o < c:
                    # Write: any source value reaches; register becomes 1+o.
                    any_v = shifted[0]
                    for v in range(1, nv):
                        any_v = any_v | shifted[v]
                    add = any_v & gates[o]
                    dp = dp | (add[None, :] & colmask[o][:, None])
                else:
                    # Read: register must already equal the returned value.
                    dp = dp | (shifted & gates[o] & colmask[o][:, None])
            return dp

        # ``| (state[0] & 0)`` types the loop carry as varying so the DP
        # also traces under the sharded engine's shard_map (a constant
        # carry with a varying loop body fails scan type checking).
        dp0 = (
            jnp.zeros((nv, nwords), u) | (state[0] & u(0))
        ).at[0, 0].set(u(1))
        # n_ops rounds of relaxation reach any appendable-op order; the
        # round body is o-unrolled but round-invariant, so a fori_loop
        # keeps the trace 2C× smaller than full unrolling.
        import jax

        dp = jax.lax.fori_loop(
            0, n_ops, lambda _, d: sweep(d), dp0, unroll=False
        )

        req = u(0)
        for i in range(c):
            req = req | jnp.where(w_completed[i], u(1 << i), u(0))
            req = req | jnp.where(r_present[i], u(1 << (c + i)), u(0))
        covers = superset_indicator(req)
        return jnp.any((dp & covers[None, :]) != u(0))
