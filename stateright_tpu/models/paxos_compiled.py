"""Bit-packed codec for the paxos workload (docs/TPU_PAXOS_DESIGN.md).

This module implements the host-side half of compiling `paxos check C`
for the TPU wavefront: an injective packed encoding of the full
``ActorModelState`` — three PaxosState server records, C scripted register
clients, the nonduplicating network as sorted envelope-code slots, and the
LinearizabilityTester history (phases + real-time snapshots + read
values).  The differential tests enumerate the host model's entire
reachable set and pin ``decode(encode(s)) == s``, which simultaneously
validates every boundedness assumption (rounds, in-flight envelopes,
multiset counts ≤ 1, proposal space) against reality.

The device step kernel builds on this codec (next round; the design note
has the plan).  Word layout (C clients, S=3 servers):

- words 0..5: three 47-bit server records, 2 words each;
- word 6: client records, 4 bits each (awaiting kind 2b + op_count 2b);
- words 7..7+M: network slots — sorted nonzero envelope codes (M=16);
- last C words: per-client tester record (phase 3b, write/read-invocation
  snapshots 2b per other client each, read value 2b).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ..actor import Envelope, Id, Network
from ..actor.model import ActorModelState
from ..actor.register import ClientState, Get, GetOk, Internal, Put, PutOk
from ..parallel.compiled import CompiledModel
from ..semantics import LinearizabilityTester, Register
from ..semantics.register import READ, ReadOk, WriteOp, WRITE_OK
from .paxos import (
    Accept,
    Accepted,
    Decided,
    NULL_VALUE,
    PaxosState,
    Prepare,
    Prepared,
)

S = 3  # servers (the golden configurations fix three)
MAX_ROUND = 15  # 4 bits; validated by the differential reachability test
NET_SLOTS = 16

# Message tags for envelope codes.
_T_PUT, _T_GET, _T_PUTOK, _T_GETOK = 0, 1, 2, 3
_T_PREPARE, _T_PREPARED, _T_ACCEPT, _T_ACCEPTED, _T_DECIDED = 4, 5, 6, 7, 8


class PaxosCompiled(CompiledModel):
    """Codec (encode/decode/init) for ``PaxosModelCfg.into_model()``."""

    def __init__(self, model):
        self.model = model
        cfg = model.cfg
        if cfg.server_count != S:
            raise ValueError("packed paxos fixes server_count=3")
        if cfg.client_count > 3:
            raise ValueError("packed paxos supports at most 3 clients")
        self.c = cfg.client_count
        self.values = tuple(
            chr(ord("A") + i) for i in range(self.c)
        )  # client i's put value (actor/register.py:126)
        # Proposal space: client i's put is (req_id=S+i, requester=S+i, v_i).
        self.proposals = tuple(
            (S + i, Id(S + i), self.values[i]) for i in range(self.c)
        )
        self.state_width = 2 * S + 1 + NET_SLOTS + self.c
        self.max_actions = NET_SLOTS  # Deliver per slot (lossless, no timers)

    def cache_key(self):
        return (type(self).__qualname__, self.c)

    # --- small-code helpers --------------------------------------------------

    def _value_code(self, v) -> int:
        """0 = NULL, 1+i = client i's value."""
        if v == NULL_VALUE:
            return 0
        return 1 + self.values.index(v)

    def _value_of(self, code: int):
        return NULL_VALUE if code == 0 else self.values[code - 1]

    def _proposal_code(self, p) -> int:
        """0 = None, else 1+index."""
        return 0 if p is None else 1 + self.proposals.index(tuple(p))

    def _proposal_of(self, code: int):
        return None if code == 0 else self.proposals[code - 1]

    def _ballot_code(self, b) -> int:
        r, leader = b
        if r > MAX_ROUND:
            raise ValueError(f"ballot round {r} exceeds MAX_ROUND")
        return r * S + int(leader)

    def _ballot_of(self, code: int) -> Tuple[int, Id]:
        return (code // S, Id(code % S))

    def _accepted_code(self, acc) -> int:
        """Option<(ballot, proposal)> -> 0 or 1 + ballot*C + proposal_idx."""
        if acc is None:
            return 0
        ballot, proposal = acc
        return 1 + self._ballot_code(ballot) * self.c + self.proposals.index(
            tuple(proposal)
        )

    def _accepted_of(self, code: int):
        if code == 0:
            return None
        code -= 1
        return (
            self._ballot_of(code // self.c),
            self.proposals[code % self.c],
        )

    # --- server record (47 bits in a u64 chunk) ------------------------------

    _ACC_BITS = 9  # 1 + 15*3*3 = 136 accepted codes fit

    def _encode_server(self, s: PaxosState) -> int:
        bits = self._ballot_code(s.ballot)  # 6 bits (rounds 0..15 * 3)
        assert bits < 64
        off = 6
        bits |= self._proposal_code(s.proposal) << off
        off += 2
        prepares = dict(s.prepares)
        for sid in range(S):
            if Id(sid) in prepares:
                bits |= 1 << off
                bits |= self._accepted_code(prepares[Id(sid)]) << (off + 1)
            off += 1 + self._ACC_BITS
        for sid in range(S):
            if Id(sid) in s.accepts:
                bits |= 1 << off
            off += 1
        bits |= self._accepted_code(s.accepted) << off
        off += self._ACC_BITS
        bits |= int(s.is_decided) << off
        off += 1
        assert off <= 64, off
        return bits

    def _decode_server(self, bits: int) -> PaxosState:
        ballot = self._ballot_of(bits & 0x3F)
        off = 6
        proposal = self._proposal_of((bits >> off) & 0x3)
        off += 2
        prepares = []
        for sid in range(S):
            if (bits >> off) & 1:
                acc = self._accepted_of(
                    (bits >> (off + 1)) & ((1 << self._ACC_BITS) - 1)
                )
                prepares.append((Id(sid), acc))
            off += 1 + self._ACC_BITS
        accepts = frozenset(
            Id(sid) for sid in range(S) if (bits >> (off + sid)) & 1
        )
        off += S
        accepted = self._accepted_of((bits >> off) & ((1 << self._ACC_BITS) - 1))
        off += self._ACC_BITS
        is_decided = bool((bits >> off) & 1)
        return PaxosState(
            ballot=ballot,
            proposal=proposal,
            prepares=tuple(prepares),
            accepts=accepts,
            accepted=accepted,
            is_decided=is_decided,
        )

    # --- envelope codes ------------------------------------------------------

    def _env_code(self, env: Envelope) -> int:
        """tag(4) | src(2) upper or client idx | fields; nonzero overall
        (slot value 0 means empty, so add 1 at the end)."""
        msg = env.msg
        src, dst = int(env.src), int(env.dst)
        if isinstance(msg, Put):
            ci = src - S
            assert msg == Put(S + ci, self.values[ci]) and dst == ci % S
            code = (_T_PUT, ci, 0)
        elif isinstance(msg, Get):
            ci = src - S
            assert msg.request_id == 2 * (S + ci) and dst == (S + ci + 1) % S
            code = (_T_GET, ci, 0)
        elif isinstance(msg, PutOk):
            ci = dst - S
            assert msg.request_id == S + ci
            code = (_T_PUTOK, src * 4 + ci, 0)
        elif isinstance(msg, GetOk):
            ci = dst - S
            assert msg.request_id == 2 * (S + ci)
            code = (_T_GETOK, src * 4 + ci, self._value_code(msg.value))
        elif isinstance(msg, Internal):
            inner = msg.msg
            if isinstance(inner, Prepare):
                assert int(inner.ballot[1]) == src
                self._ballot_code(inner.ballot)  # round bounds check
                code = (_T_PREPARE, src * 4 + dst, inner.ballot[0])
            elif isinstance(inner, Prepared):
                assert int(inner.ballot[1]) == dst
                self._ballot_code(inner.ballot)
                code = (
                    _T_PREPARED,
                    src * 4 + dst,
                    inner.ballot[0] * 256 + self._accepted_code(inner.last_accepted),
                )
            elif isinstance(inner, Accept):
                assert int(inner.ballot[1]) == src
                self._ballot_code(inner.ballot)
                code = (
                    _T_ACCEPT,
                    src * 4 + dst,
                    inner.ballot[0] * 4
                    + (self._proposal_code(inner.proposal) - 1),
                )
            elif isinstance(inner, Accepted):
                assert int(inner.ballot[1]) == dst
                self._ballot_code(inner.ballot)
                code = (_T_ACCEPTED, src * 4 + dst, inner.ballot[0])
            elif isinstance(inner, Decided):
                code = (
                    _T_DECIDED,
                    src * 4 + dst,
                    (self._ballot_code(inner.ballot) * 4)
                    + (self._proposal_code(inner.proposal) - 1),
                )
            else:
                raise ValueError(f"unknown internal message {inner!r}")
        else:
            raise ValueError(f"unknown message {msg!r}")
        tag, addr, payload = code
        assert addr < 16 and payload < (1 << 14), (addr, payload)
        return 1 + ((tag << 18) | (addr << 14) | payload)

    def _env_of(self, code: int) -> Envelope:
        code -= 1
        tag = code >> 18
        addr = (code >> 14) & 0xF
        payload = code & 0x3FFF
        if tag == _T_PUT:
            ci = addr
            return Envelope(
                Id(S + ci), Id(ci % S), Put(S + ci, self.values[ci])
            )
        if tag == _T_GET:
            ci = addr
            return Envelope(Id(S + ci), Id((S + ci + 1) % S), Get(2 * (S + ci)))
        if tag == _T_PUTOK:
            src, ci = addr // 4, addr % 4
            return Envelope(Id(src), Id(S + ci), PutOk(S + ci))
        if tag == _T_GETOK:
            src, ci = addr // 4, addr % 4
            return Envelope(
                Id(src), Id(S + ci), GetOk(2 * (S + ci), self._value_of(payload))
            )
        src, dst = addr // 4, addr % 4
        if tag == _T_PREPARE:
            return Envelope(
                Id(src), Id(dst), Internal(Prepare((payload, Id(src))))
            )
        if tag == _T_PREPARED:
            return Envelope(
                Id(src),
                Id(dst),
                Internal(
                    Prepared((payload // 256, Id(dst)), self._accepted_of(payload % 256))
                ),
            )
        if tag == _T_ACCEPT:
            return Envelope(
                Id(src),
                Id(dst),
                Internal(
                    Accept(
                        (payload // 4, Id(src)),
                        self.proposals[payload % 4],
                    )
                ),
            )
        if tag == _T_ACCEPTED:
            return Envelope(
                Id(src), Id(dst), Internal(Accepted((payload, Id(dst))))
            )
        if tag == _T_DECIDED:
            return Envelope(
                Id(src),
                Id(dst),
                Internal(
                    Decided(
                        self._ballot_of(payload // 4),
                        self.proposals[payload % 4],
                    )
                ),
            )
        raise ValueError(f"bad envelope code {code}")

    # --- tester record -------------------------------------------------------

    def _lc_code(self, last_completed, me: int) -> int:
        """Snapshot tuple -> 2 bits per other client (0 absent, else idx+1)."""
        lc = dict(last_completed)
        bits = 0
        slot = 0
        for j in range(self.c):
            if j == me:
                continue
            v = lc.get(Id(S + j))
            bits |= (0 if v is None else v + 1) << (2 * slot)
            slot += 1
        return bits

    def _lc_of(self, bits: int, me: int):
        out = []
        slot = 0
        for j in range(self.c):
            if j == me:
                continue
            v = (bits >> (2 * slot)) & 0x3
            if v:
                out.append((Id(S + j), v - 1))
            slot += 1
        return tuple(sorted(out))

    def _encode_tester(self, h: LinearizabilityTester, me: int) -> int:
        tid = Id(S + me)
        hist = h.history_by_thread.get(tid)
        inflight = h.in_flight_by_thread.get(tid)
        lc_bits = 2 * (self.c - 1)
        if hist is None and inflight is None:
            return 0  # phase 0
        if inflight is not None and not hist:
            lc, op = inflight
            assert op == WriteOp(self.values[me])
            return 1 | (self._lc_code(lc, me) << 3)
        assert hist[0][1] == WriteOp(self.values[me]) and hist[0][2] == WRITE_OK
        lc_w = self._lc_code(hist[0][0], me)
        if len(hist) == 1 and inflight is None:
            return 2 | (lc_w << 3)
        if len(hist) == 1:
            lc, op = inflight
            assert op == READ
            return 3 | (lc_w << 3) | (self._lc_code(lc, me) << (3 + lc_bits))
        assert len(hist) == 2 and inflight is None and hist[1][1] == READ
        lc_r = self._lc_code(hist[1][0], me)
        vcode = self._value_code(hist[1][2].value)
        return (
            4
            | (lc_w << 3)
            | (lc_r << (3 + lc_bits))
            | (vcode << (3 + 2 * lc_bits))
        )

    def _decode_tester_into(self, h: LinearizabilityTester, bits: int, me: int):
        tid = Id(S + me)
        phase = bits & 0x7
        if phase == 0:
            return
        lc_bits = 2 * (self.c - 1)
        lc_w = self._lc_of((bits >> 3) & ((1 << lc_bits) - 1), me)
        if phase == 1:
            h.in_flight_by_thread[tid] = (lc_w, WriteOp(self.values[me]))
            h.history_by_thread[tid] = ()
            return
        entry_w = (lc_w, WriteOp(self.values[me]), WRITE_OK)
        if phase == 2:
            h.history_by_thread[tid] = (entry_w,)
            return
        lc_r = self._lc_of((bits >> (3 + lc_bits)) & ((1 << lc_bits) - 1), me)
        if phase == 3:
            h.history_by_thread[tid] = (entry_w,)
            h.in_flight_by_thread[tid] = (lc_r, READ)
            return
        vcode = (bits >> (3 + 2 * lc_bits)) & 0x3
        h.history_by_thread[tid] = (
            entry_w,
            (lc_r, READ, ReadOk(self._value_of(vcode))),
        )

    # --- full state ----------------------------------------------------------

    def encode(self, st: ActorModelState) -> np.ndarray:
        words = np.zeros(self.state_width, dtype=np.uint32)
        for i in range(S):
            bits = self._encode_server(st.actor_states[i])
            words[2 * i] = bits & 0xFFFFFFFF
            words[2 * i + 1] = bits >> 32
        cbits = 0
        for i in range(self.c):
            cs: ClientState = st.actor_states[S + i]
            if cs.awaiting is None:
                kind = 0
            elif cs.awaiting == S + i:
                kind = 1  # awaiting the put
            else:
                assert cs.awaiting == 2 * (S + i)
                kind = 2  # awaiting the get
            assert cs.op_count <= 3
            cbits |= (kind | (cs.op_count << 2)) << (4 * i)
        words[2 * S] = cbits
        env_codes = []
        for env, count in sorted(
            st.network.counts, key=lambda ec: self._env_code(ec[0])
        ):
            assert count == 1, f"multiset count {count} for {env!r}"
            env_codes.append(self._env_code(env))
        if len(env_codes) > NET_SLOTS:
            raise ValueError(
                f"{len(env_codes)} in-flight envelopes exceed {NET_SLOTS} slots"
            )
        for k, code in enumerate(env_codes):
            words[2 * S + 1 + k] = code
        for i in range(self.c):
            words[2 * S + 1 + NET_SLOTS + i] = self._encode_tester(
                st.history, i
            )
        return words

    def decode(self, words: Sequence[int]) -> ActorModelState:
        servers = tuple(
            self._decode_server(int(words[2 * i]) | (int(words[2 * i + 1]) << 32))
            for i in range(S)
        )
        cbits = int(words[2 * S])
        clients = []
        for i in range(self.c):
            nib = (cbits >> (4 * i)) & 0xF
            kind, op_count = nib & 0x3, nib >> 2
            awaiting = {0: None, 1: S + i, 2: 2 * (S + i)}[kind]
            clients.append(ClientState(awaiting=awaiting, op_count=op_count))
        envs = []
        for k in range(NET_SLOTS):
            code = int(words[2 * S + 1 + k])
            if code:
                envs.append((self._env_of(code), 1))
        network = Network(
            kind="unordered_nonduplicating", counts=frozenset(envs)
        )
        tester = LinearizabilityTester(Register(NULL_VALUE))
        for i in range(self.c):
            self._decode_tester_into(
                tester, int(words[2 * S + 1 + NET_SLOTS + i]), i
            )
        n = S + self.c
        return ActorModelState(
            actor_states=tuple(servers) + tuple(clients),
            network=network,
            timers_set=(frozenset(),) * n,
            random_choices=((),) * n,
            crashed=(False,) * n,
            history=tester,
            actor_storages=(None,) * n,
        )


def compiled_paxos(model) -> PaxosCompiled:
    return PaxosCompiled(model)
